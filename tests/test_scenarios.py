"""Scenario compiler determinism (ISSUE 18): every layer compiles to
a pure function of (spec, seed) — compose() must emit the same
byte-identical schedule on every call, the per-layer fault entries
must carry their own `#seed` suffixes, and the merge order must be
total (deletes before creates at the same instant)."""

import dataclasses

import pytest

from karpenter_tpu.scenarios import (
    BatchTrain,
    DemandSurgeBurst,
    DiurnalWave,
    ExpiryChurn,
    MixedTenancy,
    ScenarioSpec,
    SpotStorm,
    compose,
    flywheel_spec,
    smoke_spec,
)
from karpenter_tpu.scenarios.spec import Event
from karpenter_tpu.solver import faults


class TestComposeDeterminism:
    def test_same_spec_same_digest_byte_identical(self):
        a = compose(smoke_spec(seed=18))
        b = compose(smoke_spec(seed=18))
        assert a.digest() == b.digest()
        assert a.canonical_events() == b.canonical_events()
        assert a.faults_spec == b.faults_spec

    def test_different_seed_different_digest(self):
        assert (compose(smoke_spec(seed=18)).digest()
                != compose(smoke_spec(seed=19)).digest())

    def test_layer_compile_is_pure(self):
        """A layer's compile() alone is replay-identical — no global
        RNG state leaks between calls."""
        spec = smoke_spec()
        for layer in spec.layers:
            first = [e.canonical() for e in layer.compile(spec)]
            second = [e.canonical() for e in layer.compile(spec)]
            assert first == second, layer.name

    def test_flywheel_preset_composes(self):
        sched = compose(flywheel_spec(duration_s=3600.0))
        assert sched.events
        # every pod-emitting layer contributed
        assert set(sched.counts) >= {"diurnal", "batch", "surge",
                                     "tenancy", "churn"}

    def test_counts_match_events(self):
        sched = compose(smoke_spec())
        for layer, per in sched.counts.items():
            creates = sum(1 for e in sched.events
                          if e.layer == layer and e.kind == "create")
            deletes = sum(1 for e in sched.events
                          if e.layer == layer and e.kind == "delete")
            assert per.get("create", 0) == creates
            assert per.get("delete", 0) == deletes


class TestMergeOrder:
    def test_events_sorted_by_total_order(self):
        sched = compose(smoke_spec())
        keys = [e.sort_key() for e in sched.events]
        assert keys == sorted(keys)

    def test_delete_before_create_at_same_instant(self):
        """MixedTenancy rotates at fixed instants: the retiring batch
        pod's delete must land before the replacement's create so the
        rotation frees capacity first."""
        spec = ScenarioSpec(
            name="t", seed=1, duration_s=60.0,
            layers=(MixedTenancy(serving_pods=1, batch_pods=2,
                                 rotate_every_s=30.0),),
        )
        sched = compose(spec)
        at_30 = [e for e in sched.events if abs(e.t - 30.0) < 1e-9]
        assert [e.kind for e in at_30] == ["delete", "create"]

    def test_duplicate_layer_names_rejected(self):
        spec = ScenarioSpec(
            name="dup", seed=1, duration_s=10.0,
            layers=(DiurnalWave(), DiurnalWave()),
        )
        with pytest.raises(ValueError, match="duplicate layer names"):
            compose(spec)


class TestFaultComposition:
    def test_spot_storm_entry_carries_layer_seed(self):
        sched = compose(smoke_spec(seed=18))
        assert ("spot_interruption@cloud_interrupt:*=0.03#18-spot_storm"
                in sched.faults_spec.split(","))

    def test_composed_fault_spec_parses_cleanly(self):
        """Every entry a preset composes — including the `#seed`
        suffixes — must survive faults.parse() without rejection."""
        for spec in (smoke_spec(), flywheel_spec(duration_s=3600.0)):
            sched = compose(spec)
            rejected: list = []
            rules = faults.parse(sched.faults_spec, rejected=rejected)
            assert not rejected
            assert any(r.kind == "spot_interruption" for r in rules)
            assert all(r.seed is not None for r in rules
                       if r.kind == "spot_interruption")

    def test_extra_spec_faults_ride_along(self):
        spec = dataclasses.replace(
            smoke_spec(), faults=("exec_delay@crash_tick:*=2s#lag",),
        )
        sched = compose(spec)
        entries = sched.faults_spec.split(",")
        assert "exec_delay@crash_tick:*=2s#lag" in entries
        rejected = []
        faults.parse(sched.faults_spec, rejected=rejected)
        assert not rejected

    def test_stacked_storms_do_not_alias(self):
        """Two storms in one spec carry distinct per-layer seeds."""
        spec = ScenarioSpec(
            name="storms", seed=7, duration_s=30.0,
            layers=(SpotStorm(name="storm_a", rate=0.05),
                    SpotStorm(name="storm_b", rate=0.05)),
        )
        entries = compose(spec).faults_spec.split(",")
        assert entries[0].endswith("#7-storm_a")
        assert entries[1].endswith("#7-storm_b")


class TestLayerShapes:
    def test_diurnal_wave_retires_newest_first(self):
        spec = ScenarioSpec(
            name="w", seed=3, duration_s=120.0,
            layers=(DiurnalWave(base_pods=4, amplitude=1.0,
                                period_s=80.0, sample_s=10.0,
                                cpu=0.5),),
        )
        sched = compose(spec)
        deletes = [e for e in sched.events if e.kind == "delete"]
        assert deletes
        creates_before = {}
        for e in sched.events:
            if e.kind == "create":
                creates_before[e.pod] = e.t
        # every deleted pod was created strictly earlier
        assert all(creates_before[e.pod] < e.t for e in deletes)

    def test_batch_train_gang_arrives_and_completes_together(self):
        spec = ScenarioSpec(
            name="b", seed=1, duration_s=300.0,
            layers=(BatchTrain(jobs=2, pods_per_job=3, every_s=120.0,
                               duration_s=60.0, start_s=10.0),),
        )
        sched = compose(spec)
        job0 = [e for e in sched.events if e.pod.startswith("batch-0-")]
        assert {e.t for e in job0 if e.kind == "create"} == {10.0}
        assert {e.t for e in job0 if e.kind == "delete"} == {70.0}

    def test_batch_job_past_horizon_runs_to_trace_end(self):
        spec = ScenarioSpec(
            name="b", seed=1, duration_s=40.0,
            layers=(BatchTrain(jobs=1, pods_per_job=2, every_s=120.0,
                               duration_s=60.0, start_s=10.0),),
        )
        sched = compose(spec)
        assert not [e for e in sched.events if e.kind == "delete"]

    def test_surge_past_horizon_emits_nothing(self):
        spec = ScenarioSpec(
            name="s", seed=1, duration_s=30.0,
            layers=(DemandSurgeBurst(at_s=60.0, pods=5),),
        )
        assert not compose(spec).events

    def test_expiry_churn_death_births_successor(self):
        spec = ScenarioSpec(
            name="c", seed=5, duration_s=400.0,
            layers=(ExpiryChurn(pods=2, lifetime_s=90.0),),
        )
        sched = compose(spec)
        slot0 = [e for e in sched.events if e.pod.startswith("churn-0-")]
        by_gen = {}
        for e in slot0:
            gen = int(e.pod.rsplit("-", 1)[1])
            by_gen.setdefault(gen, {})[e.kind] = e.t
        for gen in range(max(by_gen) if by_gen else 0):
            assert by_gen[gen]["delete"] == by_gen[gen + 1]["create"]

    def test_canonical_delete_omits_shape_fields(self):
        ev = Event(1.0, "l", "delete", "p")
        assert set(ev.canonical()) == {"t", "layer", "kind", "pod"}
        ev = Event(1.0, "l", "create", "p", 0.5, 1.0, 100)
        assert ev.canonical()["cpu"] == 0.5
