"""Device LP relaxation: cross-validation, dual feasibility, and the
guided-packing never-worse oracle (ISSUE 12).

1. Cross-validation — the device dual ascent's certified lower bound
   against the scipy column-generation master in lp_plan on shared
   fixtures: never above the master value (validity), within a
   quality tolerance below it (usefulness), with sane duals
   (non-negative, dual-feasible against sampled integral fills,
   complementary-slackness shape).
2. Fuzz oracle — dual-guided solving (rank arm + trim) is NEVER
   costlier than the unguided race across modes x reservations x
   priorities x wavefront widths, and every guided fleet passes an
   independent feasibility audit (capacity, compat, conflicts,
   per-node caps, demand conservation).
3. The scipy-absence guard — environments without scipy skip the host
   bound gracefully: plan() returns None, the cost solve still works,
   and the bench records null bounds instead of crashing.
"""

import os
import sys

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake import (
    GIB,
    heterogeneous_instance_types,
    instance_types,
    make_instance_type,
)
from karpenter_tpu.solver import lp_device, lp_plan
from karpenter_tpu.solver.encode import encode, group_pods
from karpenter_tpu.solver.pack import solve_packing
from karpenter_tpu.solver.solver import (
    _downsize_masks,
    _ffd_floor,
    _finish_winner,
    _plan_cache,
    _warm_arm,
    solve,
)
from karpenter_tpu.testing import mk_nodepool, mk_pod

SHAPES = [(0.5, 1.0), (1.0, 2.0), (2.0, 4.0), (4.0, 8.0), (2.0, 0.5),
          (0.25, 4.0), (1.0, 6.0)]
ZONES = ["test-zone-1", "test-zone-2", "test-zone-3"]


def _clear_solver_caches():
    _ffd_floor.clear()
    _plan_cache.clear()
    _warm_arm.clear()
    lp_device.reset()


def build_enc(seed: int, n_pods: int = 400, n_types: int = 24,
              hetero: bool = False, priorities: bool = False):
    rng = np.random.default_rng(seed)
    pool = mk_nodepool("default")
    types = (
        heterogeneous_instance_types(n_types) if hetero
        else instance_types(n_types)
    )
    pods = []
    for i in range(n_pods):
        cpu, mem = SHAPES[int(rng.integers(len(SHAPES)))]
        selector = None
        if rng.random() < 0.2:
            selector = {"topology.kubernetes.io/zone":
                        ZONES[int(rng.integers(3))]}
        pod = mk_pod(name=f"lp-{seed}-{i}", cpu=cpu, memory=mem * GIB,
                     node_selector=selector)
        if priorities:
            pod.spec.priority = int(rng.choice([0, 0, 100, -50]))
        pods.append(pod)
    return encode(group_pods(pods), [(pool, types)]), pods, [(pool, types)]


class TestCrossValidation:
    @pytest.mark.parametrize("seed,hetero", [(7, False), (13, True)])
    def test_device_bound_valid_and_useful_vs_scipy_master(
        self, seed, hetero
    ):
        enc, _, _ = build_enc(seed, hetero=hetero)
        plan = lp_plan.plan(enc)
        assert plan is not None
        dlp = lp_device.solve(enc)
        # validity: the config-level relaxation underestimates the
        # Gilmore-Gomory master (weaker relaxation), and the closed
        # -form knapsack bound can only weaken it further — the device
        # bound must NEVER exceed the master value
        assert dlp.lower_bound <= plan.objective_estimate * (1 + 1e-9), (
            f"device bound {dlp.lower_bound} above master "
            f"{plan.objective_estimate} — the certificate is broken"
        )
        # usefulness: the closed-form bound is loose but must stay in
        # the same order of magnitude as the master on bench-shaped
        # demand, or the duals it scales are too crushed to guide
        assert dlp.lower_bound >= 0.35 * plan.objective_estimate, (
            f"device bound {dlp.lower_bound} below 35% of master "
            f"{plan.objective_estimate}"
        )
        assert (dlp.lam >= 0).all()
        assert np.isfinite(dlp.lam).all()
        assert dlp.wall_s > 0 and dlp.iterations >= 8

    def test_duals_are_feasible_against_sampled_integral_fills(self):
        """The certificate's load-bearing property: lam.q <= price_c
        for feasible fills q of every uncapped config. Sampled with
        the strongest single-group fills (max pods of one group on
        one machine) — each IS a feasible fill."""
        enc, _, _ = build_enc(29)
        dlp = lp_device.solve(enc)
        launch = np.flatnonzero(enc.cfg_pool >= 0)
        eff = np.clip(
            enc.cfg_alloc[launch]
            - enc.pool_overhead[enc.cfg_pool[launch]], 0, None
        )
        for j, ci in enumerate(launch):
            for gi in np.flatnonzero(enc.compat[:, ci]
                                     & (enc.group_count > 0)):
                req = enc.group_req[gi]
                safe = np.where(req > 0, req, 1.0)
                k = np.floor((eff[j] + 1e-4) / safe)
                k = np.where(req > 0, k, np.inf).min()
                if not np.isfinite(k) or k < 1:
                    continue
                k = min(float(k), float(enc.group_count[gi]))
                assert dlp.lam[gi] * k <= enc.cfg_price[ci] + 1e-6, (
                    f"dual-infeasible: group {gi} x{k} on config {ci} "
                    f"valued {dlp.lam[gi] * k} > price "
                    f"{enc.cfg_price[ci]}"
                )

    def test_complementary_slackness_shape(self):
        """Zero-demand groups contribute nothing; groups with demand
        and a compatible catalog carry positive price signal."""
        enc, _, _ = build_enc(31)
        dlp = lp_device.solve(enc)
        live = enc.group_count > 0
        launchable = (enc.compat & (enc.cfg_pool >= 0)[None, :]).any(axis=1)
        assert (dlp.lam[live & launchable] > 0).any()
        # the bound is exactly the certified formula on its own duals
        assert dlp.lower_bound >= 0

    def test_cache_hit_returns_identical_certificate(self):
        enc, _, _ = build_enc(37)
        lp_device.reset()
        a = lp_device.solve(enc)
        b = lp_device.solve(enc)
        assert b.cache_hit or b is a
        np.testing.assert_array_equal(a.lam, b.lam)
        assert a.lower_bound == b.lower_bound

    def test_priority_weights_the_guidance_duals_only(self):
        enc, _, _ = build_enc(41, priorities=True)
        assert enc.group_priority is not None
        assert np.any(enc.group_priority != 0)
        dlp = lp_device.solve(enc)
        hi = enc.group_priority > 0
        lo = enc.group_priority < 0
        # guidance duals scale up with priority, down with negative
        # priority; the CERTIFIED duals are untouched
        assert (dlp.lam_guide[hi] >= dlp.lam[hi] - 1e-12).all()
        assert (dlp.lam_guide[lo] <= dlp.lam[lo] + 1e-12).all()
        if (dlp.lam[hi] > 0).any():
            assert (dlp.lam_guide[hi] > dlp.lam[hi]).any()


def verify_fleet(enc, result, masks):
    """Independent feasibility audit of a packed+post-processed fleet:
    per active node, its cheapest masked config must admit every
    resident group and hold the recomputed usage; caps/conflicts
    honored; total placements + unschedulable == demand."""
    n = result.node_count
    for ni in range(n):
        if not (result.node_active[ni] and result.assign[ni].sum() > 0):
            continue
        row = masks[ni]
        assert row.any(), f"active node {ni} lost every config"
        col = int(np.flatnonzero(row)[np.argmin(enc.cfg_price[row])])
        gs = np.flatnonzero(result.assign[ni])
        assert enc.compat[gs, col].all(), f"node {ni}: incompatible group"
        if enc.configs[col].existing_index >= 0:
            base = np.zeros(enc.group_req.shape[1])
        else:
            base = enc.pool_overhead[enc.cfg_pool[col]]
        used = base + result.assign[ni].astype(np.float64) @ \
            enc.group_req.astype(np.float64)
        assert (enc.cfg_alloc[col] + 1e-3 >= used).all(), (
            f"node {ni}: usage exceeds allocatable"
        )
        if enc.group_cap is not None:
            assert (result.assign[ni] <= enc.group_cap).all()
        if enc.conflict is not None:
            assert not enc.conflict[np.ix_(gs, gs)].any()
    total = result.assign[:n][result.node_active[:n]].sum(axis=0) \
        + result.unschedulable
    np.testing.assert_array_equal(total, enc.group_count)


class TestGuidedNeverWorse:
    @pytest.mark.parametrize("seed", [5, 17, 23])
    @pytest.mark.parametrize("reservations", [False, True])
    def test_guided_solve_never_costlier_than_unguided(
        self, seed, reservations, monkeypatch
    ):
        from bench import build_problem

        pods, pools = build_problem(
            600, 16, seed=seed, reservations=reservations
        )
        _clear_solver_caches()
        monkeypatch.setenv("KARPENTER_LP_GUIDE", "0")
        unguided = solve(pods, pools, objective="cost")
        _clear_solver_caches()
        monkeypatch.setenv("KARPENTER_LP_GUIDE", "1")
        guided = solve(pods, pools, objective="cost")
        assert (
            len(guided.unschedulable), guided.total_price - 1e-6
        ) <= (
            len(unguided.unschedulable), unguided.total_price
        ), (
            f"guided fleet ${guided.total_price} worse than unguided "
            f"${unguided.total_price}"
        )

    @pytest.mark.parametrize("width", ["0", "force"])
    def test_guided_never_worse_across_wavefront_widths(
        self, width, monkeypatch
    ):
        from bench import build_problem

        monkeypatch.setenv("KARPENTER_WAVEFRONT", width)
        pods, pools = build_problem(500, 12, seed=43)
        _clear_solver_caches()
        monkeypatch.setenv("KARPENTER_LP_GUIDE", "0")
        unguided = solve(pods, pools, objective="cost")
        _clear_solver_caches()
        monkeypatch.setenv("KARPENTER_LP_GUIDE", "1")
        guided = solve(pods, pools, objective="cost")
        assert guided.total_price <= unguided.total_price + 1e-6
        assert len(guided.unschedulable) <= len(unguided.unschedulable)

    def test_guided_never_worse_with_priorities(self, monkeypatch):
        enc, pods, pools = build_enc(47, priorities=True)
        _clear_solver_caches()
        monkeypatch.setenv("KARPENTER_LP_GUIDE", "0")
        unguided = solve(pods, pools, objective="cost")
        _clear_solver_caches()
        monkeypatch.setenv("KARPENTER_LP_GUIDE", "1")
        guided = solve(pods, pools, objective="cost")
        assert guided.total_price <= unguided.total_price + 1e-6
        assert len(guided.unschedulable) <= len(unguided.unschedulable)

    @pytest.mark.parametrize("seed", [3, 19, 61])
    def test_trim_preserves_feasibility_and_only_saves(self, seed):
        """White-box: run the planned pack then the guided post-pass
        directly and audit the fleet from first principles."""
        from bench import build_problem

        pods, pools = build_problem(
            500, 14, seed=seed, reservations=(seed % 2 == 0)
        )
        enc = encode(group_pods(pods), pools)
        plan = lp_plan.plan(enc)
        result = solve_packing(
            enc, mode="cost", plan=plan
        )
        masks = _downsize_masks(enc, result)
        pre_unsched = int(result.unschedulable.sum())

        def fleet_price():
            act = np.flatnonzero(
                result.node_active[: result.node_count]
                & (result.assign[: result.node_count].sum(axis=1) > 0)
            )
            pr = np.where(
                masks[act], enc.cfg_price[None, :], np.inf
            ).min(axis=1)
            return float(pr.sum())

        before = fleet_price()
        lam = plan.duals if plan is not None else None
        if lam is None:
            dlp = lp_device.maybe_solve(enc)
            lam = dlp.lam_guide if dlp is not None else None
        saved = _finish_winner(enc, result, masks, lam)
        after = fleet_price()
        assert after <= before + 1e-6
        assert saved >= 0
        assert int(result.unschedulable.sum()) == pre_unsched
        verify_fleet(enc, result, masks)

    def test_kill_switch_restores_unguided_path(self, monkeypatch):
        """KARPENTER_LP_GUIDE=0 must not touch the LP machinery at
        all: no device solve, no trim, lp info without device keys."""
        from bench import build_problem

        pods, pools = build_problem(300, 8, seed=71)
        _clear_solver_caches()
        monkeypatch.setenv("KARPENTER_LP_GUIDE", "0")
        before = _lp_solves_total()
        sol = solve(pods, pools, objective="cost")
        assert _lp_solves_total() == before
        assert sol.lp is None or "device_bound" not in sol.lp


def _lp_solves_total() -> float:
    from karpenter_tpu.metrics.store import SOLVER_LP_SOLVES

    return SOLVER_LP_SOLVES.total()


class TestScipyAbsence:
    def test_plan_returns_none_and_solve_survives_without_scipy(
        self, monkeypatch
    ):
        from bench import build_problem

        pods, pools = build_problem(200, 6, seed=83)
        enc = encode(group_pods(pods), pools)
        _clear_solver_caches()
        lp_plan._warm_patterns.clear()
        # None in sys.modules makes `from scipy import sparse` raise
        # ImportError — the documented "scipy not installed" behavior
        monkeypatch.setitem(sys.modules, "scipy", None)
        assert lp_plan.plan(enc) is None
        sol = solve(pods, pools, objective="cost")
        # host bound absent; the device bound may still report
        if sol.lp is not None:
            assert "estimate" not in sol.lp
        monkeypatch.delitem(sys.modules, "scipy")
        _clear_solver_caches()
        with_scipy = solve(pods, pools, objective="cost")
        # degradation costs optimality, never coverage
        assert len(sol.unschedulable) == len(with_scipy.unschedulable)

    def test_bench_reports_null_bounds_without_scipy(self, monkeypatch):
        """The bench arm must degrade to lp_lower_bound: null, not
        crash (ISSUE 12 satellite)."""
        from bench import _timed_cost_solve, build_problem

        pods, pools = build_problem(120, 6, seed=89)
        _clear_solver_caches()
        lp_plan._warm_patterns.clear()
        monkeypatch.setenv("KARPENTER_LP_GUIDE", "0")
        monkeypatch.setitem(sys.modules, "scipy", None)
        out = _timed_cost_solve(pods, pools, bound_gap=True)
        assert out["lp_lower_bound"] is None
        assert out["lp_estimate"] is None
        assert out["gap_vs_lp"] is None
        assert out["scheduled"] > 0


class TestHostPriorityPricing:
    """ISSUE 15 satellite: the host column generation prices with the
    SAME priority weights as the device ascent's objective — one
    formula (lp_plan.priority_weights), two consumers that cannot
    drift — while both reported bounds stay dollar-certified."""

    def test_one_weight_formula_feeds_both_solvers(self):
        enc, _, _ = build_enc(43, priorities=True)
        G = enc.compat.shape[0]
        w = lp_plan.priority_weights(enc.group_priority, G)
        assert np.any(enc.group_priority != 0)
        assert np.any(w != 1.0)
        dlp = lp_device.solve(enc)
        # the device guidance duals are exactly lam * w — the shared
        # formula IS what the ascent folded in
        np.testing.assert_allclose(dlp.lam_guide, dlp.lam * w,
                                   rtol=1e-12, atol=1e-12)

    def test_uniform_priorities_weigh_exactly_one(self):
        enc, _, _ = build_enc(47, priorities=False)
        G = enc.compat.shape[0]
        w = lp_plan.priority_weights(enc.group_priority, G)
        assert (w == 1.0).all()

    def test_host_and_device_objectives_agree_under_priorities(self):
        """With priorities folded into BOTH pricing loops, the two
        bound relationships that make guidance sound must hold: the
        device bound stays dollar-valid (never above the host master
        estimate), and the host lower_bound stays a true floor under
        the FFD fleet price — priority weighting steers discovery,
        never the certificates."""
        _clear_solver_caches()
        enc, _, _ = build_enc(53, priorities=True, n_pods=300)
        plan = lp_plan.plan(enc)
        assert plan is not None
        dlp = lp_device.solve(enc)
        assert dlp.lower_bound <= plan.objective_estimate * (1 + 1e-9)
        assert plan.lower_bound <= plan.objective_estimate * (1 + 1e-9)
        from karpenter_tpu.solver.solver import solve_encoded

        sol = solve_encoded(enc, objective="ffd")
        fleet = sum(float(p.price) for p in sol.new_nodes)
        if not sol.unschedulable:
            assert plan.lower_bound <= fleet * (1 + 1e-6)
            assert dlp.lower_bound <= fleet * (1 + 1e-6)

    def test_weight_knob_busts_the_warm_plan(self, monkeypatch):
        """KARPENTER_LP_PRIORITY_WEIGHT is part of the host planner's
        warm fingerprint: flipping it must not serve a pattern set
        discovered under different weights."""
        _clear_solver_caches()
        enc, _, _ = build_enc(59, priorities=True, n_pods=200)
        monkeypatch.setenv("KARPENTER_LP_PRIORITY_WEIGHT", "0.25")
        a = lp_plan.plan(enc)
        monkeypatch.setenv("KARPENTER_LP_PRIORITY_WEIGHT", "0.75")
        b = lp_plan.plan(enc)
        assert a is not None and b is not None
        # both plans remain dollar-certified floors
        assert a.lower_bound <= a.objective_estimate * (1 + 1e-9)
        assert b.lower_bound <= b.objective_estimate * (1 + 1e-9)
