"""Int32 width audit at million-pod shapes (ISSUE 11 satellite).

The packing kernels keep all counts in int32 (assign, group_count,
unschedulable, the flat uint32 transport). Three spots could overflow
once node axes and demands reach million-pod scale, and each now has a
guarded construction pinned here:

1. the per-group prefix fill — a plain int32 cumsum of per-node
   capacities (each clipped at CAP_MAX ~ 2e9) wraps as soon as two
   unbounded rows stack; `_prefix_take` clamps capacities at the
   group's remaining demand and saturates the running sum via a uint32
   associative scan (exact, and bit-identical to the naive prefix
   wherever int32 didn't overflow);
2. capacity casts — capacities are clipped to CAP_MAX (int32-exact)
   BEFORE the f32 -> int32 cast; casting the f32 BIG sentinel is
   implementation-defined in XLA;
3. the bulk-open ceil division — (remaining + m_star - 1) overflows
   when both near 2^31; the kernels use (remaining - 1) // m_star + 1,
   exact for the remaining >= 1 the loop guarantees.

Host-side, _run_pack rejects demands whose total exceeds int32 before
any array is staged.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from karpenter_tpu.solver.pack import CAP_MAX, _prefix_take, pack_split


def naive_take(k, remaining):
    """The definitionally-correct int64 prefix fill."""
    k64 = np.asarray(k, np.int64)
    prefix = np.cumsum(k64) - k64
    return np.clip(remaining - prefix, 0, k64).astype(np.int64)


class TestPrefixTake:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_naive_on_ordinary_capacities(self, seed):
        rng = np.random.default_rng(seed)
        k = rng.integers(0, 500, size=200).astype(np.int32)
        for remaining in (0, 1, 37, 1_000, 1_000_000):
            got = np.asarray(_prefix_take(jnp.asarray(k), jnp.int32(remaining)))
            np.testing.assert_array_equal(got, naive_take(k, remaining))

    def test_unbounded_rows_would_wrap_int32(self):
        """Four CAP_MAX rows: the raw int32 cumsum wraps at row 2 (sum
        4e9 > 2^31) — the construction this module exists to prevent —
        while the saturating scan still yields the exact fill."""
        k = np.full(4, int(CAP_MAX), np.int32)
        wrapped = np.cumsum(k, dtype=np.int32)  # the kernels' old width
        assert (wrapped < 0).any(), "precondition: naive cumsum wraps"
        got = np.asarray(_prefix_take(jnp.asarray(k), jnp.int32(5)))
        np.testing.assert_array_equal(got, [5, 0, 0, 0])

    def test_million_pod_boundary_shapes(self):
        """Node axes and demands at the million_pod bench's scale:
        35k nodes x capacities that sum far past int32."""
        rng = np.random.default_rng(7)
        k = rng.integers(0, 200_000, size=35_000).astype(np.int32)
        k[::97] = int(CAP_MAX)  # sprinkle unbounded rows
        for remaining in (1_000_000, 2**31 - 1):
            got = np.asarray(
                _prefix_take(jnp.asarray(k), jnp.int32(remaining))
            )
            np.testing.assert_array_equal(got, naive_take(k, remaining))

    def test_negative_remaining_takes_nothing(self):
        """The replaced clip(remaining - prefix, 0, k) floored negative
        demand at zero takes; the saturating scan must too (an
        unclamped min(k, remaining) wrapped -5 through the uint32 cast
        into ~4.29e9-sized takes)."""
        k = np.array([3, 10, 2], np.int32)
        got = np.asarray(_prefix_take(jnp.asarray(k), jnp.int32(-5)))
        np.testing.assert_array_equal(got, [0, 0, 0])

    def test_saturation_never_inflates_total(self):
        rng = np.random.default_rng(11)
        for _ in range(20):
            n = int(rng.integers(1, 64))
            k = rng.integers(0, int(CAP_MAX), size=n).astype(np.int32)
            remaining = int(rng.integers(0, 2**31 - 1))
            got = np.asarray(
                _prefix_take(jnp.asarray(k), jnp.int32(remaining))
            )
            assert got.astype(np.int64).sum() <= remaining
            np.testing.assert_array_equal(got, naive_take(k, remaining))


class TestKernelOverflowRegression:
    def _zero_req_problem(self, B=4, remaining=7):
        """A group requesting NOTHING (every resource dimension zero)
        against B bound rows: each row's capacity is CAP_MAX, so the
        pre-audit int32 cumsum wrapped at row 2 and the vectorized
        take fabricated ~3e8 placements on row 2."""
        G, C, R, F = 1, 32, 2, 16
        compat = np.ones((G, C), bool)
        group_req = np.zeros((G, R), np.float32)
        group_count = np.array([remaining], np.int32)
        cfg_alloc = np.full((C, R), 8.0, np.float32)
        cfg_pool = np.full((C,), -1, np.int32)  # no fresh opens
        pool_overhead = np.zeros((1, R), np.float32)
        bound_compat = np.ones((G, B), bool)
        bound_alloc = np.full((B, R), 8.0, np.float32)
        bound_used0 = np.zeros((B, R), np.float32)
        bound_slot = np.zeros((B,), np.int32)
        bound_live = np.ones((B,), bool)
        cfg_price = np.ones((C,), np.float32)
        return (
            jnp.asarray(compat), jnp.asarray(group_req),
            jnp.asarray(group_count), jnp.asarray(cfg_alloc),
            jnp.asarray(cfg_pool), jnp.asarray(pool_overhead),
            jnp.asarray(bound_compat), jnp.asarray(bound_alloc),
            jnp.asarray(bound_used0), jnp.asarray(bound_slot),
            jnp.asarray(bound_live), jnp.asarray(cfg_price),
        ), F

    def test_zero_request_group_fills_first_row_only(self):
        args, F = self._zero_req_problem()
        assign, _, node_count, unsched = [
            np.asarray(x)
            for x in pack_split(*args, max_free=F, mode="ffd")
        ]
        # first-fit: all 7 pods on bound row 0, none fabricated
        assert assign[0, 0] == 7
        assert assign[1:, 0].sum() == 0
        assert int(unsched.sum()) == 0

    def test_run_pack_rejects_demand_past_int32(self):
        from bench import build_problem
        from karpenter_tpu.solver.encode import encode, group_pods
        from karpenter_tpu.solver.pack import solve_packing

        pods, pools = build_problem(64, 8, seed=1)
        enc = encode(group_pods(pods), pools)
        enc.group_count = enc.group_count.astype(np.int64)
        enc.group_count[0] = 2**31
        with pytest.raises(ValueError, match="int32"):
            solve_packing(enc, mode="ffd")
