"""Static snapshot-seam check (ISSUE-15 satellite, pattern of
test_solve_entry_sites): disruption candidate snapshots must come
through the shared retained-inputs seam (`state/retained.py`'s
RetainedFleetSeam) — no disruption controller may rebuild fleet state
from the store directly. A controller calling
`cluster.deep_copy_nodes()` (or hand-copying StateNodes) would
silently bypass the seam's dirty-tracking, its mutation discipline
(note_mutated), AND its decision-identity oracle; this tier-1 test
makes that a failing build instead of an unaudited O(fleet) scan.
"""

import ast
import pathlib

PKG = pathlib.Path(__file__).resolve().parent.parent / "karpenter_tpu"

# controllers that consume fleet snapshots for DISRUPTION decisions:
# every snapshot they take must come from the retained seam
GUARDED_DIRS = ("disruption",)

# the seam itself (and the cluster mirror that owns the copy
# primitive) are the only modules allowed to touch the raw copy path
SNAPSHOT_NAMES = {"deep_copy_nodes", "shallow_copy"}


def _guarded_files():
    for dirname in GUARDED_DIRS:
        for path in sorted((PKG / dirname).rglob("*.py")):
            yield path


def _snapshot_calls(tree):
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in SNAPSHOT_NAMES:
            out.append((node.lineno, func.attr))
    return out


def test_disruption_controllers_route_through_the_retained_seam():
    offenders = []
    for path in _guarded_files():
        tree = ast.parse(path.read_text(), filename=str(path))
        for lineno, name in _snapshot_calls(tree):
            offenders.append(
                f"{path.relative_to(PKG.parent)}:{lineno} calls {name}"
            )
    assert not offenders, (
        "disruption controllers rebuilding fleet state from the store "
        "instead of the retained seam (state/retained.py): "
        f"{offenders}"
    )


def test_engine_snapshot_sites_use_the_seam():
    """The two snapshot consumers — the sequential simulation and the
    batched probe solver setup — are pinned to fleet_seam calls, and
    the sequential path reports its mutations back (note_mutated)."""
    source = (PKG / "disruption" / "engine.py").read_text()
    tree = ast.parse(source, filename="disruption/engine.py")
    seam_calls = []
    mutation_notes = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if (
            func.attr == "fleet_snapshot"
            and isinstance(func.value, ast.Attribute)
            and func.value.attr == "fleet_seam"
        ):
            seam_calls.append(node.lineno)
        if (
            func.attr == "note_mutated"
            and isinstance(func.value, ast.Attribute)
            and func.value.attr == "fleet_seam"
        ):
            mutation_notes.append(node.lineno)
    assert len(seam_calls) >= 2, (
        "simulate_scheduling and _build_probe_solver must both take "
        f"their snapshots from the seam (found {seam_calls})"
    )
    assert mutation_notes, (
        "the sequential simulation mutates served rows and must report "
        "them back through fleet_seam.note_mutated"
    )


def test_seam_owns_the_only_retained_copy_path():
    """Outside state/ (the seam + the mirror that owns shallow_copy),
    provisioning's full path is the one legitimate deep_copy_nodes
    caller left (the provisioner snapshots for the full Scheduler,
    whose per-round mutation model predates the seam)."""
    allowed = {
        ("state", "retained.py"),
        ("state", "cluster.py"),
        ("provisioning", "provisioner.py"),
    }
    offenders = []
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(PKG)
        key = (rel.parts[0], rel.name) if len(rel.parts) > 1 else ("", rel.name)
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "deep_copy_nodes"
            ):
                if key not in allowed:
                    offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        f"unexpected deep_copy_nodes call sites: {offenders} — route "
        "through state/retained.RetainedFleetSeam"
    )
