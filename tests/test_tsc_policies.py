"""TopologySpreadConstraint node-inclusion policies.

nodeAffinityPolicy / nodeTaintsPolicy semantics
(topologynodefilter.go:38-95; topology_test.go policy families):
which domains participate in the SKEW ACCOUNTING —

- affinity Honor (default): only domains the pod's own selector /
  required affinity can reach; Ignore: every domain, so an
  unreachable empty domain pins the global minimum at 0.
- taints Ignore (default): every domain; Honor: only domains
  reachable through taints the pod tolerates.
"""

from karpenter_tpu.apis.v1.labels import TOPOLOGY_ZONE_LABEL
from karpenter_tpu.cloudprovider.fake import make_instance_type
from karpenter_tpu.kube.objects import (
    LabelSelector,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod

ZONE = TOPOLOGY_ZONE_LABEL


def spread_pod(name, *, affinity_policy="Honor", taints_policy="Ignore",
               zones=None, tolerations=None):
    pod = mk_pod(name=name, cpu=0.25)
    pod.metadata.labels["app"] = "svc"
    pod.spec.topology_spread_constraints = [
        TopologySpreadConstraint(
            max_skew=1,
            topology_key=ZONE,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector.of({"app": "svc"}),
            node_affinity_policy=affinity_policy,
            node_taints_policy=taints_policy,
        )
    ]
    if zones:
        if isinstance(zones, str):
            pod.spec.node_selector[ZONE] = zones
        else:
            from karpenter_tpu.kube.objects import (
                Affinity,
                NodeAffinity,
                NodeSelectorRequirement,
                NodeSelectorTerm,
            )

            pod.spec.affinity = Affinity(
                node_affinity=NodeAffinity(
                    required=(
                        NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    key=ZONE, operator="In",
                                    values=tuple(zones),
                                ),
                            )
                        ),
                    )
                )
            )
    if tolerations:
        pod.spec.tolerations = list(tolerations)
    return pod


def three_zone_env():
    env = Environment(
        types=[make_instance_type("c8", cpu=8,
                                  zones=("test-zone-1", "test-zone-2",
                                         "test-zone-3"))]
    )
    env.kube.create(mk_nodepool("default"))
    return env


class TestNodeAffinityPolicy:
    def test_honor_skew_over_reachable_zones_only(self):
        # default Honor: pods restricted to 2 of 3 zones can stack 2
        # per reachable zone (the unreachable third zone is not part
        # of the minimum)
        env = three_zone_env()
        pods = [
            spread_pod(f"p{i}", zones=["test-zone-1", "test-zone-2"])
            for i in range(4)
        ]
        results = env.provision(*pods)
        assert results.scheduled_count == 4
        assert not results.errors

    def test_ignore_counts_unreachable_zone(self):
        # Ignore: the empty unreachable zone-3 pins the global minimum
        # at 0, so only maxSkew(1) pods per reachable zone may land —
        # the 3rd and 4th pods are unschedulable
        env = three_zone_env()
        pods = [
            spread_pod(
                f"p{i}", affinity_policy="Ignore",
                zones=["test-zone-1", "test-zone-2"],
            )
            for i in range(4)
        ]
        results = env.provision(*pods)
        assert results.scheduled_count == 2
        assert len(results.errors) == 2


class TestNodeTaintsPolicy:
    def _tainted_zone3_env(self):
        # zone-3 reachable only through a tainted pool
        env = Environment(
            types=[make_instance_type(
                "c8", cpu=8, zones=("test-zone-1", "test-zone-2"))]
        )
        env.kube.create(mk_nodepool("default"))
        tainted = mk_nodepool("batch-only")
        tainted.spec.template.spec.taints = [
            Taint(key="dedicated", value="batch", effect="NoSchedule")
        ]
        env.kube.create(tainted)
        env.cloud.types_by_pool = None  # same catalog for both pools
        return env

    def test_honor_excludes_intolerable_zone(self):
        # with taints=Honor, zone-3 (tainted-pool-only) neither blocks
        # the skew minimum nor accepts placement: 3 intolerant pods
        # spread 2+1 over zones 1-2... maxSkew 1 allows exactly that
        env = Environment(
            types=[
                make_instance_type(
                    "c8", cpu=8, zones=("test-zone-1", "test-zone-2")),
                make_instance_type(
                    "z3", cpu=8, zones=("test-zone-3",)),
            ]
        )
        open_pool = mk_nodepool("default")
        # zone-3 only via the tainted pool
        from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec

        open_pool.spec.template.spec.requirements = [
            RequirementSpec(key=ZONE, operator="In",
                            values=["test-zone-1", "test-zone-2"])
        ]
        env.kube.create(open_pool)
        tainted = mk_nodepool("z3-pool")
        tainted.spec.template.spec.taints = [
            Taint(key="dedicated", value="batch", effect="NoSchedule")
        ]
        tainted.spec.template.spec.requirements = [
            RequirementSpec(key=ZONE, operator="In", values=["test-zone-3"])
        ]
        env.kube.create(tainted)

        pods = [
            spread_pod(f"p{i}", taints_policy="Honor") for i in range(3)
        ]
        results = env.provision(*pods)
        # zone-3 is excluded from the accounting: 3 pods over 2 zones
        # at maxSkew 1 (2+1) all schedule
        assert results.scheduled_count == 3
        assert not results.errors

    def test_default_ignore_counts_tainted_zone(self):
        # same cluster, default taints=Ignore: empty zone-3 counts in
        # the minimum, so the 3rd pod (which cannot tolerate its way
        # in) is unschedulable
        env = Environment(
            types=[
                make_instance_type(
                    "c8", cpu=8, zones=("test-zone-1", "test-zone-2")),
                make_instance_type(
                    "z3", cpu=8, zones=("test-zone-3",)),
            ]
        )
        from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec

        open_pool = mk_nodepool("default")
        open_pool.spec.template.spec.requirements = [
            RequirementSpec(key=ZONE, operator="In",
                            values=["test-zone-1", "test-zone-2"])
        ]
        env.kube.create(open_pool)
        tainted = mk_nodepool("z3-pool")
        tainted.spec.template.spec.taints = [
            Taint(key="dedicated", value="batch", effect="NoSchedule")
        ]
        tainted.spec.template.spec.requirements = [
            RequirementSpec(key=ZONE, operator="In", values=["test-zone-3"])
        ]
        env.kube.create(tainted)

        pods = [spread_pod(f"p{i}") for i in range(3)]
        results = env.provision(*pods)
        # pods can't land in zone-3 (taint) but it still counts: only
        # 2 schedule (1 per open zone at skew 1 vs empty zone-3)
        assert results.scheduled_count == 2
        assert len(results.errors) == 1

    def test_tolerating_pods_use_the_tainted_zone(self):
        # a pod tolerating the taint treats zone-3 as reachable under
        # Honor and can spread into it
        from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec

        env = Environment(
            types=[
                make_instance_type(
                    "c8", cpu=8, zones=("test-zone-1", "test-zone-2")),
                make_instance_type("z3", cpu=8, zones=("test-zone-3",)),
            ]
        )
        open_pool = mk_nodepool("default")
        open_pool.spec.template.spec.requirements = [
            RequirementSpec(key=ZONE, operator="In",
                            values=["test-zone-1", "test-zone-2"])
        ]
        env.kube.create(open_pool)
        tainted = mk_nodepool("z3-pool")
        tainted.spec.template.spec.taints = [
            Taint(key="dedicated", value="batch", effect="NoSchedule")
        ]
        tainted.spec.template.spec.requirements = [
            RequirementSpec(key=ZONE, operator="In", values=["test-zone-3"])
        ]
        env.kube.create(tainted)
        tol = [Toleration(key="dedicated", operator="Equal", value="batch",
                          effect="NoSchedule")]
        pods = [
            spread_pod(f"p{i}", taints_policy="Honor", tolerations=tol)
            for i in range(3)
        ]
        results = env.provision(*pods)
        assert results.scheduled_count == 3
        zones = set()
        for plan_pods in results.existing_assignments.values():
            pass
        for claim in env.kube.node_claims():
            for r in claim.spec.requirements:
                if r.key == ZONE and len(r.values) == 1:
                    zones.add(r.values[0])
        assert "test-zone-3" in zones
