"""Preemption-aware provisioning (ISSUE 8): a pending higher-priority
pod that fits no launchable or existing capacity nominates
lower-priority victims — PDB-respecting, never equal/higher priority,
nominate-then-evict ordering, landings through the binding queue.
"""

import time

from karpenter_tpu.apis.v1.labels import DO_NOT_DISRUPT_ANNOTATION
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.kube.objects import (
    LabelSelector,
    ObjectMeta,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
    PriorityClass,
)
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.testing import mk_nodepool, mk_pod


class Harness:
    """Operator over a one-node-capped pool: preemption is the only
    way in once the node fills."""

    def __init__(self, cpu_limit=4.0):
        self.kube = KubeClient()
        self.cloud = KwokCloudProvider(
            self.kube,
            types=[make_instance_type("c4", cpu=4, memory=16 * GIB)],
        )
        self.op = Operator(self.kube, self.cloud)
        pool = mk_nodepool("cap", limits={"cpu": cpu_limit})
        pool.spec.disruption.consolidate_after = "Never"
        self.kube.create(pool)
        self.now = time.time()

    def drive(self, ticks=10, dt=2.0):
        for _ in range(ticks):
            self.now += dt
            self.op.step(now=self.now)

    def fill_low(self, n=2, cpu=1.5, labels=None):
        for i in range(n):
            self.kube.create(mk_pod(
                name=f"lo-{i}", cpu=cpu, labels=labels or {}
            ))
        self.drive(8)
        assert all(
            p.spec.node_name for p in self.kube.pods()
        ), "low-priority workload must bind before the preemption test"

    def add_high(self, name="hi-0", cpu=1.5, priority=1000, owner=None):
        pod = mk_pod(name=name, cpu=cpu, owner=owner)
        pod.spec.priority = priority
        self.kube.create(pod)
        return pod

    def pod(self, name):
        return self.kube.get_pod("default", name)


class TestPreemption:
    def test_higher_priority_preempts_and_lands(self):
        h = Harness()
        h.fill_low()
        h.add_high()
        h.drive(14)
        hi = h.pod("hi-0")
        assert hi is not None and hi.spec.node_name, (
            "high-priority pod must land on preempted capacity"
        )
        # one victim rebirthed pending (workload-owner semantics) and
        # stays shed while the overload persists
        lows = [h.pod(f"lo-{i}") for i in range(2)]
        unbound = [p for p in lows if p is not None and not p.spec.node_name]
        assert len(unbound) == 1
        from karpenter_tpu.metrics.store import PREEMPTION_NOMINATIONS

        assert PREEMPTION_NOMINATIONS.total() >= 1

    def test_never_preempts_equal_or_higher_priority(self):
        h = Harness()
        for i in range(2):
            pod = mk_pod(name=f"lo-{i}", cpu=1.5)
            pod.spec.priority = 1000  # same as the would-be preemptor
            h.kube.create(pod)
        h.drive(8)
        h.add_high(priority=1000)
        h.drive(12)
        hi = h.pod("hi-0")
        assert hi is not None and not hi.spec.node_name
        assert all(
            h.pod(f"lo-{i}").spec.node_name for i in range(2)
        ), "equal-priority pods must never be preempted"

    def test_pdb_blocks_preemption(self):
        h = Harness()
        h.kube.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="protect"),
            spec=PodDisruptionBudgetSpec(
                selector=LabelSelector.of({"app": "guarded"}),
                max_unavailable=0,
            ),
        ))
        h.fill_low(labels={"app": "guarded"})
        h.add_high()
        h.drive(12)
        hi = h.pod("hi-0")
        assert hi is not None and not hi.spec.node_name
        assert all(
            h.pod(f"lo-{i}").spec.node_name for i in range(2)
        ), "PDB-guarded pods must never be preempted"

    def test_do_not_disrupt_blocks_preemption(self):
        h = Harness()
        for i in range(2):
            pod = mk_pod(name=f"lo-{i}", cpu=1.5)
            pod.metadata.annotations[DO_NOT_DISRUPT_ANNOTATION] = "true"
            h.kube.create(pod)
        h.drive(8)
        h.add_high()
        h.drive(12)
        assert not h.pod("hi-0").spec.node_name
        assert all(h.pod(f"lo-{i}").spec.node_name for i in range(2))

    def test_preemption_policy_never_queues_without_evicting(self):
        h = Harness()
        h.kube.create(PriorityClass(
            metadata=ObjectMeta(name="polite", namespace=""),
            value=1000, preemption_policy="Never",
        ))
        h.fill_low()
        pod = mk_pod(name="hi-0", owner=None, cpu=1.5)
        pod.spec.priority_class_name = "polite"
        h.kube.create(pod)
        h.drive(12)
        assert not h.pod("hi-0").spec.node_name
        assert all(h.pod(f"lo-{i}").spec.node_name for i in range(2))

    def test_nominate_before_evict(self):
        """The pod-level drain-after-replace: the preemptor's
        nominatedNodeName is stamped and its binding plan queued in the
        same reconcile that evicts the victims — the landing is secured
        before anything is killed."""
        h = Harness()
        h.fill_low()
        hi = h.add_high()
        # run exactly one provisioning round's worth of ticks and
        # observe the nomination the moment the victim disappears
        seen_nomination_with_victim_gone = False
        for _ in range(14):
            h.now += 2.0
            h.op.step(now=h.now)
            live = h.pod("hi-0")
            lows = [h.pod(f"lo-{i}") for i in range(2)]
            victim_gone = any(
                p is None or p.is_terminating() or not p.spec.node_name
                for p in lows
            )
            if victim_gone and live is not None:
                assert live.status.nominated_node_name or live.spec.node_name, (
                    "victim evicted before the preemptor had a "
                    "nominated landing"
                )
                seen_nomination_with_victim_gone = True
        assert seen_nomination_with_victim_gone

    def test_min_victim_set(self):
        """Evicting one 1.5-cpu victim frees enough for a 1.0-cpu
        preemptor; the second victim survives."""
        h = Harness()
        h.fill_low()
        h.add_high(cpu=1.0)
        h.drive(14)
        assert h.pod("hi-0").spec.node_name
        lows = [h.pod(f"lo-{i}") for i in range(2)]
        bound = [p for p in lows if p is not None and p.spec.node_name]
        assert len(bound) == 1, "only the minimal victim set is evicted"

    def test_victims_are_lowest_priority_first(self):
        h = Harness()
        mid = mk_pod(name="mid", cpu=1.5)
        mid.spec.priority = 500
        low = mk_pod(name="low", cpu=1.5)
        low.spec.priority = 10
        h.kube.create(mid)
        h.kube.create(low)
        h.drive(8)
        h.add_high(cpu=1.0, priority=1000)
        h.drive(14)
        assert h.pod("hi-0").spec.node_name
        assert h.pod("mid").spec.node_name, (
            "the higher-priority victim candidate must survive when "
            "evicting the lower one suffices"
        )
        assert not h.pod("low").spec.node_name
