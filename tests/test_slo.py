"""SLO burn-rate engine (ISSUE 13 tentpole part 2).

Two contracts under test: window arithmetic pinned at boundaries under
the injectable clock, and byte-identical verdicts/burn windows between
a faulted operator run and its byte-identical fault replay."""

import json

import pytest

from karpenter_tpu.metrics import slo
from karpenter_tpu.metrics.slo import SLI, SLOEngine
from karpenter_tpu.solver import faults


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for knob in ("KARPENTER_SLO", "KARPENTER_SLO_WINDOW_SHORT",
                 "KARPENTER_SLO_WINDOW_LONG", "KARPENTER_SLO_WARN_BURN",
                 "KARPENTER_SLO_PAGE_BURN", "KARPENTER_FAULTS"):
        monkeypatch.delenv(knob, raising=False)
    slo.reset_last_digest()
    yield
    slo.reset_last_digest()


def _good(signals):
    return signals["good"]


def _engine(monkeypatch, short=3, long=6, objective=0.5):
    monkeypatch.setenv("KARPENTER_SLO_WINDOW_SHORT", str(short))
    monkeypatch.setenv("KARPENTER_SLO_WINDOW_LONG", str(long))
    return SLOEngine(
        slis=(SLI("t", "test sli", objective, _good),),
        clock=lambda: 0.0,
    )


class TestWindowArithmetic:
    def test_burn_rate_exact_at_window_boundaries(self, monkeypatch):
        """objective 0.5 => error budget 0.5 => burn = 2 x bad_frac.
        Feed bad,good,bad into short window 3 / long window 6 and pin
        every intermediate value."""
        eng = _engine(monkeypatch)
        d = eng.observe_tick({"good": (0.0, 1.0)})
        assert d["verdicts"]["t"]["burn_short"] == 2.0   # 1/1 bad
        d = eng.observe_tick({"good": (1.0, 1.0)})
        assert d["verdicts"]["t"]["burn_short"] == 1.0   # 1/2 bad
        d = eng.observe_tick({"good": (0.0, 1.0)})
        assert d["verdicts"]["t"]["burn_short"] == pytest.approx(4 / 3)
        # tick 4: the short window slides — the first bad tick falls
        # out of the 3-tick window (good,bad remain + this good)
        d = eng.observe_tick({"good": (1.0, 1.0)})
        assert d["verdicts"]["t"]["burn_short"] == pytest.approx(2 / 3)
        # long window still sees all 4 ticks: 2 bad / 4 => burn 1.0
        assert d["verdicts"]["t"]["burn_long"] == 1.0

    def test_long_window_evicts_at_exactly_maxlen(self, monkeypatch):
        """6 bad ticks then 6 good ticks: at tick 12 the long window
        holds ONLY the good ticks — burn must be exactly 0."""
        eng = _engine(monkeypatch)
        for _ in range(6):
            eng.observe_tick({"good": (0.0, 1.0)})
        last = None
        for _ in range(6):
            last = eng.observe_tick({"good": (1.0, 1.0)})
        assert last["verdicts"]["t"]["burn_long"] == 0.0
        assert last["verdicts"]["t"]["data_ticks"] == 6

    def test_dataless_ticks_do_not_move_the_budget(self, monkeypatch):
        """evaluate() returning None (no cost solve ran, so no gap)
        must neither consume nor replenish the window."""
        eng = _engine(monkeypatch)
        eng.observe_tick({"good": (0.0, 1.0)})
        before = eng.digest()["verdicts"]["t"]
        for _ in range(10):
            eng.observe_tick({})   # KeyError inside evaluate -> None
        after = eng.digest()["verdicts"]["t"]
        assert after["burn_short"] == before["burn_short"]
        assert after["data_ticks"] == 1

    def test_multiwindow_alerting_requires_both_windows(self, monkeypatch):
        """A short-window spike alone must not page: the long window
        is the blip suppressor. Alerts count state TRANSITIONS."""
        from karpenter_tpu.metrics.store import SLO_ALERTS

        monkeypatch.setenv("KARPENTER_SLO_PAGE_BURN", "2.0")
        monkeypatch.setenv("KARPENTER_SLO_WARN_BURN", "1.5")
        eng = _engine(monkeypatch, short=2, long=8)
        for _ in range(8):
            eng.observe_tick({"good": (1.0, 1.0)})
        # two bad ticks: short burn = 2.0 but long = 2/8*2 = 0.5
        eng.observe_tick({"good": (0.0, 1.0)})
        d = eng.observe_tick({"good": (0.0, 1.0)})
        assert d["verdicts"]["t"]["burn_short"] == 2.0
        assert d["verdicts"]["t"]["state"] == "ok"
        # sustain the badness until the long window burns too
        pages0 = SLO_ALERTS.value({"slo": "t", "severity": "page"})
        last = None
        for _ in range(8):
            last = eng.observe_tick({"good": (0.0, 1.0)})
        assert last["verdicts"]["t"]["state"] == "page"
        assert last["worst"] == "page"
        # one transition into page, not one increment per burning tick
        assert SLO_ALERTS.value(
            {"slo": "t", "severity": "page"}
        ) == pages0 + 1

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SLO", "0")
        eng = _engine(monkeypatch)
        d = eng.observe_tick({"good": (0.0, 1.0)})
        assert d == {"enabled": False, "ticks": 0}


class TestDefaultSLIs:
    def test_tick_latency_budget_boundary(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SLO_TICK_BUDGET_MS", "100")
        from karpenter_tpu.metrics.slo import _tick_latency

        assert _tick_latency({"tick_wall_s": 0.1}) == (1.0, 1.0)
        assert _tick_latency({"tick_wall_s": 0.1001}) == (0.0, 1.0)
        assert _tick_latency({}) is None

    def test_optimality_gap_threshold(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SLO_GAP_MAX", "0.02")
        from karpenter_tpu.metrics.slo import _optimality

        assert _optimality({"gap_vs_lp": 0.02}) == (1.0, 1.0)
        assert _optimality({"gap_vs_lp": 0.03}) == (0.0, 1.0)
        assert _optimality({}) is None

    def test_note_buffer_drains_once(self):
        slo.note("gap_vs_lp", 0.01)
        slo.note("gap_vs_lp", 0.02)   # last value wins within a tick
        assert slo.take_noted() == {"gap_vs_lp": 0.02}
        assert slo.take_noted() == {}

    def test_unscheduled_pod_ticks_accumulate(self, monkeypatch):
        eng = SLOEngine(clock=lambda: 0.0)
        eng.observe_tick({"tick_wall_s": 0.01, "unschedulable_pods": 3,
                          "oracle_divergences": 0, "priority_shed": 0})
        eng.observe_tick({"tick_wall_s": 0.01, "unschedulable_pods": 2,
                          "oracle_divergences": 0, "priority_shed": 0})
        assert eng.digest()["unscheduled_pod_ticks"] == 5.0


@pytest.mark.chaos
class TestChaosDeterminism:
    def _run(self, spec, monkeypatch, ticks=6):
        """One operator run under `spec` with an injected SLO clock;
        returns (slo report, fault replay log)."""
        from karpenter_tpu import tracing
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.kube.client import KubeClient
        from karpenter_tpu.metrics.slo import SLOEngine
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.testing import mk_nodepool, mk_pod

        monkeypatch.setenv("KARPENTER_FAULTS", spec)
        monkeypatch.setenv("KARPENTER_FAULT_SEED", "11")
        faults.reset()
        tracing.clear()
        slo.reset_last_digest()
        kube = KubeClient()
        op = Operator(kube=kube, cloud_provider=KwokCloudProvider(kube),
                      options=Options())
        # the injectable clock: each tick's wall is exactly one unit,
        # so the tick-latency SLI sees identical values in both runs
        counter = iter(range(10_000))
        op.slo = SLOEngine(clock=lambda: float(next(counter)))
        kube.create(mk_nodepool("default"))
        for i in range(4):
            kube.create(mk_pod(name=f"sd-{i}", cpu=1.0))
        base = 1_700_000_000.0
        op.provisioner.batcher.trigger(now=base)
        for i in range(ticks):
            op.step(now=base + 2 + i)
        inj = faults.get()
        log = inj.snapshot_log() if inj is not None else []
        tracing.clear()
        return op.slo.report(), log

    def test_faulted_run_and_replay_have_identical_verdicts(
        self, monkeypatch
    ):
        """The acceptance criterion: a chaos run and its byte-identical
        replay produce byte-identical SLO verdicts AND burn windows —
        the whole report compares equal as JSON."""
        spec = "device_lost@solve:2,kube_conflict@kube_write:1"
        r1, log1 = self._run(spec, monkeypatch)
        r2, log2 = self._run(spec, monkeypatch)
        assert log1 == log2, "fault replay itself diverged"
        assert json.dumps(r1, sort_keys=True) == json.dumps(
            r2, sort_keys=True
        )
        # the run evaluated real ticks, not an empty engine
        assert r1["ticks"] >= 6
        assert r1["verdicts"]

    def test_clean_run_matches_its_own_replay_too(self, monkeypatch):
        r1, _ = self._run("", monkeypatch)
        r2, _ = self._run("", monkeypatch)
        assert json.dumps(r1, sort_keys=True) == json.dumps(
            r2, sort_keys=True
        )


class TestOperatorWiring:
    def test_readyz_carries_slo_digest(self):
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.kube.client import KubeClient
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.testing import mk_nodepool, mk_pod

        kube = KubeClient()
        op = Operator(kube=kube, cloud_provider=KwokCloudProvider(kube),
                      options=Options())
        digest = op.readyz()["slo"]
        assert digest["ticks"] == 0 and digest["worst"] == "ok"
        kube.create(mk_nodepool("default"))
        kube.create(mk_pod(name="rz-0", cpu=1.0))
        for i in range(3):
            op.step(now=1_700_000_000.0 + i)
        digest = op.readyz()["slo"]
        assert digest["ticks"] == 3
        assert set(digest["verdicts"]) == {
            "tick_latency", "schedulability", "solve_integrity",
            "admission", "pod_to_bind_latency", "optimality",
        }
        assert digest["worst"] in ("ok", "warn", "page")
        json.dumps(op.readyz())   # the whole probe stays serializable
        # a live tick also published the process-global digest bench
        # arms read
        assert slo.last_digest() is not None
        assert slo.last_digest()["ticks"] == 3
