"""Resilience layer unit tests: fault-spec parsing and replay
determinism, breaker state machine (incl. the re-warm close gate),
degradation ladder routing, watchdog deadlines, and the FFD hedge.

Engine/e2e chaos scenarios (device-lost mid-consolidation, rpc-drop
mid-provisioning) live in test_chaos.py; these pin the mechanisms.
"""

import threading
import time

import numpy as np
import pytest

from bench import build_problem
from karpenter_tpu.metrics.store import (
    SOLVER_BREAKER_STATE,
    SOLVER_DEADLINE_EXCEEDED,
    SOLVER_HEDGE,
    SOLVER_LADDER,
)
from karpenter_tpu.solver import faults, resilience
from karpenter_tpu.solver.encode import encode, group_pods
from karpenter_tpu.solver.pack import solve_packing
from karpenter_tpu.solver.resilience import (
    CircuitBreaker,
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    classify,
    host_pack_result,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Every test starts from closed breakers, no faults, no leftover
    degradation notes — and leaves the process the same way (breaker
    state is global; a leaked open breaker would silently degrade
    every later test's solves)."""
    monkeypatch.delenv("KARPENTER_FAULTS", raising=False)
    resilience.reset()
    faults.reset()
    yield
    resilience.reset()
    faults.reset()


def _enc(n_pods=200, n_types=10, seed=7):
    pods, pools = build_problem(n_pods, n_types, seed=seed)
    return encode(group_pods(pods), pools)


def _same_pack(a, b) -> bool:
    n = a.node_count
    return (
        n == b.node_count
        and np.array_equal(a.assign[:n], b.assign[:n])
        and np.array_equal(a.unschedulable, b.unschedulable)
    )


class TestFaultSpec:
    def test_parse_issue_example(self):
        rules = faults.parse(
            "device_lost@solve:3,rpc_drop@probe:*,compile_delay=5s"
        )
        assert [(r.kind, r.site, r.lo, r.hi) for r in rules] == [
            ("device_lost", "solve", 3, 3),
            ("rpc_drop", "probe", 0, -1),
            ("compile_delay", "compile", 0, -1),
        ]
        assert rules[2].delay == 5.0

    def test_parse_ranges_defaults_durations(self):
        rules = faults.parse(
            "rpc_drop:2-4,device_lost:5+,exec_delay=250ms"
        )
        assert (rules[0].site, rules[0].lo, rules[0].hi) == ("rpc", 2, 4)
        assert (rules[1].lo, rules[1].hi) == (5, -1)
        assert rules[2].site == "execute" and rules[2].delay == 0.25

    def test_malformed_entries_dropped_not_fatal(self):
        rules = faults.parse(
            "nonsense@solve, device_lost@badsite, compile_delay, "
            "device_lost@solve:0-0, ,device_lost@solve:2"
        )
        assert [(r.kind, r.lo) for r in rules] == [("device_lost", 2)]

    def test_occurrence_matching_is_per_site(self):
        inj = faults.FaultInjector(faults.parse("device_lost@solve:2"))
        inj.fire("probe")           # other sites don't advance 'solve'
        inj.fire("solve")           # occurrence 1: no fault
        with pytest.raises(faults.DeviceLostError):
            inj.fire("solve")       # occurrence 2: fires
        inj.fire("solve")           # occurrence 3: clear again

    def test_replay_is_byte_identical(self):
        spec = "device_lost@solve:2,rpc_drop@rpc:1-2,compile_delay:3=10ms"

        def run():
            inj = faults.FaultInjector(
                faults.parse(spec), sleep=lambda _t: None)
            for site in ("solve", "rpc", "compile", "solve", "rpc",
                         "compile", "solve", "compile"):
                try:
                    inj.fire(site)
                except faults.FaultError:
                    pass
            return inj.snapshot_log()

        first, second = run(), run()
        assert first == second
        assert first  # the spec actually fired something

    def test_env_spec_change_resets_counters(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@solve:1")
        with pytest.raises(faults.DeviceLostError):
            faults.fire("solve")
        faults.fire("solve")  # occurrence 2: clear
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@solve:1 ")
        with pytest.raises(faults.DeviceLostError):
            faults.fire("solve")  # fresh injector: occurrence 1 again


class TestFaultSeedGrammar:
    """ISSUE 18 satellite: the per-entry `#seed` suffix — each entry
    replays its rate/surge schedule from its OWN seed (scenario layers
    compose independently-seeded storms into one spec this way),
    falling back to the injector-wide KARPENTER_FAULT_SEED."""

    def test_seed_suffix_parses_on_every_param_shape(self):
        rules = faults.parse(
            "spot_interruption@cloud_interrupt:*=0.05#storm-a,"
            "compile_delay=5s#lag.1,"
            "demand_surge@provision_intake:2=100#burst_x,"
            "device_lost@solve:3#s"
        )
        assert [r.seed for r in rules] == [
            "storm-a", "lag.1", "burst_x", "s",
        ]
        assert rules[0].rate == 0.05 and rules[1].delay == 5.0
        assert rules[2].count == 100 and rules[3].lo == 3

    def test_entries_without_suffix_keep_none_seed(self):
        rules = faults.parse("spot_interruption@cloud_interrupt:*=0.1")
        assert rules[0].seed is None

    @pytest.mark.parametrize("bad", [
        "spot_interruption@cloud_interrupt:*=0.1#",       # empty
        "spot_interruption@cloud_interrupt:*=0.1#a#b",    # embedded #
        "spot_interruption@cloud_interrupt:*=0.1#a:b",    # embedded :
        "spot_interruption@cloud_interrupt:*=0.1#a=b",    # embedded =
        "spot_interruption@cloud_interrupt:*=0.1#a@b",    # embedded @
        "spot_interruption@cloud_interrupt:*=0.1#a b",    # whitespace
    ])
    def test_malformed_seeds_rejected_loudly(self, bad):
        from karpenter_tpu.metrics.store import FAULTS_REJECTED

        before = FAULTS_REJECTED.total()
        rejected: list = []
        rules = faults.parse(bad, rejected=rejected)
        assert rules == []
        assert rejected == [bad]
        assert FAULTS_REJECTED.total() == before + 1

    def test_per_entry_seed_overrides_injector_seed(self):
        """Same injector-wide seed, different `#seed`s: the rate
        schedules must diverge — and the same `#seed` must replay
        byte-identically regardless of the injector seed."""
        def fired(spec, injector_seed):
            inj = faults.FaultInjector(
                faults.parse(spec), sleep=lambda _t: None,
                seed=injector_seed,
            )
            out = []
            for seq in range(200):
                try:
                    inj.fire("cloud_interrupt")
                except faults.FaultError:
                    out.append(seq)
            return out

        spec_a = "spot_interruption@cloud_interrupt:*=0.2#aaa"
        spec_b = "spot_interruption@cloud_interrupt:*=0.2#bbb"
        assert fired(spec_a, "7") != fired(spec_b, "7")
        assert fired(spec_a, "7") == fired(spec_a, "99")

    def test_unseeded_entry_follows_injector_seed(self):
        def fired(injector_seed):
            inj = faults.FaultInjector(
                faults.parse("spot_interruption@cloud_interrupt:*=0.2"),
                sleep=lambda _t: None, seed=injector_seed,
            )
            out = []
            for seq in range(200):
                try:
                    inj.fire("cloud_interrupt")
                except faults.FaultError:
                    out.append(seq)
            return out

        assert fired("7") == fired("7")
        assert fired("7") != fired("99")

    def test_env_seed_fallback_via_get(self, monkeypatch):
        monkeypatch.setenv(
            "KARPENTER_FAULTS",
            "spot_interruption@cloud_interrupt:*=0.5#pinned",
        )
        monkeypatch.setenv("KARPENTER_FAULT_SEED", "3")
        faults.reset()
        inj = faults.get()
        assert inj.seed == "3"
        assert inj.rules[0].seed == "pinned"


class TestClassification:
    def test_taxonomy(self):
        assert classify(faults.DeviceLostError("x")) == "device_lost"
        assert classify(faults.RpcDropError("x")) == "rpc_unavailable"
        assert classify(resilience.CompileDeadlineExceeded("x")) == (
            "compile_timeout"
        )
        assert classify(resilience.DeadlineExceeded("x")) == "deadline"
        assert classify(ConnectionRefusedError("x")) == "rpc_unavailable"
        assert classify(ValueError("x")) == "error"

    def test_xla_runtime_error_is_device_lost(self):
        try:
            import jaxlib

            err_cls = jaxlib.xla_extension.XlaRuntimeError
        except Exception:
            pytest.skip("jaxlib XlaRuntimeError not importable")
        assert classify(err_cls("INTERNAL: device lost")) == "device_lost"


class TestCircuitBreaker:
    def _breaker(self, **kw):
        kw.setdefault("threshold", 2)
        kw.setdefault("base_cooldown", 0.05)
        kw.setdefault("max_cooldown", 0.2)
        return CircuitBreaker("test", **kw)

    def test_opens_after_threshold_then_half_opens_then_closes(self):
        br = self._breaker()
        assert br.allow()
        br.record_failure("device_lost")
        assert br.state == STATE_CLOSED and br.allow()
        br.record_failure("device_lost")
        assert br.state == STATE_OPEN
        assert not br.allow()
        time.sleep(0.06)
        assert br.allow()  # half-open probe admitted
        assert br.state == STATE_HALF_OPEN
        assert not br.allow()  # only ONE probe
        br.record_success()
        assert br.state == STATE_CLOSED
        assert SOLVER_BREAKER_STATE.value({"backend": "test"}) == 0.0

    def test_half_open_failure_reopens_with_longer_cooldown(self):
        br = self._breaker(rng=__import__("random").Random(3))
        br.record_failure("deadline")
        br.record_failure("deadline")
        first_retry = br._retry_at
        time.sleep(0.06)
        assert br.allow()
        br.record_failure("deadline")
        assert br.state == STATE_OPEN
        assert br._retry_at > first_retry

    def test_success_in_closed_resets_failure_streak(self):
        br = self._breaker()
        br.record_failure("error")
        br.record_success()
        br.record_failure("error")
        assert br.state == STATE_CLOSED  # streak broken, never tripped

    def test_close_gate_failure_keeps_breaker_open(self):
        verdicts = [False, True]
        br = self._breaker(close_gate=lambda: verdicts.pop(0))
        br.record_failure("device_lost")
        br.record_failure("device_lost")
        time.sleep(0.06)
        assert br.allow()
        br.record_success()  # gate says the device still can't compile
        assert br.state == STATE_OPEN
        time.sleep(0.25)
        assert br.allow()
        br.record_success()  # gate passes now
        assert br.state == STATE_CLOSED

    def test_abandoned_half_open_probe_does_not_wedge(self):
        br = self._breaker()
        br.record_failure("deadline")
        br.record_failure("deadline")
        time.sleep(0.06)
        assert br.allow()          # probe admitted ... then abandoned
        time.sleep(0.06)           # probe TTL elapses with no verdict
        assert br.allow()          # a new probe is admitted


class TestLadder:
    def test_healthy_path_serves_device_rung(self):
        enc = _enc()
        direct = solve_packing(enc, mode="ffd")
        before = SOLVER_LADDER.value({"rung": "device", "outcome": "ok"})
        out = resilience.shared().solve_packing(enc, mode="ffd")
        assert _same_pack(out, direct)
        assert SOLVER_LADDER.value(
            {"rung": "device", "outcome": "ok"}) == before + 1

    def test_device_lost_degrades_to_host_oracle(self, monkeypatch):
        enc = _enc(seed=11)
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@solve:*")
        faults.reset()
        out = resilience.shared().solve_packing(enc, mode="ffd")
        assert _same_pack(out, host_pack_result(enc))

    def test_sharded_rung_serves_wavefront_and_streaming(self, monkeypatch):
        """ISSUE 11: the ladder's sharded rung now routes the
        wavefront kernel over the streamed per-shard staging. The
        served result must equal the direct unsharded solve, and the
        sharded rung — not device — must take the ok."""
        from karpenter_tpu.solver import stream

        enc = _enc(seed=17)
        monkeypatch.setenv("KARPENTER_WAVEFRONT", "force")
        monkeypatch.setenv("KARPENTER_STREAM_ENCODE", "auto")
        direct = solve_packing(enc, mode="ffd")
        monkeypatch.setenv("KARPENTER_SOLVER_SHARDS", "8")
        before = SOLVER_LADDER.value({"rung": "sharded", "outcome": "ok"})
        stream.reset_stats()
        out = resilience.shared().solve_packing(enc, mode="ffd")
        assert _same_pack(out, direct)
        assert SOLVER_LADDER.value(
            {"rung": "sharded", "outcome": "ok"}) == before + 1
        # the rung's staging actually streamed (blocks were shipped)
        assert stream.last_stats().get("blocks", 0) > 0

    def test_sharded_rung_failure_degrades_to_single_device(
        self, monkeypatch
    ):
        """One injected device loss on the sharded rung: the ladder
        falls to the single-device rung, whose answer is identical."""
        enc = _enc(seed=19)
        direct = solve_packing(enc, mode="ffd")
        monkeypatch.setenv("KARPENTER_SOLVER_SHARDS", "8")
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@solve:1")
        faults.reset()
        resilience.reset()
        before_dev = SOLVER_LADDER.value({"rung": "device", "outcome": "ok"})
        out = resilience.shared().solve_packing(enc, mode="ffd")
        assert _same_pack(out, direct)
        assert SOLVER_LADDER.value(
            {"rung": "device", "outcome": "ok"}) == before_dev + 1

    def test_breaker_opens_and_skips_then_recloses(self, monkeypatch):
        enc = _enc(seed=13)
        # cooldown far beyond any suite-load stall: the skip assertion
        # below must observe a breaker that is STILL cooling down, so
        # the elapse is forced explicitly rather than slept for
        monkeypatch.setenv("KARPENTER_BREAKER_COOLDOWN_MS", "60000")
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@solve:*")
        faults.reset()
        rs = resilience.shared()
        rs.solve_packing(enc, mode="ffd")
        rs.solve_packing(enc, mode="ffd")
        assert rs.breaker("device").state == STATE_OPEN
        before = SOLVER_LADDER.value(
            {"rung": "device", "outcome": "skipped_open"})
        rs.solve_packing(enc, mode="ffd")  # open: no device attempt
        assert SOLVER_LADDER.value(
            {"rung": "device", "outcome": "skipped_open"}) == before + 1
        # fault clears; cooldown elapses (forced); half-open probe
        # succeeds and closes the breaker
        monkeypatch.delenv("KARPENTER_FAULTS")
        faults.reset()
        rs.breaker("device")._retry_at = 0.0
        direct = solve_packing(enc, mode="ffd")
        out = rs.solve_packing(enc, mode="ffd")
        assert _same_pack(out, direct)
        assert rs.breaker("device").state == STATE_CLOSED

    def test_rewarm_gate_consulted_on_close(self, monkeypatch):
        enc = _enc(seed=17)
        monkeypatch.setenv("KARPENTER_BREAKER_COOLDOWN_MS", "30")
        monkeypatch.setenv("KARPENTER_REWARM_ON_CLOSE", "1")
        calls = []

        import karpenter_tpu.solver.warm_pool as wp

        monkeypatch.setattr(
            wp, "rewarm_canary", lambda: calls.append(1) or True)
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@solve:1-2")
        faults.reset()
        rs = resilience.shared()
        rs.solve_packing(enc, mode="ffd")
        rs.solve_packing(enc, mode="ffd")
        assert rs.breaker("device").state == STATE_OPEN
        time.sleep(0.06)
        rs.solve_packing(enc, mode="ffd")  # probe succeeds -> gate runs
        assert calls, "re-warm gate was not consulted on close"
        assert rs.breaker("device").state == STATE_CLOSED

    def test_explicit_ladder_order_override(self, monkeypatch):
        enc = _enc(seed=19)
        monkeypatch.setenv("KARPENTER_SOLVE_LADDER", "host")
        out = resilience.shared().solve_packing(enc, mode="ffd")
        assert _same_pack(out, host_pack_result(enc))

    def test_async_fetch_failure_falls_down_ladder(self, monkeypatch):
        enc = _enc(seed=23)
        # the dispatch succeeds; the EXECUTE fetch loses the device
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@execute:*")
        faults.reset()
        pending = resilience.shared().solve_packing_async(enc, mode="ffd")
        out = pending.result()
        assert _same_pack(out, host_pack_result(enc))


class TestDeadlines:
    def test_compile_stall_times_out_and_degrades(self, monkeypatch):
        enc = _enc(seed=29)
        monkeypatch.setenv("KARPENTER_FAULTS", "compile_delay=1.5s")
        monkeypatch.setenv("KARPENTER_COMPILE_DEADLINE_MS", "150")
        monkeypatch.setenv("KARPENTER_SOLVE_DEADLINE_MS", "400")
        faults.reset()
        before = SOLVER_DEADLINE_EXCEEDED.value({"phase": "compile"})
        t0 = time.monotonic()
        out = resilience.shared().solve_packing(enc, mode="ffd")
        wall = time.monotonic() - t0
        assert _same_pack(out, host_pack_result(enc))
        assert SOLVER_DEADLINE_EXCEEDED.value(
            {"phase": "compile"}) == before + 1
        assert wall < 1.4, (
            f"decision took {wall:.2f}s — the watchdog must not wait "
            "out the stalled compile"
        )
        assert SOLVER_LADDER.value(
            {"rung": "device", "outcome": "compile_timeout"}) >= 1

    def test_execute_stall_times_out_within_deadline(self, monkeypatch):
        enc = _enc(seed=31)
        monkeypatch.setenv("KARPENTER_FAULTS", "exec_delay=1.5s")
        monkeypatch.setenv("KARPENTER_SOLVE_DEADLINE_MS", "300")
        faults.reset()
        t0 = time.monotonic()
        out = resilience.shared().solve_packing(enc, mode="ffd")
        wall = time.monotonic() - t0
        assert _same_pack(out, host_pack_result(enc))
        assert wall < 1.4
        assert SOLVER_LADDER.value(
            {"rung": "device", "outcome": "deadline"}) >= 1

    def test_hedge_precomputes_the_degraded_answer(self, monkeypatch):
        """Flaked under suite load (CHANGES.md): the 50ms hedge timer
        occasionally fired late enough (CPU contention) that the
        deadline path served the direct host solve and no `win` was
        counted, though the RESULT was always right. Best-of-N retry:
        the result assertion holds every attempt; the timing-coupled
        win-counter assertion must hold on at least one of three —
        a systematically broken hedge still fails all three."""
        from karpenter_tpu.testing import interleaved_best_of

        enc = _enc(seed=37)
        monkeypatch.setenv("KARPENTER_FAULTS", "exec_delay=1.5s")
        monkeypatch.setenv("KARPENTER_SOLVE_DEADLINE_MS", "500")
        monkeypatch.setenv("KARPENTER_SOLVE_HEDGE_MS", "50")

        def attempt() -> float:
            faults.reset()
            wins = SOLVER_HEDGE.value({"outcome": "win"})
            out = resilience.shared().solve_packing(enc, mode="ffd")
            # the RESULT must be right on every attempt; only the
            # timing-coupled win counter gets the best-of-N retry
            assert _same_pack(out, host_pack_result(enc))
            return float(
                SOLVER_HEDGE.value({"outcome": "win"}) == wins + 1
            )

        # the shared interleaved best-of-N helper, degenerate single
        # side with reduce=max: early exit on the first win, up to 3
        # attempts — a systematically broken hedge still fails all 3
        best = interleaved_best_of(
            {"hedge_won": attempt},
            rounds=3,
            min_rounds=1,
            satisfied=lambda b: b["hedge_won"] >= 1.0,
            reduce=max,
            disable_gc=False,
        )
        assert best["hedge_won"] >= 1.0, (
            "hedge never supplied the degraded answer in 3 attempts"
        )

    def test_instant_failure_does_not_burn_compile_budget(self, monkeypatch):
        """A device that dies BEFORE the kernel dispatch must release
        the watchdog immediately — not let the compile-budget wait
        sleep out its full window per rung."""
        enc = _enc(seed=47)
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@solve:*")
        monkeypatch.setenv("KARPENTER_COMPILE_DEADLINE_MS", "5000")
        monkeypatch.setenv("KARPENTER_SOLVE_DEADLINE_MS", "8000")
        faults.reset()
        t0 = time.monotonic()
        out = resilience.shared().solve_packing(enc, mode="ffd")
        wall = time.monotonic() - t0
        assert _same_pack(out, host_pack_result(enc))
        assert wall < 2.0, (
            f"instant device failure took {wall:.2f}s — the compile "
            "budget was slept out instead of released"
        )

    def test_degraded_report_survives_worker_thread_ladder(
        self, monkeypatch
    ):
        """With a deadline set the ladder runs on a watchdog/executor
        thread — the degradation note must still land on the CALLING
        thread (the one the scheduler pops)."""
        enc = _enc(seed=53)
        monkeypatch.setenv("KARPENTER_FAULTS", "device_lost@solve:*")
        monkeypatch.setenv("KARPENTER_SOLVE_DEADLINE_MS", "8000")
        faults.reset()
        resilience.pop_degraded()
        pending = resilience.shared().solve_packing_async(enc, mode="ffd")
        out = pending.result()
        assert _same_pack(out, host_pack_result(enc))
        assert "host" in resilience.pop_degraded()

    def test_healthy_solve_ignores_generous_deadline(self, monkeypatch):
        enc = _enc(seed=41)
        monkeypatch.setenv("KARPENTER_SOLVE_DEADLINE_MS", "60000")
        direct = solve_packing(enc, mode="ffd")
        out = resilience.shared().solve_packing(enc, mode="ffd")
        assert _same_pack(out, direct)


class TestHostOracleParity:
    def test_host_pack_result_matches_backend_host_decode(self):
        """host_pack_result must be the SAME oracle `backend=host`
        decodes — the ladder's floor and the explicit host backend can
        never drift apart."""
        from karpenter_tpu.solver.solver import (
            _build_solution_arrays,
            _decode_host,
        )

        enc = _enc(seed=43)
        via_ladder = host_pack_result(enc)
        sol_ladder = _build_solution_arrays(
            enc,
            np.flatnonzero(via_ladder.node_active[: via_ladder.node_count]),
            via_ladder.node_mask,
            via_ladder.assign,
            via_ladder.unschedulable,
        )
        sol_host = _decode_host(enc)
        assert len(sol_ladder.new_nodes) == len(sol_host.new_nodes)
        assert [n.price for n in sol_ladder.new_nodes] == [
            n.price for n in sol_host.new_nodes
        ]
        assert len(sol_ladder.unschedulable) == len(sol_host.unschedulable)
