"""Split-kernel equivalence: pack_split must reproduce the dense
kernel (`pack`) bit-for-bit.

The split kernel moves one-hot rows (existing + LP-planned nodes) out
of the [N, C] mask state into a per-row vector block; the dense kernel
stays as the oracle. Any divergence in assignment, masks, node count,
or unschedulable tallies on randomized problems is a correctness bug,
not a tolerance issue — every kernel choice is an index-tie-broken
arg-reduction, so results are exactly reproducible.
"""

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake import GIB, instance_types, make_instance_type
from karpenter_tpu.solver.encode import encode, group_pods
from karpenter_tpu.solver.pack import (
    _pad_axis,
    pack,
    pack_split,
)
from karpenter_tpu.testing import mk_nodepool, mk_pod

import jax.numpy as jnp


def _run_both(enc, existing_mask, existing_used, max_nodes, mode,
              quota=None):
    """Run dense and split kernels on identical padded inputs and
    compare every output."""
    G, C = enc.compat.shape
    R = enc.group_req.shape[1]
    E = existing_mask.shape[0]
    Gp, Cp = _pad_axis(G), _pad_axis(C)
    Cp = -(-Cp // 32) * 32
    Ep = _pad_axis(E) if E else 0
    N = max_nodes

    compat = np.zeros((Gp, Cp), bool)
    compat[:G, :C] = enc.compat
    group_req = np.zeros((Gp, R), np.float32)
    group_req[:G] = enc.group_req
    group_count = np.zeros((Gp,), np.int32)
    group_count[:G] = enc.group_count
    cfg_alloc = np.zeros((Cp, R), np.float32)
    cfg_alloc[:C] = enc.cfg_alloc
    cfg_pool = np.full((Cp,), -1, np.int32)
    cfg_pool[:C] = enc.cfg_pool
    cfg_price = np.zeros((Cp,), np.float32)
    cfg_price[:C] = enc.cfg_price
    emask = np.zeros((Ep, Cp), bool)
    eused = np.zeros((Ep, R), np.float32)
    if E:
        emask[:E, :C] = existing_mask
        eused[:E] = existing_used

    cfg_rsv = None
    rsv_cap = None
    K = 0
    if enc.rsv_cap is not None and enc.rsv_cap.size:
        K = int(enc.rsv_cap.size)
        rsvp = np.full((Cp,), -1, np.int32)
        rsvp[:C] = enc.cfg_rsv
        cfg_rsv = jnp.asarray(rsvp)
        rsv_cap = jnp.asarray(enc.rsv_cap.astype(np.float32))
        cfg_rsv_h = rsvp
    else:
        cfg_rsv_h = np.full((Cp,), -1, np.int32)

    quota_full = None
    bound_quota = None
    if quota is not None:
        quota_full = np.full((N, Gp), np.int16(32767), np.int16)
        quota_full[: quota.shape[0], :G] = np.minimum(
            quota[:, :G], 32767
        ).astype(np.int16)
        bound_quota = np.full((Ep, Gp), np.int16(32767), np.int16)
        bound_quota[: quota.shape[0], :G] = np.minimum(
            quota[:, :G], 32767
        ).astype(np.int16)
        quota_full = jnp.asarray(quota_full)
        bound_quota = jnp.asarray(bound_quota)

    dense = pack(
        jnp.asarray(compat), jnp.asarray(group_req), jnp.asarray(group_count),
        jnp.asarray(cfg_alloc), jnp.asarray(cfg_pool),
        jnp.asarray(enc.pool_overhead), jnp.asarray(emask),
        jnp.asarray(eused), jnp.asarray(cfg_price),
        max_nodes=N, mode=mode, quota=quota_full,
        cfg_rsv=cfg_rsv, rsv_cap=rsv_cap,
    )
    d_assign, d_mask, _, d_active, d_count, d_unsched = [
        np.asarray(x) for x in dense
    ]

    bound_cfg = np.full((Ep,), -1, np.int32)
    if E:
        bound_cfg[:E] = np.where(
            existing_mask.any(axis=1), existing_mask.argmax(axis=1), -1
        )
    bound_live = bound_cfg >= 0
    safe_cfg = np.maximum(bound_cfg, 0)
    bound_alloc = np.where(bound_live[:, None], cfg_alloc[safe_cfg], 0.0)
    bound_compat = compat[:, safe_cfg] & bound_live[None, :] if Ep else np.zeros((Gp, 0), bool)
    bound_slot = np.where(
        bound_live & (cfg_rsv_h[safe_cfg] >= 0), cfg_rsv_h[safe_cfg], K
    ).astype(np.int32)

    split = pack_split(
        jnp.asarray(compat), jnp.asarray(group_req), jnp.asarray(group_count),
        jnp.asarray(cfg_alloc), jnp.asarray(cfg_pool),
        jnp.asarray(enc.pool_overhead),
        jnp.asarray(bound_compat), jnp.asarray(bound_alloc.astype(np.float32)),
        jnp.asarray(eused), jnp.asarray(bound_slot), jnp.asarray(bound_live),
        jnp.asarray(cfg_price),
        max_free=N - Ep, mode=mode, bound_quota=bound_quota,
        cfg_rsv=cfg_rsv, rsv_cap=rsv_cap,
    )
    s_assign, s_free_mask, s_count, s_unsched = [np.asarray(x) for x in split]

    np.testing.assert_array_equal(d_assign, s_assign)
    assert d_count == s_count
    np.testing.assert_array_equal(d_unsched, s_unsched)
    # dense mask rows [Ep:] must equal split free rows; bound rows stay
    # one-hot in the dense kernel (never tightened)
    np.testing.assert_array_equal(d_mask[Ep:], s_free_mask)
    if Ep:
        for b in range(Ep):
            expected = np.zeros((Cp,), bool)
            if bound_live[b]:
                expected[bound_cfg[b]] = True
            np.testing.assert_array_equal(d_mask[b], expected)


def _random_problem(seed, n_pods=300, n_types=20, reservations=False):
    rng = np.random.default_rng(seed)
    if reservations:
        types = []
        for i in range(n_types):
            cpu = float(rng.choice([2, 4, 8, 16]))
            rsv = (
                [(f"rsv-{i}", "test-zone-1", int(rng.integers(1, 4)))]
                if rng.random() < 0.3
                else None
            )
            types.append(
                make_instance_type(
                    f"t-{i}", cpu=cpu, memory=cpu * 4 * GIB,
                    price=cpu * float(rng.uniform(0.8, 1.2)),
                    reservations=rsv,
                )
            )
    else:
        types = instance_types(n_types)
    pool = mk_nodepool("default")
    pods = []
    for i in range(n_pods):
        cpu = float(rng.choice([0.25, 0.5, 1.0, 2.0, 4.0]))
        mem = float(rng.choice([0.5, 1.0, 2.0, 8.0])) * GIB
        sel = {}
        if rng.random() < 0.3:
            sel["kubernetes.io/arch"] = "amd64"
        pods.append(mk_pod(name=f"p-{i}", cpu=cpu, memory=mem,
                           node_selector=sel))
    enc = encode(group_pods(pods), [(pool, types)], [])
    return enc


class TestSplitEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    @pytest.mark.parametrize("mode", ["ffd", "cost"])
    def test_fresh_only(self, seed, mode):
        enc = _random_problem(seed)
        existing_mask = np.zeros((0, enc.compat.shape[1]), bool)
        existing_used = np.zeros((0, enc.group_req.shape[1]), np.float32)
        _run_both(enc, existing_mask, existing_used, 256, mode)

    @pytest.mark.parametrize("seed", [5, 6])
    @pytest.mark.parametrize("mode", ["ffd", "cost"])
    def test_with_reservations(self, seed, mode):
        enc = _random_problem(seed, reservations=True)
        existing_mask = np.zeros((0, enc.compat.shape[1]), bool)
        existing_used = np.zeros((0, enc.group_req.shape[1]), np.float32)
        _run_both(enc, existing_mask, existing_used, 256, mode)

    @pytest.mark.parametrize("seed", [7, 8])
    def test_with_existing_rows(self, seed):
        enc = _random_problem(seed)
        C = enc.compat.shape[1]
        R = enc.group_req.shape[1]
        rng = np.random.default_rng(seed + 100)
        E = 6
        existing_mask = np.zeros((E, C), bool)
        existing_used = np.zeros((E, R), np.float32)
        launchable = np.flatnonzero(enc.cfg_pool >= 0)
        for e in range(E):
            c = int(rng.choice(launchable))
            existing_mask[e, c] = True
            existing_used[e] = enc.cfg_alloc[c] * float(rng.uniform(0, 0.5))
        _run_both(enc, existing_mask, existing_used, 256, "ffd")

    def test_planned_quota_rows(self):
        """Planned slots with per-group quotas (the LP path shape)."""
        enc = _random_problem(11)
        C = enc.compat.shape[1]
        R = enc.group_req.shape[1]
        G = enc.compat.shape[0]
        rng = np.random.default_rng(42)
        P = 8
        existing_mask = np.zeros((P, C), bool)
        existing_used = np.zeros((P, R), np.float32)
        launchable = np.flatnonzero(enc.cfg_pool >= 0)
        quota = np.zeros((P, G), np.int32)
        for p in range(P):
            c = int(rng.choice(launchable))
            existing_mask[p, c] = True
            existing_used[p] = enc.pool_overhead[enc.cfg_pool[c]]
            quota[p] = rng.integers(0, 5, size=G)
        _run_both(enc, existing_mask, existing_used, 256, "cost",
                  quota=quota)
