"""NodeClaim lifecycle long tail.

Ports uncovered families from
/root/reference/pkg/controllers/nodeclaim/lifecycle/*_test.go:
initialization gating (NotReady, missing resources, startup and
ephemeral taints), registration sync (labels/annotations/taints,
unregistered-taint removal, node owner reference), launch errors
(ICE / NodeClassNotReady delete the claim), and liveness timeouts.
"""

import time

from karpenter_tpu.apis.v1.labels import (
    NODE_INITIALIZED_LABEL,
    NODE_REGISTERED_LABEL,
    UNREGISTERED_TAINT_KEY,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
)
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.cloudprovider.types import (
    InsufficientCapacityError,
    NodeClassNotReadyError,
)
from karpenter_tpu.kube.objects import Taint
from karpenter_tpu.lifecycle.nodeclaim_lifecycle import (
    LAUNCH_TIMEOUT_SECONDS,
    REGISTRATION_TIMEOUT_SECONDS,
)
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def _env(**env_kwargs):
    env = Environment(
        types=[make_instance_type("c8", cpu=8, memory=32 * GIB)],
        **env_kwargs,
    )
    env.kube.create(mk_nodepool("default"))
    return env


class TestRegistrationSync:
    def test_registered_label_and_unregistered_taint(self):
        env = _env()
        env.provision(mk_pod(cpu=1.0))
        node = env.kube.nodes()[0]
        assert node.metadata.labels.get(NODE_REGISTERED_LABEL) == "true"
        assert not any(
            t.key == UNREGISTERED_TAINT_KEY for t in node.spec.taints
        )

    def test_claim_labels_annotations_sync_to_node(self):
        env = Environment(types=[
            make_instance_type("c8", cpu=8, memory=32 * GIB),
        ])
        pool = mk_nodepool("default")
        pool.spec.template.labels["team"] = "ml"
        pool.spec.template.annotations["contact"] = "oncall"
        env.kube.create(pool)
        env.provision(mk_pod(cpu=1.0))
        node = env.kube.nodes()[0]
        assert node.metadata.labels.get("team") == "ml"
        assert node.metadata.annotations.get("contact") == "oncall"

    def test_node_owned_by_claim(self):
        # registration.go adds the NodeClaim controller reference
        env = _env()
        env.provision(mk_pod(cpu=1.0))
        node = env.kube.nodes()[0]
        claim = env.kube.node_claims()[0]
        owners = [r for r in node.metadata.owner_references
                  if r.kind == "NodeClaim"]
        assert owners and owners[0].name == claim.metadata.name
        assert owners[0].controller

    def test_owner_reference_not_duplicated(self):
        env = _env()
        env.provision(mk_pod(cpu=1.0))
        # re-running registration must not stack references
        env.lifecycle.reconcile_all()
        env.lifecycle.reconcile_all()
        node = env.kube.nodes()[0]
        owners = [r for r in node.metadata.owner_references
                  if r.kind == "NodeClaim"]
        assert len(owners) == 1

    def test_pool_taints_sync_to_node(self):
        env = Environment(types=[
            make_instance_type("c8", cpu=8, memory=32 * GIB),
        ])
        pool = mk_nodepool("default")
        pool.spec.template.spec.taints = [
            Taint(key="dedicated", value="batch", effect="NoSchedule")
        ]
        env.kube.create(pool)
        pod = mk_pod(cpu=1.0)
        from karpenter_tpu.kube.objects import Toleration

        pod.spec.tolerations = [
            Toleration(key="dedicated", operator="Exists")
        ]
        env.provision(pod)
        node = env.kube.nodes()[0]
        assert any(t.key == "dedicated" for t in node.spec.taints)


class TestInitializationGating:
    def _stalled_claim(self, registration_delay=0.0, startup_taints=()):
        env = Environment(
            types=[make_instance_type("c8", cpu=8, memory=32 * GIB)],
            registration_delay=registration_delay,
        )
        pool = mk_nodepool("default")
        pool.spec.template.spec.startup_taints = list(startup_taints)
        env.kube.create(pool)
        return env

    def test_not_initialized_before_registered(self):
        env = self._stalled_claim(registration_delay=3600.0)
        env.provision(mk_pod(cpu=1.0))
        claim = env.kube.node_claims()[0]
        assert claim.status_conditions.is_true(COND_LAUNCHED)
        assert not claim.status_conditions.is_true(COND_REGISTERED)
        assert not claim.status_conditions.is_true(COND_INITIALIZED)

    def test_not_initialized_while_node_not_ready(self):
        env = _env()
        env.provision(mk_pod(cpu=1.0))
        node = env.kube.nodes()[0]
        claim = env.kube.node_claims()[0]
        assert claim.status_conditions.is_true(COND_INITIALIZED)
        # a NEW claim whose node goes NotReady never initializes
        env.kube.create(mk_pod(name="more", cpu=7.5))
        env.provisioner.batcher.trigger()
        env.provisioner.reconcile()
        env.lifecycle.reconcile_all()
        env.cloud.tick()
        fresh = [n for n in env.kube.nodes()
                 if n.metadata.name != node.metadata.name]
        assert fresh, "setup: second node never provisioned"
        fresh[0].status.conditions[0].status = "False"
        env.lifecycle.reconcile_all()
        fresh_claim = [
            c for c in env.kube.node_claims()
            if c.status.node_name == fresh[0].metadata.name
        ][0]
        assert not fresh_claim.status_conditions.is_true(COND_INITIALIZED)

    def test_not_initialized_until_startup_taints_removed(self):
        env = self._stalled_claim(startup_taints=[
            Taint(key="cni.example.com/not-ready", value="",
                  effect="NoExecute"),
        ])
        env.provision(mk_pod(cpu=1.0))
        claim = env.kube.node_claims()[0]
        node = env.kube.nodes()[0]
        assert claim.status_conditions.is_true(COND_REGISTERED)
        assert not claim.status_conditions.is_true(COND_INITIALIZED)
        # the CNI daemon removes its taint: initialization completes
        node.spec.taints = [
            t for t in node.spec.taints
            if t.key != "cni.example.com/not-ready"
        ]
        env.kube.update(node)
        env.lifecycle.reconcile_all()
        assert claim.status_conditions.is_true(COND_INITIALIZED)
        assert node.metadata.labels.get(NODE_INITIALIZED_LABEL) == "true"

    def test_not_initialized_until_ephemeral_taints_removed(self):
        env = _env()
        env.provision(mk_pod(cpu=1.0))
        node = env.kube.nodes()[0]
        # a fresh ephemeral taint (node.kubernetes.io/*) blocks a NEW
        # claim's initialization; simulate by un-initializing state
        claim = env.kube.node_claims()[0]
        claim.status_conditions.set_false(
            COND_INITIALIZED, "Test", "reset", now=time.time()
        )
        node.metadata.labels.pop(NODE_INITIALIZED_LABEL, None)
        node.spec.taints.append(
            Taint(key="node.kubernetes.io/not-ready", effect="NoExecute")
        )
        env.kube.update(node)
        env.lifecycle.reconcile_all()
        assert not claim.status_conditions.is_true(COND_INITIALIZED)
        node.spec.taints = [
            t for t in node.spec.taints
            if t.key != "node.kubernetes.io/not-ready"
        ]
        env.kube.update(node)
        env.lifecycle.reconcile_all()
        assert claim.status_conditions.is_true(COND_INITIALIZED)

    def test_not_initialized_until_extended_resources_registered(self):
        from karpenter_tpu.cloudprovider.fake import make_instance_type as mit

        env = Environment(types=[
            mit("gpu8", cpu=8, memory=32 * GIB,
                extra_resources={"example.com/gpu": 4.0}),
        ])
        env.kube.create(mk_nodepool("default"))
        env.provision(mk_pod(cpu=1.0))
        claim = env.kube.node_claims()[0]
        node = env.kube.get_node(claim.status.node_name)
        # simulate the device plugin not having advertised yet
        claim.status_conditions.set_false(
            COND_INITIALIZED, "Test", "reset", now=time.time()
        )
        node.metadata.labels.pop(NODE_INITIALIZED_LABEL, None)
        claim.spec.resources = {"example.com/gpu": 2.0}
        saved = node.status.allocatable.pop("example.com/gpu")
        env.kube.update(node)
        env.lifecycle.reconcile_all()
        assert not claim.status_conditions.is_true(COND_INITIALIZED)
        node.status.allocatable["example.com/gpu"] = saved
        env.kube.update(node)
        env.lifecycle.reconcile_all()
        assert claim.status_conditions.is_true(COND_INITIALIZED)


class TestLaunchErrors:
    def test_insufficient_capacity_deletes_claim(self):
        env = _env()
        env.cloud.next_create_error = InsufficientCapacityError("sold out")
        env.kube.create(mk_pod(name="w", cpu=1.0))
        env.provisioner.batcher.trigger()
        env.provisioner.reconcile()
        claims = env.kube.node_claims()
        assert claims, "setup: no claim was created"
        env.lifecycle.reconcile_all()
        # ICE is terminal for the claim (lifecycle deletes it; the pod
        # reschedules through a fresh solve)
        for claim in claims:
            live = env.kube.get_node_claim(claim.metadata.name)
            assert live is None or live.metadata.deletion_timestamp is not None

    def test_node_class_not_ready_deletes_claim(self):
        env = _env()
        env.cloud.next_create_error = NodeClassNotReadyError("nodeclass gone")
        env.kube.create(mk_pod(name="w", cpu=1.0))
        env.provisioner.batcher.trigger()
        env.provisioner.reconcile()
        claims = env.kube.node_claims()
        assert claims, "setup: no claim was created"
        env.lifecycle.reconcile_all()
        for claim in claims:
            live = env.kube.get_node_claim(claim.metadata.name)
            assert live is None or live.metadata.deletion_timestamp is not None


class TestLivenessTimeouts:
    def test_launch_timeout_deletes_after_window(self):
        env = _env()
        env.cloud.next_create_error = RuntimeError("transient API error")
        env.kube.create(mk_pod(name="w", cpu=1.0))
        env.provisioner.batcher.trigger()
        now = time.time()
        env.provisioner.reconcile(now=now)
        claims = env.kube.node_claims()
        assert claims and not claims[0].status_conditions.is_true(
            COND_LAUNCHED
        )
        # inside the window: kept (retried)
        env.cloud.next_create_error = RuntimeError("still failing")
        env.lifecycle.reconcile_all(now=now + LAUNCH_TIMEOUT_SECONDS - 10)
        assert env.kube.get_node_claim(claims[0].metadata.name) is not None
        # past the window: deleted
        env.cloud.next_create_error = RuntimeError("still failing")
        env.lifecycle.reconcile_all(now=now + LAUNCH_TIMEOUT_SECONDS + 10)
        env.reconcile_termination(now=now + LAUNCH_TIMEOUT_SECONDS + 11)
        remaining = env.kube.get_node_claim(claims[0].metadata.name)
        assert remaining is None or remaining.metadata.deletion_timestamp

    def test_registration_timeout_deletes_after_window(self):
        env = Environment(
            types=[make_instance_type("c8", cpu=8, memory=32 * GIB)],
            registration_delay=10 * REGISTRATION_TIMEOUT_SECONDS,
        )
        env.kube.create(mk_nodepool("default"))
        env.kube.create(mk_pod(name="w", cpu=1.0))
        env.provisioner.batcher.trigger()
        now = time.time()
        env.provisioner.reconcile(now=now)
        env.lifecycle.reconcile_all(now=now)
        claim = env.kube.node_claims()[0]
        assert claim.status_conditions.is_true(COND_LAUNCHED)
        assert not claim.status_conditions.is_true(COND_REGISTERED)
        env.lifecycle.reconcile_all(
            now=now + REGISTRATION_TIMEOUT_SECONDS - 10
        )
        assert claim.metadata.deletion_timestamp is None
        env.lifecycle.reconcile_all(
            now=now + REGISTRATION_TIMEOUT_SECONDS + 10
        )
        assert claim.metadata.deletion_timestamp is not None
