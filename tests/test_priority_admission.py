"""Priority-aware overload protection (ISSUE 8): PriorityClass
resolution, the priority-ordered admission contract and its fuzz
oracle, the preemption controller, and the priority-aware disruption
veto.

The admission oracle is the tentpole's acceptance check: under demand
> capacity (fuzzed pool limits and catalogs), the unscheduled set must
equal the LOWEST-PRIORITY TAIL of the admission order — sorted pods by
(-priority, deterministic FFD order), the unscheduled pods are exactly
a suffix — across seeds.
"""

import random

import pytest

from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.kube.objects import ObjectMeta, PriorityClass
from karpenter_tpu.provisioning.priority import (
    PRIORITY_SHED_ERROR,
    admission_order,
    mixed_priorities,
    placeable_keys,
)
from karpenter_tpu.scheduling.priority import (
    SYSTEM_CLASSES,
    resolve_pod_priorities,
    resolve_priority,
)
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def _env(types=None, limits=None, consolidate="Never"):
    env = Environment(
        types=types or [make_instance_type("c4", cpu=4, memory=16 * GIB)]
    )
    pool = mk_nodepool("default", limits=limits or {})
    pool.spec.disruption.consolidate_after = consolidate
    env.kube.create(pool)
    return env, pool


class TestPriorityResolution:
    def test_class_name_resolves_value(self):
        env, _ = _env()
        env.kube.create(PriorityClass(
            metadata=ObjectMeta(name="critical", namespace=""), value=5000
        ))
        pod = mk_pod(name="p")
        pod.spec.priority_class_name = "critical"
        env.kube.create(pod)
        resolve_pod_priorities([pod], env.kube)
        assert pod.spec.priority == 5000

    def test_explicit_priority_wins_over_class(self):
        env, _ = _env()
        env.kube.create(PriorityClass(
            metadata=ObjectMeta(name="critical", namespace=""), value=5000
        ))
        pod = mk_pod(name="p")
        pod.spec.priority = 7
        pod.spec.priority_class_name = "critical"
        resolve_pod_priorities([pod], env.kube)
        assert pod.spec.priority == 7

    def test_global_default_applies_without_class_name(self):
        env, _ = _env()
        env.kube.create(PriorityClass(
            metadata=ObjectMeta(name="dft", namespace=""), value=42,
            global_default=True,
        ))
        pod = mk_pod(name="p")
        resolve_pod_priorities([pod], env.kube)
        assert pod.spec.priority == 42

    def test_dangling_class_name_resolves_to_zero(self):
        env, _ = _env()
        pod = mk_pod(name="p")
        pod.spec.priority_class_name = "nonexistent"
        resolve_pod_priorities([pod], env.kube)
        assert pod.spec.priority == 0

    def test_system_classes_known_without_objects(self):
        pod = mk_pod(name="p")
        pod.spec.priority_class_name = "system-cluster-critical"
        assert resolve_priority(pod, {}) == SYSTEM_CLASSES[
            "system-cluster-critical"
        ]

    def test_mixed_priorities_detector(self):
        a, b = mk_pod(name="a"), mk_pod(name="b")
        assert not mixed_priorities([a, b])
        b.spec.priority = 1
        assert mixed_priorities([a, b])

    def test_round_trips_through_cr(self):
        from karpenter_tpu.kube.serialize import from_cr, to_cr

        pc = PriorityClass(
            metadata=ObjectMeta(name="gold", namespace=""), value=900,
            global_default=True, preemption_policy="Never",
        )
        back = from_cr(to_cr(pc))
        assert back.value == 900
        assert back.global_default is True
        assert back.preemption_policy == "Never"


class TestAdmissionOrder:
    def test_priority_major_then_ffd(self):
        big_low = mk_pod(name="big-low", cpu=3.0)
        small_high = mk_pod(name="small-high", cpu=0.5)
        small_high.spec.priority = 10
        order = admission_order([big_low, small_high])
        assert [p.metadata.name for p in order] == [
            "small-high", "big-low"
        ]

    def test_uniform_priority_keeps_ffd_order(self):
        big = mk_pod(name="big", cpu=3.0)
        small = mk_pod(name="small", cpu=0.5)
        order = admission_order([small, big])
        assert [p.metadata.name for p in order] == ["big", "small"]


class TestAdmissionContract:
    def test_high_priority_survives_pool_limit_overload(self):
        env, _ = _env(limits={"cpu": 8.0})  # two c4 nodes max
        pods = []
        for i in range(4):
            p = mk_pod(name=f"hi-{i}", cpu=1.5)
            p.spec.priority = 1000
            pods.append(p)
        for i in range(6):
            pods.append(mk_pod(name=f"lo-{i}", cpu=1.5))
        results = env.provision(*pods, now=0.0)
        shed = {k for k, e in results.errors.items()
                if e == PRIORITY_SHED_ERROR}
        assert shed == {f"default/lo-{i}" for i in range(6)}
        bound = {p.metadata.name for p in env.kube.pods()
                 if p.spec.node_name}
        assert bound == {f"hi-{i}" for i in range(4)}

    def test_uniform_priority_is_untouched(self):
        """Every-pod-priority-0 rounds keep the pre-priority behavior:
        no shed errors, plain limit rejection."""
        env, _ = _env(limits={"cpu": 4.0})
        pods = [mk_pod(name=f"p-{i}", cpu=1.5) for i in range(5)]
        results = env.provision(*pods, now=0.0)
        assert not any(
            e == PRIORITY_SHED_ERROR for e in results.errors.values()
        )

    def test_unplaceable_pod_never_drags_the_tail(self):
        """A high-priority pod no machine can hold keeps its own error;
        lower-priority placeable pods still schedule."""
        env, _ = _env()
        giant = mk_pod(name="giant", cpu=64.0)
        giant.spec.priority = 10_000
        low = mk_pod(name="low", cpu=1.0)
        results = env.provision(giant, low, now=0.0)
        assert results.errors.get("default/giant") not in (
            None, PRIORITY_SHED_ERROR
        )
        assert env.kube.get_pod("default", "low").spec.node_name

    def test_placeable_keys_respects_fit(self):
        pool = mk_nodepool("default")
        types = [make_instance_type("c4", cpu=4, memory=16 * GIB)]
        fits = mk_pod(name="fits", cpu=1.0)
        giant = mk_pod(name="giant", cpu=64.0)
        keys = placeable_keys([fits, giant], [(pool, types)])
        assert keys == {"default/fits"}


@pytest.mark.parametrize("seed", [3, 11, 29, 57])
class TestAdmissionOracle:
    """Fuzzed pool limits × catalogs × priorities: the unscheduled set
    is exactly the lowest-priority tail of the admission order."""

    def test_unscheduled_set_is_the_lowest_priority_tail(self, seed):
        rng = random.Random(seed)
        n_types = rng.choice([1, 2])
        types = [
            make_instance_type(
                f"c{4 * (i + 1)}", cpu=4.0 * (i + 1),
                memory=16 * (i + 1) * GIB, price=1.0 + i,
            )
            for i in range(n_types)
        ]
        # limit forces overload: room for roughly half the demand
        limit_cpu = rng.choice([4.0, 8.0, 12.0])
        env, _ = _env(types=types, limits={"cpu": limit_cpu})
        pods = []
        for i in range(rng.randint(8, 16)):
            p = mk_pod(
                name=f"p-{i}",
                cpu=rng.choice([0.5, 1.0, 1.5]),
                memory=2 * GIB,
            )
            p.spec.priority = rng.choice([0, 10, 100, 1000])
            pods.append(p)
        results = env.provision(*pods, now=0.0)

        order = admission_order(pods)
        keys = [p.key for p in order]
        unscheduled = {
            p.key for p in pods
            if not env.kube.get_pod(*p.key.split("/", 1)).spec.node_name
        }
        # every unscheduled pod must carry an error
        assert unscheduled == set(results.errors), (
            results.errors, unscheduled,
        )
        # the unscheduled set is a SUFFIX of the admission order
        if unscheduled:
            cut = min(keys.index(k) for k in unscheduled)
            assert set(keys[cut:]) == unscheduled, (
                f"seed {seed}: unscheduled not a tail "
                f"(cut {cut}): {sorted(unscheduled)} vs "
                f"{keys[cut:]}"
            )
            # and therefore: no pod outranks a scheduled one while
            # itself starving
            max_unsched = max(
                p.spec.priority for p in pods if p.key in unscheduled
            )
            min_sched = min(
                (p.spec.priority for p in pods
                 if p.key not in unscheduled),
                default=max_unsched,
            )
            assert max_unsched <= min_sched


class TestDisruptionPriorityVeto:
    def test_sim_vetoes_when_higher_priority_pending_starves(self):
        """A consolidation-style simulation must fail when a pending
        pod of strictly higher priority than the displaced pods stays
        capacity-unschedulable."""
        env, pool = _env(limits={"cpu": 4.0})
        low = mk_pod(name="low", cpu=1.0)
        env.provision(low, now=0.0)
        # a higher-priority pod arrives; the pool limit blocks growth
        high = mk_pod(name="high", cpu=3.9)
        high.spec.priority = 1000
        env.kube.create(high)
        state = env.cluster.nodes()[0]
        from karpenter_tpu.disruption.engine import Candidate

        candidate = Candidate(
            state_node=state, node_pool=pool,
            reschedulable_pods=[
                env.kube.get_pod("default", "low")
            ],
            instance_type_name="c4", capacity_type="on-demand",
            zone="zone-a", price=1.0, disruption_cost=1.0,
        )
        _, ok = env.disruption.simulate_scheduling([candidate])
        assert not ok

    def test_sim_unaffected_at_uniform_priority(self):
        env, pool = _env(limits={"cpu": 4.0})
        low = mk_pod(name="low", cpu=1.0)
        env.provision(low, now=0.0)
        pending = mk_pod(name="pending", cpu=3.9)  # priority 0, like low
        env.kube.create(pending)
        state = env.cluster.nodes()[0]
        from karpenter_tpu.disruption.engine import Candidate

        candidate = Candidate(
            state_node=state, node_pool=pool,
            reschedulable_pods=[env.kube.get_pod("default", "low")],
            instance_type_name="c4", capacity_type="on-demand",
            zone="zone-a", price=1.0, disruption_cost=1.0,
        )
        results, ok = env.disruption.simulate_scheduling([candidate])
        # the displaced pod itself still schedules; the equal-priority
        # pending pod's starvation does not veto
        assert ok


class TestIncrementalPriorityGate:
    def test_priority_bearing_tick_is_eligible(self):
        """ISSUE 15 widened the envelope: a priority-bearing tick
        rides the incremental path (priority-major grouping is
        inherited from group_pods); only a mixed-priority tick with a
        capacity failure — where the admission machinery would act —
        falls back (see _priority_overloaded)."""
        env, _ = _env()
        pod = mk_pod(name="p", cpu=1.0)
        pod.spec.priority = 10
        env.kube.create(pod)
        reason = env.provisioner.incremental._ineligible(
            [pod], env.provisioner.ready_pools_with_types()
        )
        assert reason is None

    def test_mixed_priority_capacity_failure_falls_back(self):
        """The overload gate: mixed priorities + a no-capacity error
        is exactly where the shed/cutoff machinery acts, and it wraps
        only full-path results."""
        from karpenter_tpu.provisioning.scheduler import (
            NO_CAPACITY_ERROR,
            SchedulerResults,
        )

        env, _ = _env()
        tick = env.provisioner.incremental
        hi = mk_pod(name="hi", cpu=1.0)
        hi.spec.priority = 10
        lo = mk_pod(name="lo", cpu=1.0)
        clean = SchedulerResults(new_node_plans=[],
                                 existing_assignments={})
        assert not tick._priority_overloaded([hi, lo], clean)
        failed = SchedulerResults(
            new_node_plans=[], existing_assignments={},
            errors={"default/lo": NO_CAPACITY_ERROR},
        )
        assert tick._priority_overloaded([hi, lo], failed)
        # uniform priority never engages admission, failure or not
        assert not tick._priority_overloaded([lo], failed)
