"""Static solve-seam check (ISSUE-7 satellite, pattern of
test_kube_write_sites): every controller-layer solve must route
through the audited pipeline seam — `provisioning/scheduler.py`
(the full Scheduler) or `provisioning/incremental_tick.py` (the
retained-state live tick with its oracle audit). A controller calling
`solver.solve` / `solve_encoded` / `_solve_packing` directly would
silently bypass the incremental tick's audit + backstop coverage, the
scheduler's metrics, AND the resilience ladder's degradation report;
this tier-1 test makes that a failing build instead of an unaudited
fleet decision.
"""

import ast
import pathlib

PKG = pathlib.Path(__file__).resolve().parent.parent / "karpenter_tpu"

# controller layers: everything that DECIDES fleet shape from cluster
# state (the solver package itself, the service codecs, and the bench
# are solver-internal surfaces, not controllers)
CONTROLLER_DIRS = (
    "provisioning", "disruption", "operator", "lifecycle", "state",
    "metrics", "events",
)

# the audited seam: the only controller-layer modules allowed to reach
# the raw solve entry points
SEAM = {
    ("provisioning", "scheduler.py"),
    ("provisioning", "incremental_tick.py"),
}

SOLVE_ENTRY_NAMES = {
    "solve", "solve_encoded", "_solve_packing", "_solve_packing_async",
}


def _controller_files():
    for dirname in CONTROLLER_DIRS:
        for path in sorted((PKG / dirname).rglob("*.py")):
            yield dirname, path


def _solver_solve_imports(tree):
    """Names imported from karpenter_tpu.solver.solver that are solve
    entry points (importing types like NodePlan stays legal)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.endswith("solver.solver")
        ):
            for alias in node.names:
                if alias.name in SOLVE_ENTRY_NAMES:
                    out.append((node.lineno, alias.name))
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("solver.solver"):
                    out.append((node.lineno, alias.name))
    return out


def _solve_attribute_calls(tree):
    """Calls of the shape `<anything>.solve_encoded(...)` or
    `<anything>._solve_packing[_async](...)` — reaching the kernel
    seam through a module attribute instead of an import."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in (
            "solve_encoded", "_solve_packing", "_solve_packing_async"
        ):
            out.append((node.lineno, func.attr))
    return out


def test_no_controller_bypasses_the_solve_seam():
    offenders = []
    for dirname, path in _controller_files():
        if (dirname, path.name) in SEAM:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for lineno, name in _solver_solve_imports(tree):
            offenders.append(
                f"{path.relative_to(PKG.parent)}:{lineno} imports {name}"
            )
        for lineno, name in _solve_attribute_calls(tree):
            offenders.append(
                f"{path.relative_to(PKG.parent)}:{lineno} calls {name}"
            )
    assert not offenders, (
        "controller-layer solves bypassing the audited Scheduler/"
        f"incremental-tick seam: {offenders}"
    )


def test_provisioner_routes_through_the_incremental_seam():
    """The live reconcile's structure is pinned: Provisioner.schedule
    must consult the incremental tick first and fall back through
    _make_scheduler — not construct a Scheduler ad hoc elsewhere."""
    source = (PKG / "provisioning" / "provisioner.py").read_text()
    tree = ast.parse(source, filename="provisioning/provisioner.py")
    prov = next(
        node for node in tree.body
        if isinstance(node, ast.ClassDef) and node.name == "Provisioner"
    )
    scheduler_ctors = []
    tick_calls = []
    for node in ast.walk(prov):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and node.func.id == "Scheduler":
                scheduler_ctors.append(node.lineno)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "tick"
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == "incremental"
            ):
                tick_calls.append(node.lineno)
    methods = {
        m.name: m for m in prov.body if isinstance(m, ast.FunctionDef)
    }
    assert tick_calls, "Provisioner.schedule must route through " \
                       "self.incremental.tick"
    ctor_owners = set()
    for lineno in scheduler_ctors:
        for name, m in methods.items():
            if m.lineno <= lineno <= max(
                getattr(m, "end_lineno", m.lineno), m.lineno
            ):
                ctor_owners.add(name)
    assert ctor_owners <= {"_make_scheduler"}, (
        "full-path Scheduler construction must live in _make_scheduler "
        f"(the seam the oracle audit shares), found in: {ctor_owners}"
    )


def test_disruption_engine_routes_through_scheduler_only():
    """The engine simulates through Scheduler (and the batched probe
    solver, which wraps it) — never through raw solver entry points."""
    for fname in ("engine.py", "validation.py", "interruption.py"):
        tree = ast.parse(
            (PKG / "disruption" / fname).read_text(), filename=fname
        )
        assert not _solver_solve_imports(tree), fname
        assert not _solve_attribute_calls(tree), fname
