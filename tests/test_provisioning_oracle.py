"""Provisioning oracle suite, ported from the reference's
provisioning/suite_test.go property families: resource limits,
daemonset overhead accounting, batcher windows, claim creation
(requirement tightening, label/annotation propagation, TGP),
deleting/invalid nodepools, weighted fallthrough.
"""

import time

from karpenter_tpu.apis.v1.labels import NODEPOOL_LABEL
from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.kube.objects import (
    Affinity,
    DaemonSet,
    DaemonSetSpec,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    PodTemplateSpec,
    Taint,
    Toleration,
)
from karpenter_tpu.provisioning.provisioner import Batcher
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def types():
    return [
        make_instance_type("c4", cpu=4, memory=16 * GIB, price=1.0),
        make_instance_type("c16", cpu=16, memory=64 * GIB, price=4.0),
        make_instance_type(
            "gpu8", cpu=8, memory=32 * GIB, price=10.0,
            extra_resources={"example.com/gpu": 4.0},
        ),
    ]


def mk_daemonset(name="ds", cpu=0.5, memory=GIB, tolerations=None,
                 node_selector=None, affinity=None, labels=None):
    from karpenter_tpu.kube.objects import Container, PodSpec

    return DaemonSet(
        metadata=ObjectMeta(name=name),
        spec=DaemonSetSpec(
            template=PodTemplateSpec(
                metadata=ObjectMeta(name=f"{name}-pod", labels=labels or {}),
                spec=PodSpec(
                    containers=[
                        Container(requests={"cpu": cpu, "memory": memory})
                    ],
                    tolerations=tolerations or [],
                    node_selector=node_selector or {},
                    affinity=affinity,
                ),
            )
        ),
    )


class TestResourceLimits:
    def test_not_schedule_when_limits_exceeded(self):
        # suite_test.go:741: committed capacity already exceeds the
        # limit and no existing node has room -> creation blocked
        env = Environment(types=[types()[0]])  # c4 only: 1 pod per node
        pool = mk_nodepool("p")
        pool.spec.limits = {"cpu": 20.0}
        env.kube.create(pool)
        env.provision(*[mk_pod(cpu=3.5) for _ in range(5)])  # 5x4 = 20 cpu
        before = len(env.kube.node_claims())
        results = env.provision(mk_pod(name="over", cpu=3.5), bind=False)
        assert len(env.kube.node_claims()) == before
        assert "default/over" in results.errors

    def test_schedule_if_limits_would_be_met(self):
        # suite_test.go:764
        env = Environment(types=types())
        pool = mk_nodepool("p")
        pool.spec.limits = {"cpu": 50.0}
        env.kube.create(pool)
        env.provision(mk_pod(cpu=3.0))
        assert len(env.kube.node_claims()) == 1

    def test_gpu_limits(self):
        # suite_test.go:846: extended-resource limits block too
        env = Environment(types=types())
        pool = mk_nodepool("p")
        pool.spec.limits = {"example.com/gpu": 4.0}
        env.kube.create(pool)
        gpu_pod = mk_pod(name="g1", cpu=1.0)
        gpu_pod.spec.containers[0].requests["example.com/gpu"] = 4.0
        env.provision(gpu_pod)
        assert len(env.kube.node_claims()) == 1
        gpu_pod2 = mk_pod(name="g2", cpu=1.0)
        gpu_pod2.spec.containers[0].requests["example.com/gpu"] = 2.0
        results = env.provision(gpu_pod2, bind=False)
        assert len(env.kube.node_claims()) == 1
        assert "default/g2" in results.errors

    def test_limits_hold_across_rounds(self):
        # suite_test.go:862: the second round sees the first round's usage
        env = Environment(types=types())
        pool = mk_nodepool("p")
        pool.spec.limits = {"cpu": 5.0}
        env.kube.create(pool)
        env.provision(mk_pod(cpu=3.0))
        claims_1 = len(env.kube.node_claims())
        env.provision(mk_pod(name="second", cpu=3.0), bind=False)
        assert len(env.kube.node_claims()) == claims_1


class TestDaemonSets:
    def test_overhead_reserved_on_fresh_nodes(self):
        # suite_test.go:892
        env = Environment(types=[types()[0]])  # only c4
        env.kube.create(mk_nodepool("p"))
        env.kube.create(mk_daemonset(cpu=2.0))
        env.provision(*[mk_pod(name=f"w-{i}", cpu=1.5) for i in range(2)])
        # 2x1.5 + 2.0 daemon = 5 cpu > one c4: two nodes needed
        assert len(env.kube.node_claims()) == 2

    def test_too_large_daemonset_blocks(self):
        # suite_test.go:961: overhead alone exceeds every type
        env = Environment(types=[types()[0]])
        env.kube.create(mk_nodepool("p"))
        env.kube.create(mk_daemonset(cpu=100.0))
        results = env.provision(mk_pod(name="w", cpu=0.5), bind=False)
        assert not env.kube.node_claims()
        assert "default/w" in results.errors

    def test_non_tolerating_daemonset_ignored(self):
        # suite_test.go:1100: pool taint the daemonset does not tolerate
        env = Environment(types=[types()[0]])
        pool = mk_nodepool("p")
        pool.spec.template.spec.taints = [
            Taint(key="example.com/team", value="a", effect="NoSchedule")
        ]
        env.kube.create(pool)
        env.kube.create(mk_daemonset(cpu=3.0))  # would not fit alongside
        pod = mk_pod(cpu=3.0)
        pod.spec.tolerations = [
            Toleration(key="example.com/team", operator="Equal", value="a",
                       effect="NoSchedule")
        ]
        env.provision(pod)
        # daemonset ignored: one c4 holds the 3-cpu pod
        assert len(env.kube.node_claims()) == 1

    def test_tolerating_daemonset_counted(self):
        env = Environment(types=[types()[0]])
        pool = mk_nodepool("p")
        pool.spec.template.spec.taints = [
            Taint(key="example.com/team", value="a", effect="NoSchedule")
        ]
        env.kube.create(pool)
        env.kube.create(mk_daemonset(
            cpu=2.0,
            tolerations=[Toleration(key="example.com/team", operator="Equal",
                                    value="a", effect="NoSchedule")],
        ))
        pod = mk_pod(cpu=3.0)
        pod.spec.tolerations = [
            Toleration(key="example.com/team", operator="Equal", value="a",
                       effect="NoSchedule")
        ]
        results = env.provision(pod, bind=False)
        # 3 + 2 daemon > c4's ~3.9 allocatable: unschedulable on c4-only
        assert not results.new_node_plans or "default/" in next(
            iter(results.errors), "default/"
        )

    def test_daemonset_with_incompatible_selector_ignored(self):
        # suite_test.go:1177-1337 family: a daemonset whose node
        # affinity can never match the pool contributes no overhead
        env = Environment(types=[types()[0]])
        env.kube.create(mk_nodepool("p"))
        env.kube.create(mk_daemonset(
            cpu=3.0, node_selector={"example.com/region": "mars"}
        ))
        env.provision(mk_pod(cpu=3.0))
        assert len(env.kube.node_claims()) == 1

    def test_daemonset_or_terms_any_match_counts(self):
        """suite_test.go:1249: required node-affinity terms are ORed —
        a daemonset whose FIRST term can never match the pool but
        whose second can is schedulable, so its overhead counts."""
        env = Environment(types=[types()[0]])
        env.kube.create(mk_nodepool("p"))
        affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred=(),
                required=(
                    NodeSelectorTerm(match_expressions=(
                        NodeSelectorRequirement(
                            "kubernetes.io/os", "In", ("windows",)
                        ),
                    )),
                    NodeSelectorTerm(match_expressions=(
                        NodeSelectorRequirement(
                            "kubernetes.io/os", "In", ("linux",)
                        ),
                    )),
                ),
            )
        )
        env.kube.create(mk_daemonset(cpu=2.0, affinity=affinity))
        results = env.provision(mk_pod(cpu=3.0), bind=False)
        # 3 + 2 daemon > c4's allocatable: the overhead MUST count,
        # leaving the pod unschedulable on a c4-only catalog
        assert results.errors

    def test_daemonset_hostname_pin_ignored_for_new_capacity(self):
        """suite_test.go:1177: a daemonset pinned to an EXISTING
        node's hostname says nothing about new capacity — the
        hostname term is dropped before the schedulability check, so
        the overhead still counts on fresh nodes."""
        env = Environment(types=[types()[0]])
        env.kube.create(mk_nodepool("p"))
        affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred=(),
                required=(
                    NodeSelectorTerm(match_expressions=(
                        NodeSelectorRequirement(
                            "kubernetes.io/hostname", "In", ("node-x",)
                        ),
                    )),
                ),
            )
        )
        env.kube.create(mk_daemonset(cpu=2.0, affinity=affinity))
        results = env.provision(mk_pod(cpu=3.0), bind=False)
        assert results.errors  # overhead counted despite the pin

    def test_daemonset_notin_unspecified_key_counts(self):
        """suite_test.go:1154: NotIn over a key the template leaves
        undefined is satisfiable — the daemonset schedules, so its
        overhead counts."""
        env = Environment(types=[types()[0]])
        env.kube.create(mk_nodepool("p"))
        affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred=(),
                required=(
                    NodeSelectorTerm(match_expressions=(
                        NodeSelectorRequirement(
                            "example.com/lane", "NotIn", ("slow",)
                        ),
                    )),
                ),
            )
        )
        env.kube.create(mk_daemonset(cpu=2.0, affinity=affinity))
        results = env.provision(mk_pod(cpu=3.0), bind=False)
        assert results.errors

    def test_daemonset_prefer_no_schedule_taint_counts(self):
        """suite_test.go:1337: a PreferNoSchedule pool taint never
        blocks a daemonset, so the overhead counts untolerated."""
        env = Environment(types=[types()[0]])
        pool = mk_nodepool("p")
        pool.spec.template.spec.taints = [
            Taint(key="example.com/soft", value="x",
                  effect="PreferNoSchedule")
        ]
        env.kube.create(pool)
        env.kube.create(mk_daemonset(cpu=2.0))
        pod = mk_pod(cpu=3.0)
        results = env.provision(pod, bind=False)
        assert results.errors

    def test_daemonset_preference_does_not_block(self):
        # suite_test.go:1309: an incompatible PREFERENCE still leaves
        # the daemonset schedulable -> overhead counted
        env = Environment(types=[types()[0]])
        env.kube.create(mk_nodepool("p"))
        affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred=(),
                required=(
                    NodeSelectorTerm(
                        match_expressions=(
                            NodeSelectorRequirement(
                                "kubernetes.io/os", "In", ("linux",)
                            ),
                        )
                    ),
                ),
            )
        )
        env.kube.create(mk_daemonset(cpu=2.0, affinity=affinity))
        env.provision(*[mk_pod(name=f"w-{i}", cpu=1.5) for i in range(2)])
        assert len(env.kube.node_claims()) == 2


def _tgp_types():
    return [make_instance_type("c4", cpu=4)]


class TestTerminationGracePeriodDefaulting:
    """provisioning/suite_test.go:244-279 — claim TGP resolution:
    pool value > global runtime default > nil."""

    def _tgp(self, env):
        env.provision(mk_pod())
        return env.kube.node_claims()[0].spec.termination_grace_period

    def test_global_default_used_when_pool_unset(self):
        from karpenter_tpu.provisioning import provisioner as prov_mod

        env = Environment(types=_tgp_types())
        env.kube.create(mk_nodepool("default"))
        prov_mod.DEFAULT_TERMINATION_GRACE_PERIOD = 98 * 3600.0
        try:
            assert self._tgp(env) == 98 * 3600.0
        finally:
            prov_mod.DEFAULT_TERMINATION_GRACE_PERIOD = None

    def test_nil_when_neither_set(self):
        env = Environment(types=_tgp_types())
        env.kube.create(mk_nodepool("default"))
        assert self._tgp(env) is None

    def test_pool_value_wins_over_global(self):
        from karpenter_tpu.provisioning import provisioner as prov_mod

        env = Environment(types=_tgp_types())
        pool = mk_nodepool("default")
        pool.spec.template.spec.termination_grace_period = 60.0
        env.kube.create(pool)
        prov_mod.DEFAULT_TERMINATION_GRACE_PERIOD = 98 * 3600.0
        try:
            assert self._tgp(env) == 60.0
        finally:
            prov_mod.DEFAULT_TERMINATION_GRACE_PERIOD = None


class TestBatcher:
    def test_idle_window_fires(self):
        # suite_test.go:118
        b = Batcher(idle_seconds=1.0, max_seconds=10.0)
        b.trigger(now=100.0)
        assert not b.ready(now=100.5)
        assert b.ready(now=101.1)

    def test_new_pod_extends_window(self):
        # suite_test.go:174
        b = Batcher(idle_seconds=1.0, max_seconds=10.0)
        b.trigger(now=100.0)
        b.trigger(now=100.8)
        assert not b.ready(now=101.5)  # idle restarted at 100.8
        assert b.ready(now=101.9)

    def test_max_window_caps_extension(self):
        b = Batcher(idle_seconds=1.0, max_seconds=10.0)
        b.trigger(now=100.0)
        for i in range(20):
            b.trigger(now=100.0 + 0.6 * i)  # continuous arrivals
        assert b.ready(now=110.1)  # max window forces the flush


class TestClaimCreation:
    def test_deleting_nodepool_ignored(self):
        # suite_test.go:280
        env = Environment(types=types())
        pool = mk_nodepool("p")
        pool.metadata.finalizers = ["keep"]
        env.kube.create(pool)
        env.kube.delete(pool)
        results = env.provision(mk_pod(name="w", cpu=1.0), bind=False)
        assert not env.kube.node_claims()
        assert "default/w" in results.errors

    def test_no_nodepools_unschedulable(self):
        # suite_test.go:291
        env = Environment(types=types())
        results = env.provision(mk_pod(name="w", cpu=1.0), bind=False)
        assert "default/w" in results.errors

    def test_claim_carries_template_metadata_and_tgp(self):
        # suite_test.go:267,1376,1394: labels/annotations/TGP propagate
        env = Environment(types=types())
        pool = mk_nodepool("p")
        pool.spec.template.labels = {"example.com/tier": "gold"}
        pool.spec.template.annotations = {"example.com/note": "hi"}
        pool.spec.template.spec.termination_grace_period = "30m"
        env.kube.create(pool)
        env.provision(mk_pod(cpu=1.0))
        claim = env.kube.node_claims()[0]
        assert claim.metadata.labels["example.com/tier"] == "gold"
        assert claim.metadata.annotations["example.com/note"] == "hi"
        assert claim.spec.termination_grace_period == "30m"
        node = env.kube.nodes()[0]
        assert node.metadata.labels["example.com/tier"] == "gold"

    def test_claim_requirements_tightened_to_solution(self):
        # suite_test.go:1522: instance-type requirement reflects the
        # solved set, not the whole catalog
        env = Environment(types=types())
        env.kube.create(mk_nodepool("p"))
        env.provision(mk_pod(cpu=1.0))
        claim = env.kube.node_claims()[0]
        type_req = next(
            r for r in claim.spec.requirements
            if r.key == "node.kubernetes.io/instance-type"
        )
        assert set(type_req.values) <= {"c4", "c16", "gpu8"}
        zone_req = next(
            r for r in claim.spec.requirements
            if r.key == "topology.kubernetes.io/zone"
        )
        assert zone_req.values  # solved zones recorded


class TestWeightedFallthrough:
    def test_higher_weight_pool_wins_when_feasible(self):
        # suite_test.go:2623
        env = Environment(types=types())
        low = mk_nodepool("low")
        high = mk_nodepool("high")
        high.spec.weight = 50
        env.kube.create(low)
        env.kube.create(high)
        env.provision(mk_pod(cpu=1.0))
        claim = env.kube.node_claims()[0]
        assert claim.metadata.labels[NODEPOOL_LABEL] == "high"

    def test_falls_through_when_high_weight_cannot_fit(self):
        env = Environment(types=types())
        low = mk_nodepool("low")
        high = mk_nodepool("high")
        high.spec.weight = 50
        high.spec.template.spec.requirements = [
            RequirementSpec(key="kubernetes.io/arch", operator="In",
                            values=("arm64",))
        ]
        env.kube.create(low)
        env.kube.create(high)
        pod = mk_pod(cpu=1.0, node_selector={"kubernetes.io/arch": "amd64"})
        env.provision(pod)
        claim = env.kube.node_claims()[0]
        assert claim.metadata.labels[NODEPOOL_LABEL] == "low"


class TestPoolPinnedDaemonSet:
    def test_daemonset_pinned_to_other_pool_not_budgeted(self):
        # a daemonset nodeSelector-pinned to pool-a must not inflate
        # pool-b's overhead (NewNodeClaimTemplate includes the nodepool
        # pin in the template requirements)
        env = Environment(types=[types()[0]])
        env.kube.create(mk_nodepool("pool-a"))
        env.kube.create(mk_nodepool("pool-b"))
        env.kube.create(mk_daemonset(
            cpu=3.0, node_selector={NODEPOOL_LABEL: "pool-a"}
        ))
        pod = mk_pod(cpu=3.0, node_selector={NODEPOOL_LABEL: "pool-b"})
        env.provision(pod)
        claims = env.kube.node_claims()
        assert len(claims) == 1
        assert claims[0].metadata.labels[NODEPOOL_LABEL] == "pool-b"
