"""Static-pool drift/deprovisioning, forced expiration, mid-TTL
validation races, reserved-offering consolidation, preference and
minValues interactions, and disruption metrics.

Ports uncovered families from
/root/reference/pkg/controllers/disruption/{staticdrift_test.go,
validation_test.go,consolidation_test.go} and
nodeclaim/expiration/controller.go.
"""

import time

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    DO_NOT_DISRUPT_ANNOTATION,
    INSTANCE_TYPE_LABEL,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_DRIFTED,
    COND_INITIALIZED,
)
from karpenter_tpu.apis.v1.nodepool import Budget, REASON_UNDERUTILIZED
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.kube.objects import (
    LabelSelector,
    ObjectMeta,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
)
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def _types():
    return [
        make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0),
        make_instance_type("c4", cpu=4, memory=16 * GIB, price=3.0),
    ]


class TestStaticPoolDeep:
    def _static_env(self, replicas=2):
        from karpenter_tpu.operator.options import FeatureGates, Options

        env = Environment(types=_types(), options=Options(
            feature_gates=FeatureGates(static_capacity=True),
        ))
        pool = mk_nodepool("static")
        pool.spec.replicas = replicas
        env.kube.create(pool)
        now = time.time()
        for _ in range(6):
            env.static.reconcile_all(now=now)
            env.lifecycle.reconcile_all(now=now)
            env.cloud.tick(now=now)
            env.lifecycle.reconcile_all(now=now)
            now += 2
        assert len(env.kube.node_claims()) == replicas
        return env, now

    def test_static_pool_excluded_from_consolidation(self):
        # consolidation_test.go "should not consolidate static
        # NodePool nodes"
        env, now = self._static_env(2)
        env.pod_events.reconcile_all(now=now + 120)
        env.conditions.reconcile_all(now=now + 120)
        assert env.disruption.get_candidates(
            REASON_UNDERUTILIZED, now + 121
        ) == []

    def test_static_drift_rolls_replacement_first(self):
        # staticdrift.go:50-116: the replacement launches BEFORE the
        # drifted claim is removed; replica count never dips
        env, now = self._static_env(2)
        claim = env.kube.node_claims()[0]
        claim.status_conditions.set_true(COND_DRIFTED, now=now)
        env.static.reconcile_all(now=now)
        # replacement launched: 3 claims during the roll
        assert len(env.kube.node_claims()) == 3
        # drive to convergence: replacement initializes, drifted leaves
        for _ in range(10):
            env.static.reconcile_all(now=now)
            env.lifecycle.reconcile_all(now=now)
            env.cloud.tick(now=now)
            env.lifecycle.reconcile_all(now=now)
            env.reconcile_termination(now=now)
            now += 5
        live = [c for c in env.kube.node_claims()
                if c.metadata.deletion_timestamp is None]
        assert len(live) == 2
        assert all(
            not c.status_conditions.is_true(COND_DRIFTED) for c in live
        )

    def test_static_drift_rolls_one_at_a_time(self):
        # budget 1 (default allowed disruptions): with every claim
        # drifted, the roll proceeds stepwise, never all at once
        env, now = self._static_env(3)
        for claim in env.kube.node_claims():
            claim.status_conditions.set_true(COND_DRIFTED, now=now)
        env.static.reconcile_all(now=now)
        fresh = [c for c in env.kube.node_claims()
                 if not c.status_conditions.is_true(COND_DRIFTED)]
        assert len(fresh) == 1  # one replacement in flight

    def test_static_scale_down_prefers_drifted(self):
        env, now = self._static_env(3)
        drifted = env.kube.node_claims()[1]
        drifted.status_conditions.set_true(COND_DRIFTED, now=now)
        pool = env.kube.get_node_pool("static")
        pool.spec.replicas = 2
        env.kube.touch(pool)
        env.static.reconcile_all(now=now)
        gone = [c for c in env.kube.node_claims()
                if c.metadata.deletion_timestamp is not None]
        assert [c.metadata.name for c in gone] == [drifted.metadata.name]

    def test_static_scale_down_prefers_low_disruption_cost(self):
        env, now = self._static_env(2)
        claims = env.kube.node_claims()
        # put an expensive-to-disrupt pod on claim 0's node
        node_name = claims[0].status.node_name
        pod = mk_pod(cpu=0.2)
        pod.spec.priority = 100000
        env.kube.create(pod)
        env.kube.bind_pod(
            env.kube.get_pod("default", pod.metadata.name), node_name
        )
        pool = env.kube.get_node_pool("static")
        pool.spec.replicas = 1
        env.kube.touch(pool)
        env.static.reconcile_all(now=now)
        gone = [c for c in env.kube.node_claims()
                if c.metadata.deletion_timestamp is not None]
        assert [c.metadata.name for c in gone] == [claims[1].metadata.name]


class TestForcedExpiration:
    def _env(self, expire_after="1h"):
        env = Environment(types=_types())
        pool = mk_nodepool("default")
        pool.spec.disruption.consolidate_after = "0s"
        pool.spec.template.spec.expire_after = expire_after
        env.kube.create(pool)
        return env

    def test_claim_expires_at_lifetime(self):
        env = self._env("1h")
        env.provision(mk_pod(cpu=0.5))
        claim = env.kube.node_claims()[0]
        base = claim.metadata.creation_timestamp
        env.expiration.reconcile_all(now=base + 3599)
        assert claim.metadata.deletion_timestamp is None
        env.expiration.reconcile_all(now=base + 3601)
        assert claim.metadata.deletion_timestamp is not None

    def test_expiration_is_forceful_ignores_pdbs(self):
        # expiration is FORCEFUL (nodeclaim/expiration/controller.go:
        # 57-64 — no budget, no PDB consult on the delete itself; the
        # drain that follows still honors them via TGP)
        env = self._env("1h")
        env.provision(mk_pod(cpu=0.5, labels={"app": "web"}))
        env.kube.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb"),
            spec=PodDisruptionBudgetSpec(
                selector=LabelSelector.of({"app": "web"}),
                max_unavailable=0,
            ),
        ))
        claim = env.kube.node_claims()[0]
        base = claim.metadata.creation_timestamp
        env.expiration.reconcile_all(now=base + 3601)
        assert claim.metadata.deletion_timestamp is not None

    def test_never_expiring_claim(self):
        env = self._env("Never")
        env.provision(mk_pod(cpu=0.5))
        claim = env.kube.node_claims()[0]
        env.expiration.reconcile_all(
            now=claim.metadata.creation_timestamp + 10 * 365 * 24 * 3600
        )
        assert claim.metadata.deletion_timestamp is None


class TestValidationMidTtlRaces:
    """consolidation_test.go TTL-wait family: between command compute
    and execution, the world changes and validation must catch it."""

    def _replace_command(self, env, now):
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        return env.disruption.reconcile(now=now + 1)

    def _env(self):
        from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec

        env = Environment(types=[
            make_instance_type("c1", cpu=1, memory=4 * GIB, price=1.2),
            make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0),
        ])
        pool = mk_nodepool("default")
        pool.spec.disruption.consolidate_after = "0s"
        # on-demand: a spot candidate would hide the replace behind
        # the 15-type spot-to-spot rule
        pool.spec.template.spec.requirements = [
            RequirementSpec(key=CAPACITY_TYPE_LABEL, operator="In",
                            values=("on-demand",)),
        ]
        env.kube.create(pool)
        env.provision(mk_pod(cpu=0.4,
                             node_selector={INSTANCE_TYPE_LABEL: "c2"}))
        for pod in env.kube.pods():
            pod.spec.node_selector = {}
        return env

    def test_do_not_disrupt_pod_arriving_mid_wait_rolls_back(self):
        # "should not replace node if a pod schedules with
        # karpenter.sh/do-not-disrupt during the TTL wait"
        env = self._env()
        now = time.time() + 120
        command = self._replace_command(env, now)
        assert command is not None
        node_name = env.kube.nodes()[0].metadata.name
        guard = mk_pod(cpu=0.1)
        guard.metadata.annotations[DO_NOT_DISRUPT_ANNOTATION] = "true"
        env.kube.create(guard)
        env.kube.bind_pod(
            env.kube.get_pod("default", guard.metadata.name), node_name
        )
        for i in range(12):
            env.reconcile_disruption(now=now + 11 * (i + 1))
        # the candidate survived: validation saw the guard pod
        assert any(n.metadata.name == node_name for n in env.kube.nodes())

    def test_blocking_pdb_arriving_mid_wait_rolls_back(self):
        # "should not replace node if a pod schedules with a blocking
        # PDB during the TTL wait"
        env = self._env()
        now = time.time() + 120
        command = self._replace_command(env, now)
        assert command is not None
        node_name = env.kube.nodes()[0].metadata.name
        env.kube.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb"),
            spec=PodDisruptionBudgetSpec(
                selector=LabelSelector.of({}), max_unavailable=0,
            ),
        ))
        for i in range(12):
            env.reconcile_disruption(now=now + 11 * (i + 1))
        assert any(n.metadata.name == node_name for n in env.kube.nodes())

    def test_candidate_vanishing_mid_wait_rolls_back(self):
        env = self._env()
        now = time.time() + 120
        command = self._replace_command(env, now)
        assert command is not None
        # the candidate's claim is deleted out from under the command
        claim = command.candidates[0].state_node.node_claim
        env.kube.delete(claim, now=now + 2)
        for i in range(12):
            env.reconcile_disruption(now=now + 11 * (i + 1))
        # no stuck command, fleet converges with the workload bound
        live = [p for p in env.kube.pods() if not p.is_terminal()]
        assert all(p.spec.node_name for p in live)
        assert env.disruption.queue.active == []


class TestReservedConsolidation:
    def test_consolidates_onto_reserved_offering(self):
        # "can consolidate from one reserved offering to another":
        # reserved capacity prices ~0, so moving a workload onto a
        # reservation is always a win
        from karpenter_tpu.operator.options import FeatureGates, Options

        types = [
            make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0),
            make_instance_type(
                "r2", cpu=2, memory=8 * GIB, price=2.0,
                reservations=[("res-1", "test-zone-1", 2)],
            ),
        ]
        env = Environment(types=types, options=Options(
            feature_gates=FeatureGates(reserved_capacity=True,
                                       spot_to_spot_consolidation=True),
        ))
        pool = mk_nodepool("default")
        pool.spec.disruption.consolidate_after = "0s"
        env.kube.create(pool)
        env.provision(mk_pod(
            cpu=0.4,
            node_selector={INSTANCE_TYPE_LABEL: "c2",
                           CAPACITY_TYPE_LABEL: "on-demand"},
        ))
        for pod in env.kube.pods():
            pod.spec.node_selector = {}
        now = time.time() + 120
        for i in range(10):
            env.reconcile_disruption(now=now + 11 * i)
        assert len(env.kube.nodes()) == 1
        node = env.kube.nodes()[0]
        assert node.metadata.labels.get(CAPACITY_TYPE_LABEL) == "reserved"


class TestDisruptionMetrics:
    def test_disrupted_counter_carries_reason_and_pool(self):
        from karpenter_tpu.metrics.store import NODECLAIMS_DISRUPTED

        env = Environment(types=_types())
        pool = mk_nodepool("default")
        pool.spec.disruption.consolidate_after = "0s"
        env.kube.create(pool)
        env.provision(mk_pod(cpu=0.5))
        env.kube.delete(env.kube.pods()[0])
        before = NODECLAIMS_DISRUPTED.value(
            {"reason": "Empty", "nodepool": "default"}
        )
        now = time.time() + 120
        for i in range(6):
            env.reconcile_disruption(now=now + 11 * i)
        assert len(env.kube.nodes()) == 0
        after = NODECLAIMS_DISRUPTED.value(
            {"reason": "Empty", "nodepool": "default"}
        )
        assert after == before + 1

    def test_evaluation_duration_observed_per_method(self):
        from karpenter_tpu.metrics.store import DISRUPTION_EVALUATION_DURATION

        env = Environment(types=_types())
        pool = mk_nodepool("default")
        pool.spec.disruption.consolidate_after = "0s"
        env.kube.create(pool)
        env.provision(mk_pod(cpu=0.5))
        now = time.time() + 120
        env.reconcile_disruption(now=now)
        for method in ("emptiness", "single_node_consolidation"):
            assert DISRUPTION_EVALUATION_DURATION.count(
                {"method": method}
            ) >= 1
