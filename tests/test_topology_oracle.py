"""Topology oracle suite, ported from the reference's property
families (provisioning/scheduling/topology_test.go).

Covers the families the round-1 review called out as unported:
unknown keys / degenerate selectors, NodePool-constrained zonal
domains, skew edges, hostname maxSkew > 1, multi-deployment spreads,
capacity-type spreads under constraints, combined constraint stacks,
spread x node-affinity domain limiting, pod-affinity targets, and
NodePool taints. Line references point at topology_test.go property
names.
"""

from collections import Counter

import pytest

from karpenter_tpu.apis.v1.labels import (
    ARCH_LABEL,
    CAPACITY_TYPE_LABEL,
    HOSTNAME_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.kube.objects import (
    Affinity,
    LabelSelector,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PodAffinity,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.provisioning.scheduler import Scheduler
from karpenter_tpu.testing import mk_nodepool, mk_pod


def types():
    return [
        make_instance_type("c4", cpu=4, memory=16 * GIB, price=1.0),
        make_instance_type("c4-arm", cpu=4, memory=16 * GIB, price=0.9,
                           arch="arm64"),
        make_instance_type("c16", cpu=16, memory=64 * GIB, price=4.0),
    ]


def spread_pod(name, app, key=TOPOLOGY_ZONE_LABEL, skew=1, cpu=0.5,
               when="DoNotSchedule", min_domains=None, selector=None,
               extra_constraints=()):
    pod = mk_pod(name=name, cpu=cpu)
    pod.metadata.labels["app"] = app
    pod.spec.topology_spread_constraints = [
        TopologySpreadConstraint(
            max_skew=skew,
            topology_key=key,
            when_unsatisfiable=when,
            label_selector=(
                LabelSelector.of({"app": app}) if selector is None else selector
            ),
            min_domains=min_domains,
        ),
        *extra_constraints,
    ]
    return pod


def solve(pods, pools=None, **kw):
    sched = Scheduler(
        pools_with_types=pools or [(mk_nodepool("p"), types())], **kw
    )
    return sched.solve(pods), sched


def domain_counts(results, key):
    counts = Counter()
    for plan in results.new_node_plans:
        if key == TOPOLOGY_ZONE_LABEL:
            domain = plan.offerings[0].zone
        elif key == CAPACITY_TYPE_LABEL:
            domain = plan.offerings[0].capacity_type
        else:
            domain = f"planned-{id(plan)}"
        counts[domain] += len(plan.pods)
    return counts


def pool_with_reqs(*reqs, name="p"):
    pool = mk_nodepool(name)
    pool.spec.template.spec.requirements = [
        RequirementSpec(key=k, operator=op, values=tuple(v)) for k, op, v in reqs
    ]
    return pool


class TestDegenerateSpread:
    def test_unknown_topology_key_ignored(self):
        # topology_test.go:60 "should ignore unknown topology keys":
        # the reference leaves such pods pending; we mirror that the
        # constraint never poisons the rest of the solve
        good = [mk_pod(name=f"g-{i}", cpu=0.5) for i in range(3)]
        weird = spread_pod("w", "app", key="example.com/unknown-topology")
        res, _ = solve(good + [weird])
        placed = {p.key for plan in res.new_node_plans for p in plan.pods}
        assert all(p.key in placed for p in good)

    def test_empty_label_selector_matches_nothing_spreads_trivially(self):
        # topology_test.go:94: nil selector -> no pods counted, skew 0
        pods = [
            spread_pod(f"n-{i}", "app", selector=LabelSelector())
            for i in range(4)
        ]
        res, _ = solve(pods)
        assert res.scheduled_count == 4


class TestZonalSpread:
    def test_balance_across_zones_match_labels(self):
        # topology_test.go:110
        pods = [spread_pod(f"z-{i}", "web") for i in range(9)]
        res, _ = solve(pods)
        counts = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        assert res.scheduled_count == 9
        assert len(counts) == 3
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_respects_nodepool_zonal_subset(self):
        # topology_test.go:159: pool limited to two zones -> spread
        # happens over exactly those two
        pool = pool_with_reqs(
            (TOPOLOGY_ZONE_LABEL, "In", ["test-zone-1", "test-zone-2"])
        )
        pods = [spread_pod(f"z-{i}", "web") for i in range(6)]
        res, _ = solve(pods, pools=[(pool, types())])
        counts = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        assert res.scheduled_count == 6
        assert set(counts) == {"test-zone-1", "test-zone-2"}
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_respects_nodepool_zonal_subset_via_labels(self):
        # topology_test.go:175: a template LABEL pins the domain
        pool = mk_nodepool("p")
        pool.spec.template.labels = {TOPOLOGY_ZONE_LABEL: "test-zone-2"}
        pods = [spread_pod(f"z-{i}", "web", when="ScheduleAnyway")
                for i in range(4)]
        res, _ = solve(pods, pools=[(pool, types())])
        assert res.scheduled_count == 4
        assert set(domain_counts(res, TOPOLOGY_ZONE_LABEL)) == {"test-zone-2"}

    def test_domains_across_nodepools_union(self):
        # topology_test.go:206: two pools each pinned to one zone; the
        # spread discovers the union of domains
        pool_a = pool_with_reqs((TOPOLOGY_ZONE_LABEL, "In", ["test-zone-1"]),
                                name="pa")
        pool_b = pool_with_reqs((TOPOLOGY_ZONE_LABEL, "In", ["test-zone-2"]),
                                name="pb")
        pods = [spread_pod(f"z-{i}", "web") for i in range(6)]
        res, _ = solve(pods, pools=[(pool_a, types()), (pool_b, types())])
        counts = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        assert res.scheduled_count == 6
        assert set(counts) == {"test-zone-1", "test-zone-2"}

    def test_max_skew_hard_limit_never_violated(self):
        # topology_test.go:349: DoNotSchedule means skew <= maxSkew in
        # every prefix of the solution
        pods = [spread_pod(f"z-{i}", "web", skew=2) for i in range(10)]
        res, _ = solve(pods)
        counts = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        assert res.scheduled_count == 10
        assert max(counts.values()) - min(counts.values()) <= 2

    def test_min_domains_blocks_when_unreachable(self):
        # topology_test.go:484: minDomains > available zones -> the
        # constraint cannot be met; DoNotSchedule leaves pods pending
        pods = [spread_pod(f"m-{i}", "app", min_domains=5) for i in range(2)]
        res, _ = solve(pods)
        assert res.scheduled_count + len(res.errors) == 2

    def test_min_domains_equal_available_ok(self):
        # topology_test.go:504
        pods = [spread_pod(f"m-{i}", "app", min_domains=3) for i in range(3)]
        res, _ = solve(pods)
        assert res.scheduled_count == 3
        assert len(domain_counts(res, TOPOLOGY_ZONE_LABEL)) == 3


class TestHostnameSpread:
    def test_balance_across_nodes(self):
        # topology_test.go:547
        pods = [spread_pod(f"h-{i}", "db", key=HOSTNAME_LABEL)
                for i in range(4)]
        res, _ = solve(pods)
        assert res.scheduled_count == 4
        assert len(res.new_node_plans) == 4
        for plan in res.new_node_plans:
            assert len([p for p in plan.pods if "db" in p.metadata.labels.get(
                "app", "")]) <= 1

    def test_max_skew_two_allows_pairs(self):
        # topology_test.go:560: "balance pods on the same hostname up
        # to maxskew"
        pods = [spread_pod(f"h-{i}", "db", key=HOSTNAME_LABEL, skew=2)
                for i in range(6)]
        res, _ = solve(pods)
        assert res.scheduled_count == 6
        per_node = [len(plan.pods) for plan in res.new_node_plans]
        assert max(per_node) <= 2

    def test_multiple_deployments_spread_independently(self):
        # topology_test.go:573: two apps each hostname-spread; their
        # constraints must not interfere
        pods = []
        for i in range(3):
            pods.append(spread_pod(f"a-{i}", "app-a", key=HOSTNAME_LABEL))
            pods.append(spread_pod(f"b-{i}", "app-b", key=HOSTNAME_LABEL))
        res, _ = solve(pods)
        assert res.scheduled_count == 6
        for plan in res.new_node_plans:
            apps = Counter(p.metadata.labels["app"] for p in plan.pods)
            assert all(v <= 1 for v in apps.values())


class TestCapacityTypeSpread:
    def test_balance_across_capacity_types(self):
        # topology_test.go:655
        pods = [spread_pod(f"c-{i}", "web", key=CAPACITY_TYPE_LABEL)
                for i in range(6)]
        res, _ = solve(pods)
        counts = domain_counts(res, CAPACITY_TYPE_LABEL)
        assert res.scheduled_count == 6
        assert len(counts) == 2
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_respects_nodepool_capacity_type_constraint(self):
        # topology_test.go:668: pool pinned to spot -> one domain only
        pool = pool_with_reqs((CAPACITY_TYPE_LABEL, "In", ["spot"]))
        pods = [spread_pod(f"c-{i}", "web", key=CAPACITY_TYPE_LABEL,
                           when="ScheduleAnyway") for i in range(4)]
        res, _ = solve(pods, pools=[(pool, types())])
        assert res.scheduled_count == 4
        assert set(domain_counts(res, CAPACITY_TYPE_LABEL)) == {"spot"}

    def test_schedule_anyway_violates_when_needed(self):
        # topology_test.go:718: pods nodeSelector-pinned to on-demand
        # with a ScheduleAnyway ct spread still schedule
        pods = []
        for i in range(4):
            pod = spread_pod(f"c-{i}", "web", key=CAPACITY_TYPE_LABEL,
                             when="ScheduleAnyway")
            pod.spec.node_selector = {CAPACITY_TYPE_LABEL: "on-demand"}
            pods.append(pod)
        res, _ = solve(pods)
        assert res.scheduled_count == 4
        assert set(domain_counts(res, CAPACITY_TYPE_LABEL)) == {"on-demand"}


class TestCombinedConstraints:
    def test_hostname_and_zonal_together(self):
        # topology_test.go:943
        extra = TopologySpreadConstraint(
            max_skew=1,
            topology_key=HOSTNAME_LABEL,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector.of({"app": "both"}),
        )
        pods = [
            spread_pod(f"hz-{i}", "both", extra_constraints=(extra,))
            for i in range(6)
        ]
        res, _ = solve(pods)
        assert res.scheduled_count == 6
        zc = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        assert max(zc.values()) - min(zc.values()) <= 1
        for plan in res.new_node_plans:
            assert len(plan.pods) <= 1  # hostname skew 1

    def test_zonal_and_capacity_type_together(self):
        # topology_test.go:1689-1728
        extra = TopologySpreadConstraint(
            max_skew=1,
            topology_key=CAPACITY_TYPE_LABEL,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector.of({"app": "zc"}),
        )
        pods = [
            spread_pod(f"zc-{i}", "zc", extra_constraints=(extra,))
            for i in range(6)
        ]
        res, _ = solve(pods)
        assert res.scheduled_count == 6
        zc = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        cc = domain_counts(res, CAPACITY_TYPE_LABEL)
        assert max(zc.values()) - min(zc.values()) <= 1
        assert max(cc.values()) - min(cc.values()) <= 1

    def test_all_three_constraints(self):
        # topology_test.go:1729-1766
        extras = (
            TopologySpreadConstraint(
                max_skew=1, topology_key=CAPACITY_TYPE_LABEL,
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector.of({"app": "hzc"}),
            ),
            TopologySpreadConstraint(
                max_skew=3, topology_key=HOSTNAME_LABEL,
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector.of({"app": "hzc"}),
            ),
        )
        pods = [
            spread_pod(f"x-{i}", "hzc", extra_constraints=extras)
            for i in range(6)
        ]
        res, _ = solve(pods)
        assert res.scheduled_count == 6
        zc = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        cc = domain_counts(res, CAPACITY_TYPE_LABEL)
        assert max(zc.values()) - min(zc.values()) <= 1
        assert max(cc.values()) - min(cc.values()) <= 1
        assert all(len(p.pods) <= 3 for p in res.new_node_plans)


class TestSpreadWithNodeAffinity:
    def test_node_selector_limits_spread_domains(self):
        # topology_test.go:1768: spread counts only the selector's zones
        pods = []
        for i in range(4):
            pod = spread_pod(f"s-{i}", "lim")
            pod.spec.node_selector = {TOPOLOGY_ZONE_LABEL: "test-zone-2"}
            pods.append(pod)
        res, _ = solve(pods)
        assert res.scheduled_count == 4
        assert set(domain_counts(res, TOPOLOGY_ZONE_LABEL)) == {"test-zone-2"}

    def test_required_affinity_limits_spread_domains(self):
        # topology_test.go:1816: required node affinity over two zones
        pods = []
        for i in range(6):
            pod = spread_pod(f"r-{i}", "lim2")
            pod.spec.affinity = Affinity(
                node_affinity=NodeAffinity(
                    required=(
                        NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    TOPOLOGY_ZONE_LABEL, "In",
                                    ("test-zone-1", "test-zone-2"),
                                ),
                            )
                        ),
                    )
                )
            )
            pods.append(pod)
        res, _ = solve(pods)
        counts = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        assert res.scheduled_count == 6
        assert set(counts) <= {"test-zone-1", "test-zone-2"}
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_preferred_affinity_does_not_limit_spread(self):
        # topology_test.go:1860: preferences must not shrink the domain
        # set the spread may use
        pods = []
        for i in range(6):
            pod = spread_pod(f"p-{i}", "pref")
            pod.spec.affinity = Affinity(
                node_affinity=NodeAffinity(
                    preferred=(
                        PreferredSchedulingTerm(
                            weight=1,
                            preference=NodeSelectorTerm(
                                match_expressions=(
                                    NodeSelectorRequirement(
                                        TOPOLOGY_ZONE_LABEL, "In",
                                        ("test-zone-1",),
                                    ),
                                )
                            ),
                        ),
                    )
                )
            )
            pods.append(pod)
        res, _ = solve(pods)
        assert res.scheduled_count == 6
        counts = domain_counts(res, TOPOLOGY_ZONE_LABEL)
        # skew still respected across ALL zones (preference can't pin)
        assert max(counts.values()) - min(counts.values()) <= 1


def affinity_pod(name, app, target_app, key, anti=False, cpu=0.5,
                 required=True):
    pod = mk_pod(name=name, cpu=cpu)
    pod.metadata.labels["app"] = app
    term = PodAffinityTerm(
        topology_key=key, label_selector=LabelSelector.of({"app": target_app})
    )
    pa = PodAffinity(required=(term,))
    pod.spec.affinity = Affinity(
        pod_anti_affinity=pa if anti else None,
        pod_affinity=None if anti else pa,
    )
    return pod


class TestPodAffinity:
    def test_hostname_affinity_colocates(self):
        # topology_test.go:1964
        anchor = mk_pod(name="anchor", cpu=0.5)
        anchor.metadata.labels["app"] = "anchor"
        follower = affinity_pod("f", "fol", "anchor", HOSTNAME_LABEL)
        res, _ = solve([anchor, follower])
        assert res.scheduled_count == 2
        for plan in res.new_node_plans:
            names = {p.metadata.name for p in plan.pods}
            if "anchor" in names:
                assert "f" in names

    def test_affinity_to_nonexistent_pod_unschedulable(self):
        # topology_test.go:2738
        orphan = affinity_pod("o", "orphan", "ghost", TOPOLOGY_ZONE_LABEL)
        res, _ = solve([orphan])
        assert res.scheduled_count == 0
        assert len(res.errors) == 1

    def test_self_affinity_zone(self):
        # topology_test.go:2151: all pods of the app share one zone
        pods = [
            affinity_pod(f"s-{i}", "self", "self", TOPOLOGY_ZONE_LABEL)
            for i in range(4)
        ]
        res, _ = solve(pods)
        assert res.scheduled_count == 4
        assert len(domain_counts(res, TOPOLOGY_ZONE_LABEL)) == 1

    def test_anti_affinity_hostname_separates(self):
        # topology_test.go:2325
        pods = [
            affinity_pod(f"a-{i}", "iso", "iso", HOSTNAME_LABEL, anti=True)
            for i in range(3)
        ]
        res, _ = solve(pods)
        assert res.scheduled_count == 3
        assert len(res.new_node_plans) == 3

    def test_anti_affinity_zone_caps_at_domain_count(self):
        # topology_test.go:2347: 3 zones -> at most 3 such pods
        pods = [
            affinity_pod(f"z-{i}", "zi", "zi", TOPOLOGY_ZONE_LABEL, anti=True)
            for i in range(5)
        ]
        res, _ = solve(pods)
        assert res.scheduled_count == 3
        assert len(res.errors) == 2

    def test_anti_affinity_cross_app_zone(self):
        # topology_test.go:2386 "other schedules first": app-b pods
        # must avoid zones holding app-a pods
        a = mk_pod(name="a0", cpu=0.5)
        a.metadata.labels["app"] = "app-a"
        b = affinity_pod("b0", "app-b", "app-a", TOPOLOGY_ZONE_LABEL,
                         anti=True)
        res, _ = solve([a, b])
        assert res.scheduled_count == 2
        zones = {}
        for plan in res.new_node_plans:
            for p in plan.pods:
                zones[p.metadata.name] = plan.offerings[0].zone
        assert zones["a0"] != zones["b0"]

    def test_preferred_anti_affinity_may_be_violated(self):
        # topology_test.go:2292
        pods = []
        for i in range(4):
            pod = mk_pod(name=f"pa-{i}", cpu=0.5)
            pod.metadata.labels["app"] = "soft"
            pod.spec.affinity = Affinity(
                pod_anti_affinity=PodAffinity(
                    preferred=(
                        # weight, term
                        __import__(
                            "karpenter_tpu.kube.objects", fromlist=["W"]
                        ).WeightedPodAffinityTerm(
                            weight=1,
                            pod_affinity_term=PodAffinityTerm(
                                topology_key=TOPOLOGY_ZONE_LABEL,
                                label_selector=LabelSelector.of(
                                    {"app": "soft"}
                                ),
                            ),
                        ),
                    )
                )
            )
            pods.append(pod)
        res, _ = solve(pods)
        assert res.scheduled_count == 4  # 3 zones, 4 pods: one violates


class TestNodePoolTaints:
    def test_taints_block_and_tolerations_admit(self):
        # topology_test.go:3011-3021
        pool = mk_nodepool("tainted")
        pool.spec.template.spec.taints = [
            Taint(key="example.com/dedicated", value="gpu", effect="NoSchedule")
        ]
        plain = mk_pod(name="plain", cpu=0.5)
        tolerant = mk_pod(name="tol", cpu=0.5)
        tolerant.spec.tolerations = [
            Toleration(key="example.com/dedicated", operator="Equal",
                       value="gpu", effect="NoSchedule")
        ]
        res, _ = solve([plain, tolerant], pools=[(pool, types())])
        placed = {p.key for plan in res.new_node_plans for p in plan.pods}
        assert "default/tol" in placed
        assert "default/plain" not in placed


class TestEligibleDomainMinimum:
    def test_ineligible_domain_never_whitelisted(self):
        """allowed_domains must reject a candidate the pod's own terms
        exclude, even when filtering the count map makes its count look
        like the minimum (review regression: NotIn pods were whitelisted
        into crowded excluded zones)."""
        from karpenter_tpu.scheduling.topology import (
            TYPE_SPREAD,
            TopologyGroup,
        )

        group = TopologyGroup(
            type=TYPE_SPREAD, key=TOPOLOGY_ZONE_LABEL,
            selector=LabelSelector.of({"app": "w"}),
            namespaces=frozenset({"default"}), max_skew=1,
        )
        group.counts = {"zone-a": 3, "zone-b": 0}
        allowed = group.allowed_domains({"zone-a"}, eligible={"zone-b"})
        assert allowed == set()

    def test_notin_pod_avoids_excluded_zone_end_to_end(self):
        pods = [spread_pod(f"w-{i}", "web") for i in range(3)]
        excl = spread_pod("excl", "web")
        excl.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=(
                    NodeSelectorTerm(
                        match_expressions=(
                            NodeSelectorRequirement(
                                TOPOLOGY_ZONE_LABEL, "NotIn", ("test-zone-1",)
                            ),
                        )
                    ),
                )
            )
        )
        res, _ = solve(pods + [excl])
        assert res.scheduled_count == 4
        for plan in res.new_node_plans:
            if any(p.metadata.name == "excl" for p in plan.pods):
                assert plan.offerings[0].zone != "test-zone-1"


class TestPreferentialFallback:
    def test_final_required_term_never_relaxed(self):
        # suite_test.go:2198 "should not relax the final term"
        pod = mk_pod(name="stuck", cpu=0.5)
        pod.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=(
                    NodeSelectorTerm(
                        match_expressions=(
                            NodeSelectorRequirement(
                                TOPOLOGY_ZONE_LABEL, "In", ("invalid-zone",)
                            ),
                        )
                    ),
                )
            )
        )
        res, _ = solve([pod])
        assert res.scheduled_count == 0
        assert len(res.errors) == 1

    def test_or_term_relaxation_surfaces_next_term(self):
        # suite_test.go:2196 Required family: the first OR term is
        # impossible; dropping it surfaces the satisfiable second term
        pod = mk_pod(name="fallback", cpu=0.5)
        pod.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=(
                    NodeSelectorTerm(
                        match_expressions=(
                            NodeSelectorRequirement(
                                TOPOLOGY_ZONE_LABEL, "In", ("invalid-zone",)
                            ),
                        )
                    ),
                    NodeSelectorTerm(
                        match_expressions=(
                            NodeSelectorRequirement(
                                TOPOLOGY_ZONE_LABEL, "In", ("test-zone-2",)
                            ),
                        )
                    ),
                )
            )
        )
        res, _ = solve([pod])
        assert res.scheduled_count == 1
        assert set(domain_counts(res, TOPOLOGY_ZONE_LABEL)) == {"test-zone-2"}

    def test_preference_policy_ignore_strips_preferences(self):
        # suite_test.go:2371: with honor_preferences off, preferred
        # terms are ignored outright
        pods = []
        for i in range(6):
            pod = spread_pod(f"i-{i}", "ign", when="ScheduleAnyway")
            pods.append(pod)
        res, _ = solve(pods, honor_preferences=False)
        assert res.scheduled_count == 6
