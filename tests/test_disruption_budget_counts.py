"""Disruption-budget ACCOUNTING: which nodes count toward the total,
which consume allowance.

Ports suite_test.go:699-845 (BuildDisruptionBudgetMapping,
helpers.go): unmanaged / uninitialized / InstanceTerminating nodes are
excluded from the denominator; NotReady, deleting and
MarkedForDeletion nodes consume allowance; the result never goes
negative.
"""

import time

from karpenter_tpu.apis.v1.labels import INSTANCE_TYPE_LABEL, NODEPOOL_LABEL
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_INITIALIZED,
    COND_INSTANCE_TERMINATING,
)
from karpenter_tpu.apis.v1.nodepool import Budget, REASON_UNDERUTILIZED
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.kube.objects import Node, NodeSpec, NodeStatus, ObjectMeta
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def _fleet(n_nodes=10, budget_nodes="30%"):
    """n_nodes one-pod c2 nodes under a single budget."""
    env = Environment(types=[
        make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0),
    ])
    pool = mk_nodepool("default")
    pool.spec.disruption.consolidate_after = "0s"
    pool.spec.disruption.budgets = [Budget(nodes=budget_nodes)]
    env.kube.create(pool)
    for i in range(n_nodes):
        env.provision(mk_pod(name=f"w-{i}", cpu=1.9))
    assert len(env.kube.nodes()) == n_nodes
    now = time.time() + 120
    return env, now


def _allowed(env, now, reason=REASON_UNDERUTILIZED):
    return env.disruption.budget_mapping(reason, now)["default"]


class TestBudgetDenominator:
    def test_healthy_fleet_counts_fully(self):
        env, now = _fleet(10, "30%")
        assert _allowed(env, now) == 3

    def test_unmanaged_nodes_not_counted(self):
        # suite_test.go:699
        env, now = _fleet(10, "30%")
        for i in range(5):
            env.kube.create(Node(
                metadata=ObjectMeta(name=f"byo-{i}",
                                    labels={INSTANCE_TYPE_LABEL: "c2"}),
                spec=NodeSpec(provider_id=f"external://byo-{i}"),
                status=NodeStatus(capacity={"cpu": 2.0}),
            ))
        # 15 nodes on the cluster, but 30% applies to the 10 managed
        assert _allowed(env, now) == 3

    def test_uninitialized_nodes_not_counted(self):
        # suite_test.go:712: replacements that aren't initialized yet
        # must not pad the percentage denominator
        env, now = _fleet(10, "30%")
        for claim in env.kube.node_claims()[:4]:
            claim.status_conditions.set_false(
                COND_INITIALIZED, "NotReady", "test", now=now
            )
        # denominator drops to 6 -> ceil? (30% of 6 = 1.8 -> floor..)
        assert _allowed(env, now) == env.kube.get_node_pool(
            "default"
        ).must_get_allowed_disruptions(now, 6, REASON_UNDERUTILIZED)

    def test_instance_terminating_claims_not_counted(self):
        # suite_test.go:743
        env, now = _fleet(10, "30%")
        for claim in env.kube.node_claims()[:4]:
            claim.status_conditions.set_true(COND_INSTANCE_TERMINATING, now=now)
        assert _allowed(env, now) == env.kube.get_node_pool(
            "default"
        ).must_get_allowed_disruptions(now, 6, REASON_UNDERUTILIZED)


class TestBudgetConsumers:
    def test_deleting_nodes_consume_allowance(self):
        # suite_test.go:796 (deletionTimestamp + MarkedForDeletion)
        env, now = _fleet(10, "30%")
        names = [n.metadata.name for n in env.kube.nodes()[:2]]
        for state in env.cluster.nodes():
            if state.name in names:
                state.marked_for_deletion = True
        assert _allowed(env, now) == 1

    def test_not_ready_nodes_consume_allowance(self):
        # suite_test.go:820
        env, now = _fleet(10, "30%")
        for node in env.kube.nodes()[:2]:
            node.status.conditions[0].status = "False"
        assert _allowed(env, now) == 1

    def test_never_negative(self):
        # suite_test.go:775
        env, now = _fleet(10, "20%")
        for node in env.kube.nodes()[:5]:
            node.status.conditions[0].status = "False"
        assert _allowed(env, now) == 0

    def test_mixed_exclusion_and_consumption(self):
        env, now = _fleet(10, "50%")
        claims = env.kube.node_claims()
        # 2 uninitialized (excluded), 2 marked (consume)
        for claim in claims[:2]:
            claim.status_conditions.set_false(
                COND_INITIALIZED, "NotReady", "test", now=now
            )
        excluded_pids = {c.status.provider_id for c in claims[:2]}
        marked = 0
        for state in env.cluster.nodes():
            claim = state.node_claim
            if claim is None or claim.status.provider_id in excluded_pids:
                continue
            if marked < 2:
                state.marked_for_deletion = True
                marked += 1
        # denominator 8 -> 4 allowed; minus 2 consuming = 2
        assert _allowed(env, now) == 2


class TestBudgetApplication:
    def test_emptiness_respects_consumed_allowance(self):
        """The engine stops short when in-flight deletions already
        consume the budget (suite_test.go budgets x methods)."""
        env, now = _fleet(6, "2")
        # free up all nodes
        for pod in list(env.kube.pods()):
            env.kube.delete(pod)
        # two nodes already on their way out
        for state in env.cluster.nodes()[:2]:
            state.marked_for_deletion = True
        assert _allowed(env, now) == 0
        command = env.reconcile_disruption(now=now)
        assert command is None

    def test_multi_node_consolidation_bounded_by_budget(self):
        env, now = _fleet(6, "2")
        for pod in list(env.kube.pods()):
            env.kube.delete(pod)
        command = env.reconcile_disruption(now=now)
        assert command is not None
        assert len(command.candidates) <= 2
