"""Tests for the topology lowering (solver/topo_batch.py): constrained
pods ride the batched device solver via domain pins, per-node caps and
group conflicts, with legality identical to the per-pod tracker.

Reference semantics: topologygroup.go:226-311 (spread skew),
topology.go:280-327 (anti-affinity inverse scan), hostportusage.go.
"""

from collections import Counter, defaultdict

from karpenter_tpu.apis.v1.labels import TOPOLOGY_ZONE_LABEL
from karpenter_tpu.cloudprovider.fake import GIB, instance_types, make_instance_type
from karpenter_tpu.kube.objects import (
    Affinity,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.provisioning.scheduler import Scheduler
from karpenter_tpu.testing import mk_nodepool, mk_pod

ZONE = TOPOLOGY_ZONE_LABEL
HOSTNAME = "kubernetes.io/hostname"


def spread_pod(name, app, key=ZONE, skew=1, cpu=1.0):
    pod = mk_pod(name=name, cpu=cpu)
    pod.metadata.labels["app"] = app
    pod.spec.topology_spread_constraints = [
        TopologySpreadConstraint(
            max_skew=skew,
            topology_key=key,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector.of({"app": app}),
        )
    ]
    return pod


def anti_pod(name, app, key=HOSTNAME, cpu=1.0):
    pod = mk_pod(name=name, cpu=cpu)
    pod.metadata.labels["app"] = app
    pod.spec.affinity = Affinity(
        pod_anti_affinity=PodAffinity(
            required=(
                PodAffinityTerm(
                    topology_key=key,
                    label_selector=LabelSelector.of({"app": app}),
                ),
            )
        )
    )
    return pod


def affinity_pod(name, app, key=ZONE, cpu=1.0):
    pod = mk_pod(name=name, cpu=cpu)
    pod.metadata.labels["app"] = app
    pod.spec.affinity = Affinity(
        pod_affinity=PodAffinity(
            required=(
                PodAffinityTerm(
                    topology_key=key,
                    label_selector=LabelSelector.of({"app": app}),
                ),
            )
        )
    )
    return pod


def zone_of(plan):
    return plan.offerings[0].zone


class TestZonalSpreadLowering:
    def test_skew_within_bound(self):
        pods = [spread_pod(f"p-{i}", f"svc-{i % 4}") for i in range(60)]
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), instance_types(20))])
        res = sched.solve(pods)
        assert res.scheduled_count == 60 and not res.errors
        per_app = defaultdict(Counter)
        for plan in res.new_node_plans:
            for pod in plan.pods:
                per_app[pod.metadata.labels["app"]][zone_of(plan)] += 1
        for app, counts in per_app.items():
            # all three zones carry load and skew <= 1
            values = [counts.get(z, 0) for z in
                      ("test-zone-1", "test-zone-2", "test-zone-3")]
            assert max(values) - min(values) <= 1, (app, counts)

    def test_large_skew_allows_imbalance_but_schedules(self):
        pods = [spread_pod(f"p-{i}", "svc", skew=5) for i in range(20)]
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), instance_types(20))])
        res = sched.solve(pods)
        assert res.scheduled_count == 20 and not res.errors

    def test_seeded_counts_respected(self):
        """Pods already in zone-1 pull new placements toward the other
        zones (water-fill starts from live counts)."""
        from karpenter_tpu.testing import Environment

        env = Environment(types=instance_types(20))
        env.kube.create(mk_nodepool("p"))
        seed = [spread_pod(f"s-{i}", "svc") for i in range(3)]
        env.provision(*seed)
        placed = Counter()
        for node in env.kube.nodes():
            zone = node.metadata.labels.get(ZONE)
            state = env.cluster.node_for_name(node.metadata.name)
            placed[zone] += len(state.pod_keys)
        more = [spread_pod(f"m-{i}", "svc") for i in range(6)]
        env.provision(*more)
        counts = Counter()
        for node in env.kube.nodes():
            zone = node.metadata.labels.get(ZONE)
            state = env.cluster.node_for_name(node.metadata.name)
            counts[zone] += len(state.pod_keys)
        values = [counts.get(z, 0) for z in
                  ("test-zone-1", "test-zone-2", "test-zone-3")]
        assert max(values) - min(values) <= 1, counts


class TestHostnameAntiAffinityLowering:
    def test_owners_on_distinct_nodes(self):
        pods = [anti_pod(f"a-{i}", "db") for i in range(4)]
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), instance_types(20))])
        res = sched.solve(pods)
        assert res.scheduled_count == 4 and not res.errors
        for plan in res.new_node_plans:
            owners = [p for p in plan.pods if p.metadata.labels.get("app") == "db"]
            assert len(owners) <= 1

    def test_matched_pods_avoid_owner_nodes(self):
        """Selector-matched pods without the term must not share a node
        with an owner (the inverse scan)."""
        owners = [anti_pod(f"a-{i}", "web") for i in range(2)]
        plain = []
        for i in range(6):
            pod = mk_pod(name=f"w-{i}", cpu=1.0)
            pod.metadata.labels["app"] = "web"
            plain.append(pod)
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), instance_types(20))])
        res = sched.solve(owners + plain)
        assert res.scheduled_count == 8 and not res.errors
        for plan in res.new_node_plans:
            apps = [p.metadata.name for p in plan.pods
                    if p.metadata.labels.get("app") == "web"]
            has_owner = any(n.startswith("a-") for n in apps)
            if has_owner:
                assert len(apps) == 1, f"owner shares node: {apps}"


class TestZoneAffinityAntiLowering:
    def test_zone_anti_distinct_zones_and_overflow_errors(self):
        pods = [anti_pod(f"z-{i}", "singleton", key=ZONE) for i in range(5)]
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), instance_types(20))])
        res = sched.solve(pods)
        # 3 zones -> 3 scheduled, 2 unplaceable
        assert res.scheduled_count == 3
        assert len(res.errors) == 2
        zones = [zone_of(plan) for plan in res.new_node_plans for _ in plan.pods]
        assert len(set(zones)) == len(zones)

    def test_zone_affinity_colocates(self):
        pods = [affinity_pod(f"c-{i}", "cache") for i in range(6)]
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), instance_types(20))])
        res = sched.solve(pods)
        assert res.scheduled_count == 6 and not res.errors
        zones = {zone_of(plan) for plan in res.new_node_plans if plan.pods}
        assert len(zones) == 1


class TestHostnameSpreadLowering:
    def test_per_node_cap(self):
        pods = [spread_pod(f"h-{i}", "svc", key=HOSTNAME, skew=2, cpu=0.25)
                for i in range(10)]
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), instance_types(20))])
        res = sched.solve(pods)
        assert res.scheduled_count == 10 and not res.errors
        for plan in res.new_node_plans:
            assert len(plan.pods) <= 2


class TestBatchIntegration:
    def test_constrained_pods_avoid_per_pod_fallback(self):
        """The bench shape (zonal spread + hostname anti) must lower
        fully — nothing routed to the per-pod path."""
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import topo_batch

        pods = []
        for i in range(40):
            pod = spread_pod(f"b-{i}", f"svc-{i % 4}")
            if i % 10 == 0:
                pod.spec.affinity = Affinity(
                    pod_anti_affinity=PodAffinity(
                        required=(
                            PodAffinityTerm(
                                topology_key=HOSTNAME,
                                label_selector=LabelSelector.of(
                                    {"app": pod.metadata.labels["app"]}
                                ),
                            ),
                        )
                    )
                )
            pods.append(pod)
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), instance_types(20))])
        topo = sched.topology
        full = Topology(
            domains=topo.domains,
            cluster_pods=[],
            pending_pods=pods,
            honor_schedule_anyway=True,
        )
        tb = topo_batch.prepare(pods, full, sched.existing_inputs, {})
        assert not tb.fallback and not tb.errors
        assert sum(g.count for g in tb.groups) == 40

    def test_mixed_simple_and_constrained_share_plans(self):
        """Constrained pods join fast-path open plans instead of
        opening fresh nodes (pseudo-existing plan inputs)."""
        porty = mk_pod(name="porty", cpu=0.25)
        porty.spec.containers[0].ports = [443]
        plain = [mk_pod(name=f"plain-{i}", cpu=0.25) for i in range(3)]
        types = [make_instance_type("c8", cpu=8, memory=32 * GIB, price=1.0)]
        sched = Scheduler(pools_with_types=[(mk_nodepool("p"), types)])
        res = sched.solve([porty] + plain)
        assert res.scheduled_count == 4
        assert len(res.new_node_plans) == 1
