"""Restart-chaos harness (ISSUE 5): kill the operator at injected
crash points mid-provisioning / mid-binding / mid-disruption, restart
it against the SURVIVING InMemoryApiServer (and the surviving cloud —
launched instances do not die with the operator), and assert the
cluster converges to the same state as an uninterrupted run:

- same node set (instance-type multiset; names are process-local),
- same bindings (per-node pod-name partition),
- zero orphaned nodeclaims (every claim backed by a node + instance),
- zero double launches (cloud instances == claim provider ids),

with the fault schedule replaying byte-identically
(`FaultInjector.snapshot_log`).

The crash mechanism is `operator_crash@<site>:<occ>` raising
OperatorCrashError out of `Operator.step` — the deterministic stand-in
for SIGKILL between two API writes. The restarted operator gets a
FRESH RealKubeClient (mirror rebuilt from LIST, exactly like informer
start) and an empty memory: pending-binding plans, the lifecycle
active set, and the disruption queue must all be re-derived from the
API alone (Operator._recover).
"""

import time

import pytest

from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.kube.real import InMemoryApiServer, RealKubeClient
from karpenter_tpu.metrics.store import OPERATOR_RECOVERY
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.solver import faults
from karpenter_tpu.testing import mk_nodepool, mk_pod


@pytest.fixture()
def clean_faults(monkeypatch):
    monkeypatch.delenv("KARPENTER_FAULTS", raising=False)
    monkeypatch.setenv("KARPENTER_KUBE_RETRY_BASE_MS", "1")
    monkeypatch.setenv("KARPENTER_KUBE_RELIST_MIN_MS", "0")
    faults.reset()
    yield monkeypatch
    faults.reset()


def _singleton_types():
    # one-pod-per-node catalog: a 1.5-cpu pod only fits a c2, so EVERY
    # solve (the uninterrupted one and any post-crash partial re-solve)
    # is forced to the same singleton partition — binding identity is
    # assertable exactly, not just statistically
    return [make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0)]


def _consolidation_types():
    return [
        make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0),
        make_instance_type("c8", cpu=8, memory=32 * GIB, price=5.0),
    ]


class Harness:
    """One cluster run: a surviving API server + surviving cloud, and
    an operator that may die (OperatorCrashError) and be rebooted with
    fresh memory at any tick."""

    def __init__(self, types):
        self.server = InMemoryApiServer()
        kube = RealKubeClient(self.server)
        self.cloud = KwokCloudProvider(kube, types=types)
        self.op = Operator(kube=kube, cloud_provider=self.cloud)
        self.user = RealKubeClient(self.server)
        self.now = time.time()
        self.crashes = 0

    def drive(self, ticks, dt=2.0):
        for _ in range(ticks):
            self.now += dt
            try:
                self.op.step(now=self.now)
            except faults.OperatorCrashError:
                self.crashes += 1
                self._restart()

    def _restart(self):
        # the operator process died; the API server and the cloud did
        # not. New client (fresh LIST-fed mirror), new operator (empty
        # memory); the cloud's node-materialization writes ride the
        # new client, as the kubelet rides the real apiserver.
        kube = RealKubeClient(self.server)
        self.cloud.kube = kube
        self.op = Operator(kube=kube, cloud_provider=self.cloud)

    # -- workload script (identical for every arm) ------------------------

    def seed(self, pods, consolidate="Never"):
        pool = mk_nodepool("default")
        pool.spec.disruption.consolidate_after = consolidate
        self.user.create(pool)
        for name, cpu in pods:
            self.user.create(mk_pod(name=name, cpu=cpu))

    def delete_pods(self, names):
        self.user.deliver()
        for name in names:
            pod = self.user.get_pod("default", name)
            if pod is not None:
                self.user.delete(pod)

    def create_pods(self, pods):
        for name, cpu in pods:
            self.user.create(mk_pod(name=name, cpu=cpu))

    # -- converged-state identity ----------------------------------------

    def fingerprint(self):
        """Name-agnostic converged state + the no-leak invariants."""
        kube = self.op.kube
        claims = kube.node_claims()
        assert all(
            c.metadata.deletion_timestamp is None for c in claims
        ), "orphaned (wedged-deleting) nodeclaim"
        claim_pids = sorted(
            c.status.provider_id for c in claims if c.status.provider_id
        )
        assert len(claim_pids) == len(claims), "claim never launched"
        inst_pids = sorted(
            i.status.provider_id for i in self.cloud.list()
        )
        assert inst_pids == claim_pids, (
            "leaked instance or double launch: "
            f"cloud={inst_pids} claims={claim_pids}"
        )
        nodes = kube.nodes()
        assert sorted(n.spec.provider_id for n in nodes) == claim_pids, (
            "node set diverged from claim set"
        )
        live = [
            p for p in kube.pods()
            if p.metadata.deletion_timestamp is None
        ]
        assert all(p.spec.node_name for p in live), (
            "stranded pod: "
            f"{[p.metadata.name for p in live if not p.spec.node_name]}"
        )
        assert self.op.cluster.synced()
        assert self.op.cluster.unpaired_claim_names() == [], (
            "in-flight claim never materialized"
        )
        parts = sorted(
            (
                n.metadata.labels.get(
                    "node.kubernetes.io/instance-type", ""
                ),
                tuple(sorted(
                    p.metadata.name
                    for p in kube.pods_on_node(n.metadata.name)
                )),
            )
            for n in nodes
        )
        return parts


def _provisioning_run(spec, monkeypatch):
    """Six 1.5-cpu pods on a singleton catalog: converge to six c2
    nodes, one pod each."""
    if spec:
        monkeypatch.setenv("KARPENTER_FAULTS", spec)
    else:
        monkeypatch.delenv("KARPENTER_FAULTS", raising=False)
    faults.reset()
    h = Harness(_singleton_types())
    h.seed([(f"w-{i}", 1.5) for i in range(6)])
    h.drive(14, dt=2.0)
    # ride past the GC interval so a reaped double-launch (crash_launch)
    # has been collected before the final fingerprint
    h.now += 130
    h.drive(8, dt=2.0)
    inj = faults.get()
    h.fault_log = inj.snapshot_log() if inj is not None else []
    monkeypatch.delenv("KARPENTER_FAULTS", raising=False)
    return h


def _disruption_run(spec, monkeypatch):
    """Fifteen 1.5-cpu pods -> three c8 nodes; thin to one pod per node
    -> multi-node consolidation replaces 3 with 1; the drained pods die
    (the real-client stack fabricates no successors) and the fleet
    empties; recreate three pods -> one c8. Crashes anywhere along the
    way must land on the same end state."""
    if spec:
        monkeypatch.setenv("KARPENTER_FAULTS", spec)
    else:
        monkeypatch.delenv("KARPENTER_FAULTS", raising=False)
    faults.reset()
    h = Harness(_consolidation_types())
    h.seed([(f"w-{i}", 1.5) for i in range(15)], consolidate="0s")
    h.drive(14, dt=2.0)
    # keep the first-listed pod on each node, delete the rest
    h.user.deliver()
    keep: set = set()
    doomed = []
    for pod in sorted(h.user.pods(), key=lambda p: p.metadata.name):
        if pod.spec.node_name and pod.spec.node_name not in keep:
            keep.add(pod.spec.node_name)
        else:
            doomed.append(pod.metadata.name)
    h.delete_pods(doomed)
    h.drive(30, dt=15.0)
    h.create_pods([(f"r-{i}", 1.5) for i in range(3)])
    h.drive(12, dt=2.0)
    h.now += 130
    h.drive(8, dt=2.0)
    inj = faults.get()
    h.fault_log = inj.snapshot_log() if inj is not None else []
    monkeypatch.delenv("KARPENTER_FAULTS", raising=False)
    return h


_REFERENCE: dict = {}


def _reference(kind, monkeypatch):
    if kind not in _REFERENCE:
        run = {"prov": _provisioning_run, "disr": _disruption_run}[kind]
        _REFERENCE[kind] = run("", monkeypatch).fingerprint()
    return _REFERENCE[kind]


PROVISIONING_CRASHES = [
    "operator_crash@crash_tick:2",
    "operator_crash@crash_claims:1",
    "operator_crash@crash_provision:1",
    "operator_crash@crash_bind:2",
    "operator_crash@crash_launch:3",
    # inside the incremental live tick (ISSUE 7): after the dirty sets
    # drained but before the residual solve, and after the solve but
    # before the plans become NodeClaim writes — the restarted operator
    # must rebuild the retained cache from the API (not resurrect the
    # drained delta) and still converge
    "operator_crash@crash_incr_solve:1",
    "operator_crash@crash_incr_commit:1",
]

DISRUPTION_CRASHES = [
    "operator_crash@crash_disruption:1",
    "operator_crash@crash_disruption_started:1",
]


@pytest.mark.restart_chaos
@pytest.mark.parametrize("spec", PROVISIONING_CRASHES)
def test_provisioning_crash_converges_to_uninterrupted_state(
    spec, clean_faults
):
    want = _reference("prov", clean_faults)
    assert len(want) == 6 and all(len(p[1]) == 1 for p in want)
    h = _provisioning_run(spec, clean_faults)
    assert h.crashes >= 1, f"{spec} never fired"
    assert h.fingerprint() == want
    # the restarted operator reported what it rebuilt from the API
    assert "readopted_claims" in h.op.readyz()["recovery"]


@pytest.mark.restart_chaos
@pytest.mark.parametrize("spec", DISRUPTION_CRASHES)
def test_disruption_crash_converges_to_uninterrupted_state(
    spec, clean_faults
):
    want = _reference("disr", clean_faults)
    h = _disruption_run(spec, clean_faults)
    assert h.crashes >= 1, f"{spec} never fired"
    assert h.fingerprint() == want


@pytest.mark.restart_chaos
def test_incremental_crash_rebuilds_the_retained_cache(clean_faults):
    """A crash INSIDE the incremental tick must not resurrect the
    pre-crash retained state: the restarted operator rebuilds from the
    API (recovery invalidates + forces an oracle audit), converges to
    the uninterrupted fleet, and reports zero divergences — the
    rebuilt cache agreed with the full solve."""
    want = _reference("prov", clean_faults)
    h = _provisioning_run(
        "operator_crash@crash_incr_commit:1", clean_faults
    )
    assert h.crashes >= 1
    assert h.fingerprint() == want
    inc = h.op.readyz()["incremental"]
    assert inc["divergences"] == 0
    assert inc["ticks"]["incremental"] >= 1, (
        "the restarted operator must resume the incremental path, "
        f"not wedge on the full backstop: {inc}"
    )


@pytest.mark.restart_chaos
def test_crash_launch_reaps_the_unrecorded_twin(clean_faults):
    """The double-launch window in isolation: a crash between the
    provider create and the claim's status write leaves a running
    instance no claim records. The restarted operator re-launches
    (one live instance per claim) and its recovery GC reaps the twin —
    observable in karpenter_operator_recovery_total."""
    reaped0 = OPERATOR_RECOVERY.value({"action": "reaped_leak"})
    h = _provisioning_run("operator_crash@crash_launch:1", clean_faults)
    assert h.crashes == 1
    assert h.fingerprint() == _reference("prov", clean_faults)
    assert OPERATOR_RECOVERY.value({"action": "reaped_leak"}) > reaped0


@pytest.mark.restart_chaos
def test_fault_schedule_replays_byte_identically(clean_faults):
    """Same spec + same workload script => identical fired-fault log
    AND identical converged state — a restart-chaos failure found in
    CI replays exactly on a laptop."""
    spec = "operator_crash@crash_bind:2,kube_conflict@kube_write:5-7"
    h_a = _provisioning_run(spec, clean_faults)
    h_b = _provisioning_run(spec, clean_faults)
    assert h_a.fault_log, "spec never fired"
    assert h_a.fault_log == h_b.fault_log, (
        "fault sequences must replay identically"
    )
    assert h_a.crashes == h_b.crashes >= 1
    assert h_a.fingerprint() == h_b.fingerprint()
