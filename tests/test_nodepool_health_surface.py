"""NodePool registration health surfaced to operators (ISSUE 8
satellite): the state/nodepoolhealth ring buffers were state-only —
visible to the NodeRegistrationHealthy condition writer and nobody
else. Now every record publishes
`karpenter_nodepool_registration_healthy{nodepool}` and
`Operator.readyz()["nodepool_health"]` snapshots the degraded set.
"""

from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.metrics.store import NODEPOOL_REGISTRATION_HEALTHY
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.state.nodepoolhealth import HealthTracker
from karpenter_tpu.testing import mk_nodepool


class TestHealthGauge:
    def test_record_publishes_gauge(self):
        tracker = HealthTracker()
        tracker.record("pool-g1", True)
        assert NODEPOOL_REGISTRATION_HEALTHY.value(
            {"nodepool": "pool-g1"}
        ) == 1.0
        for _ in range(6):
            tracker.record("pool-g1", False)
        assert NODEPOOL_REGISTRATION_HEALTHY.value(
            {"nodepool": "pool-g1"}
        ) == 0.0
        tracker.reset("pool-g1")
        # series dropped, not frozen at the stale verdict
        assert ({"nodepool": "pool-g1"} not in [
            dict(k) for k, _ in NODEPOOL_REGISTRATION_HEALTHY.samples()
        ])

    def test_snapshot_reports_degraded_pools(self):
        tracker = HealthTracker()
        tracker.record("good", True)
        for _ in range(5):
            tracker.record("bad", False)
        snap = tracker.snapshot()
        assert snap["tracked_pools"] == 2
        assert list(snap["degraded"]) == ["bad"]
        assert snap["degraded"]["bad"]["recent_failures"] == 5
        assert snap["degraded"]["bad"]["window"] == 5


class TestReadyzSurface:
    def test_readyz_carries_nodepool_health(self):
        kube = KubeClient()
        cloud = KwokCloudProvider(
            kube, types=[make_instance_type("c4", cpu=4, memory=16 * GIB)]
        )
        op = Operator(kube, cloud)
        kube.create(mk_nodepool("flaky"))
        for _ in range(5):
            op.health.record("flaky", False)
        ready = op.readyz()
        health = ready["nodepool_health"]
        assert health["tracked_pools"] == 1
        assert "flaky" in health["degraded"]
        assert NODEPOOL_REGISTRATION_HEALTHY.value(
            {"nodepool": "flaky"}
        ) == 0.0
