"""Execution-time validation of disruption commands.

The reference re-verifies a consolidation command against fresh state
after a TTL before executing it (disruption/validation.go:152-316):
candidates must still be disruptable AND the command must still make
economic sense. These tests exercise the window between compute and
execute — prices move, offerings vanish, pods become unschedulable —
and assert the command rolls back instead of executing stale.
"""

import time

from karpenter_tpu.apis.v1.labels import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.disruption.validation import (
    VALIDATION_TTL_SECONDS,
    ValidationError,
    Validator,
)
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def consolidation_types():
    return [
        make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0),
        make_instance_type("c4", cpu=4, memory=16 * GIB, price=3.0),
        make_instance_type("c8", cpu=8, memory=32 * GIB, price=5.0),
    ]


def make_env(**pool_kwargs):
    env = Environment(types=consolidation_types())
    pool = mk_nodepool("default")
    pool.spec.disruption.consolidate_after = "0s"
    for key, value in pool_kwargs.items():
        setattr(pool.spec.disruption, key, value)
    env.kube.create(pool)
    return env


def start_multi_node_command(env):
    """Provision 3 one-cpu pods onto 3 small nodes, then compute the
    multi-node consolidation command (3 x c2 @ 2.0 -> 1 x c4 @ 3.0)
    WITHOUT progressing the queue: the replacement claims exist but are
    not yet initialized, so nothing validates or executes yet."""
    pods = []
    for _ in range(3):
        pod = mk_pod(cpu=1.0, memory=2 * GIB)
        env.provision(pod)
        pods.append(pod)
    assert len(env.kube.nodes()) == 3
    now = time.time() + 120
    env.pod_events.reconcile_all(now=now)
    env.conditions.reconcile_all(now=now)
    command = env.disruption.reconcile(now=now)
    assert command is not None and len(command.candidates) >= 2
    assert command.replacement_count == 1
    return command, now


def initialize_replacements(env, now):
    env.lifecycle.reconcile_all(now=now)
    env.cloud.tick(now=now)
    env.lifecycle.reconcile_all(now=now)


def candidate_nodes_intact(env, command):
    """Candidates not deleting and un-tainted (rollback happened)."""
    for candidate in command.candidates:
        claim = env.kube.get_node_claim(
            candidate.state_node.node_claim.metadata.name
        )
        if claim is None or claim.metadata.deletion_timestamp is not None:
            return False
        node = candidate.state_node.node
        if node is not None and any(
            t.key == DISRUPTED_NO_SCHEDULE_TAINT.key for t in node.spec.taints
        ):
            return False
    return True


def reprice_replacement_types(env, command, price):
    """Move every offering of every type a replacement plan could still
    launch (the plan keeps fallback types, e.g. c8 behind c4 — all must
    move for the economics to change)."""
    plan_types = {
        it.name for plan in command.results.new_node_plans
        for it in plan.instance_types
    }
    for it in env.cloud.types:
        if it.name in plan_types:
            for off in it.offerings:
                off.price = price


class TestEconomicsRevalidation:
    def test_replacement_price_rise_rolls_back(self):
        """Every replacement offering's price jumps above the retired
        price between compute and execute: the command must NOT delete
        the candidates (validation.go:297-310 economics guard)."""
        env = make_env()
        command, now = start_multi_node_command(env)
        retired = sum(c.price for c in command.candidates)
        reprice_replacement_types(env, command, retired * 1.5)
        initialize_replacements(env, now)
        env.disruption.queue.reconcile(now=now)
        assert candidate_nodes_intact(env, command)
        # the never-loaded replacement claim is retired on rollback
        replacement = command.results.new_node_plans[0].claim_name
        claim = env.kube.get_node_claim(replacement)
        assert claim is None or claim.metadata.deletion_timestamp is not None

    def test_replacement_offering_vanished_rolls_back(self):
        """Every instance type a plan could launch disappears from the
        catalog (sold out / retired) before execution."""
        env = make_env()
        command, now = start_multi_node_command(env)
        plan_types = {
            it.name for plan in command.results.new_node_plans
            for it in plan.instance_types
        }
        env.cloud.types = [
            it for it in env.cloud.types if it.name not in plan_types
        ]
        initialize_replacements(env, now)
        env.disruption.queue.reconcile(now=now)
        assert candidate_nodes_intact(env, command)

    def test_candidate_price_drop_rolls_back(self):
        """The CANDIDATES' own offerings get cheaper so the merge no
        longer wins (retired total falls below the replacement's
        cheapest surviving price)."""
        env = make_env()
        command, now = start_multi_node_command(env)
        cheapest_replacement = min(
            o.price
            for plan in command.results.new_node_plans
            for o in plan.offerings
        )
        per_candidate = cheapest_replacement / (len(command.candidates) + 1)
        for it in env.cloud.types:
            if it.name == "c2":
                for off in it.offerings:
                    off.price = per_candidate
        initialize_replacements(env, now)
        env.disruption.queue.reconcile(now=now)
        assert candidate_nodes_intact(env, command)

    def test_unchanged_prices_execute(self):
        """Prices stay put -> the command executes and candidates
        drain (no false rollback from the new checks)."""
        env = make_env()
        command, now = start_multi_node_command(env)
        initialize_replacements(env, now)
        env.disruption.queue.reconcile(now=now)
        # candidates now deleting
        deleting = sum(
            1
            for candidate in command.candidates
            if (claim := env.kube.get_node_claim(
                candidate.state_node.node_claim.metadata.name
            )) is None or claim.metadata.deletion_timestamp is not None
        )
        assert deleting == len(command.candidates)

    def test_replacement_offering_unavailable_still_executes(self):
        """An offering going unavailable for NEW launches must not roll
        back a replacement that is already running on it — availability
        gates launchability, not existing nodes."""
        env = make_env()
        command, now = start_multi_node_command(env)
        initialize_replacements(env, now)
        plan_types = {
            it.name for plan in command.results.new_node_plans
            for it in plan.instance_types
        }
        for it in env.cloud.types:
            if it.name in plan_types:
                for off in it.offerings:
                    off.available = False
        env.disruption.queue.reconcile(now=now)
        assert not candidate_nodes_intact(env, command)

    def test_price_rise_within_margin_still_executes(self):
        """A replacement price move that KEEPS the strict win executes:
        every replacement offering rises but stays just below the
        retired price."""
        env = make_env()
        command, now = start_multi_node_command(env)
        retired = sum(c.price for c in command.candidates)
        reprice_replacement_types(env, command, retired * 0.95)
        initialize_replacements(env, now)
        env.disruption.queue.reconcile(now=now)
        assert not candidate_nodes_intact(env, command)


class TestTTLResimulation:
    def test_resimulation_runs_after_ttl_and_executes(self):
        """Past the TTL with nothing changed, re-simulation passes (the
        launched replacement is live capacity) and the command
        executes."""
        env = make_env()
        command, now = start_multi_node_command(env)
        initialize_replacements(env, now)
        late = now + VALIDATION_TTL_SECONDS + 1
        env.disruption.queue.reconcile(now=late)
        assert not candidate_nodes_intact(env, command)

    def test_resimulation_unschedulable_pods_roll_back(self):
        """After the TTL, candidate pods that can no longer reschedule
        anywhere (selector now impossible) roll the command back
        (validateCommand, validation.go:262-268)."""
        env = make_env()
        command, now = start_multi_node_command(env)
        for candidate in command.candidates:
            for pod in candidate.reschedulable_pods:
                pod.spec.node_selector = {"no-such-label": "true"}
        initialize_replacements(env, now)
        late = now + VALIDATION_TTL_SECONDS + 1
        env.disruption.queue.reconcile(now=late)
        assert candidate_nodes_intact(env, command)

    def test_within_ttl_skips_resimulation(self):
        """Inside the TTL the re-simulation is skipped (the reference
        validates exactly once after the TTL; cheap checks still run):
        impossible selectors go unnoticed and the command executes."""
        env = make_env()
        command, now = start_multi_node_command(env)
        for candidate in command.candidates:
            for pod in candidate.reschedulable_pods:
                pod.spec.node_selector = {"no-such-label": "true"}
        initialize_replacements(env, now)
        env.disruption.queue.reconcile(now=now + 1)
        assert not candidate_nodes_intact(env, command)


class TestTransientFailures:
    def test_catalog_fetch_blip_retries_then_executes(self):
        """A transient provider error during the validation-time
        catalog re-fetch must NOT roll the command back (the queue has
        a retry deadline for exactly this): the command stays active
        and executes once the catalog is reachable again."""
        env = make_env()
        command, now = start_multi_node_command(env)
        initialize_replacements(env, now)
        real = env.cloud.get_instance_types

        def flaky(pool):
            raise RuntimeError("API blip")

        env.cloud.get_instance_types = flaky
        env.disruption.queue.reconcile(now=now)
        # not rolled back, not executed: still active (candidates stay
        # tainted while in flight), and no candidate is deleting yet
        assert command in env.disruption.queue.active
        for candidate in command.candidates:
            claim = env.kube.get_node_claim(
                candidate.state_node.node_claim.metadata.name
            )
            assert claim is not None
            assert claim.metadata.deletion_timestamp is None
        env.cloud.get_instance_types = real
        env.disruption.queue.reconcile(now=now + 1)
        assert not candidate_nodes_intact(env, command)

    def test_catalog_outage_past_deadline_rolls_back(self):
        """A catalog outage that outlives the command's retry deadline
        rolls the command back instead of retrying forever."""
        from karpenter_tpu.disruption.engine import COMMAND_TIMEOUT_SECONDS

        env = make_env()
        command, now = start_multi_node_command(env)
        initialize_replacements(env, now)

        def down(pool):
            raise RuntimeError("API down")

        env.cloud.get_instance_types = down
        env.disruption.queue.reconcile(now=now + COMMAND_TIMEOUT_SECONDS + 1)
        assert command not in env.disruption.queue.active
        assert candidate_nodes_intact(env, command)


class TestValidatorUnit:
    def test_direct_validate_raises_on_price_move(self):
        env = make_env()
        command, now = start_multi_node_command(env)
        retired = sum(c.price for c in command.candidates)
        reprice_replacement_types(env, command, retired * 2)
        validator = Validator(env.disruption)
        try:
            validator.validate_for_execution(command, now=now)
            raised = False
        except ValidationError:
            raised = True
        assert raised

    def test_direct_validate_ok_when_fresh(self):
        env = make_env()
        command, now = start_multi_node_command(env)
        Validator(env.disruption).validate_for_execution(command, now=now)

    def test_nominated_candidate_rolls_back(self):
        """A candidate nominated for a pod during the in-flight window
        fails validation (validation.go:242-246)."""
        env = make_env()
        command, now = start_multi_node_command(env)
        live = env.cluster.node_for_name(command.candidates[0].state_node.name)
        live.nominate(now=now)
        validator = Validator(env.disruption)
        try:
            validator.validate_for_execution(command, now=now)
            raised = False
        except ValidationError:
            raised = True
        assert raised
