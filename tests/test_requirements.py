"""Requirement/Requirements set-algebra tests.

Property sets derived from the reference's
pkg/scheduling/requirement_test.go and requirements_test.go: operator
semantics, intersections across the full operator matrix, Gt/Lt bounds,
minValues propagation, and Compatible's custom-label rules.
"""

import pytest

from karpenter_tpu.apis.v1.labels import WELL_KNOWN_LABELS
from karpenter_tpu.scheduling.requirement import (
    DOES_NOT_EXIST,
    EXISTS,
    GT,
    IN,
    LT,
    NOT_IN,
    Requirement,
)
from karpenter_tpu.scheduling.requirements import Requirements


def req(op, *values, key="key", min_values=None):
    return Requirement(key, op, values, min_values=min_values)


class TestRequirementHas:
    def test_in(self):
        r = req(IN, "a", "b")
        assert r.has("a") and r.has("b") and not r.has("c")

    def test_not_in(self):
        r = req(NOT_IN, "a")
        assert not r.has("a") and r.has("b")

    def test_exists(self):
        assert req(EXISTS).has("anything")

    def test_does_not_exist(self):
        assert not req(DOES_NOT_EXIST).has("anything")

    def test_gt_lt(self):
        assert req(GT, "5").has("6")
        assert not req(GT, "5").has("5")
        assert req(LT, "5").has("4")
        assert not req(LT, "5").has("5")
        # non-numeric values fail bounds
        assert not req(GT, "5").has("abc")

    def test_operator_names(self):
        assert req(IN, "a").operator() == IN
        assert req(NOT_IN, "a").operator() == NOT_IN
        assert req(EXISTS).operator() == EXISTS
        assert req(DOES_NOT_EXIST).operator() == DOES_NOT_EXIST
        # Gt/Lt become bounded Exists
        assert req(GT, "1").operator() == EXISTS


class TestIntersection:
    def test_in_in(self):
        out = req(IN, "a", "b").intersection(req(IN, "b", "c"))
        assert out.operator() == IN and out.value_list() == ["b"]

    def test_in_in_disjoint(self):
        out = req(IN, "a").intersection(req(IN, "b"))
        assert out.operator() == DOES_NOT_EXIST

    def test_in_not_in(self):
        out = req(IN, "a", "b").intersection(req(NOT_IN, "b"))
        assert out.value_list() == ["a"]

    def test_not_in_not_in(self):
        out = req(NOT_IN, "a").intersection(req(NOT_IN, "b"))
        assert out.operator() == NOT_IN
        assert not out.has("a") and not out.has("b") and out.has("c")

    def test_exists_in(self):
        out = req(EXISTS).intersection(req(IN, "a"))
        assert out.operator() == IN and out.value_list() == ["a"]

    def test_does_not_exist_wins(self):
        out = req(DOES_NOT_EXIST).intersection(req(IN, "a"))
        assert out.operator() == DOES_NOT_EXIST

    def test_gt_lt_band(self):
        out = req(GT, "1").intersection(req(LT, "5"))
        assert not out.has("1") and out.has("2") and out.has("4") and not out.has("5")

    def test_gt_lt_empty_band(self):
        out = req(GT, "5").intersection(req(LT, "5"))
        assert out.operator() == DOES_NOT_EXIST

    def test_in_with_bounds(self):
        out = req(IN, "1", "3", "9").intersection(req(LT, "5"))
        assert sorted(out.value_list()) == ["1", "3"]

    def test_min_values_max_propagates(self):
        out = req(IN, "a", "b", min_values=1).intersection(req(IN, "a", "b", min_values=2))
        assert out.min_values == 2

    def test_commutative_on_has(self):
        cases = [
            (req(IN, "a", "b"), req(NOT_IN, "b")),
            (req(EXISTS), req(IN, "x")),
            (req(GT, "2"), req(IN, "1", "3")),
            (req(NOT_IN, "a"), req(NOT_IN, "b")),
        ]
        for a, b in cases:
            ab, ba = a.intersection(b), b.intersection(a)
            for v in ["a", "b", "x", "1", "3", "7"]:
                assert ab.has(v) == ba.has(v)


class TestHasIntersection:
    @pytest.mark.parametrize(
        "a,b,expected",
        [
            (req(IN, "a"), req(IN, "a", "b"), True),
            (req(IN, "a"), req(IN, "b"), False),
            (req(IN, "a"), req(NOT_IN, "a"), False),
            (req(IN, "a", "b"), req(NOT_IN, "a"), True),
            (req(NOT_IN, "a"), req(NOT_IN, "b"), True),
            (req(EXISTS), req(DOES_NOT_EXIST), False),
            (req(GT, "5"), req(LT, "4"), False),
            (req(GT, "5"), req(IN, "10"), True),
            (req(LT, "5"), req(IN, "10"), False),
        ],
    )
    def test_matrix(self, a, b, expected):
        assert a.has_intersection(b) == expected
        assert b.has_intersection(a) == expected
        # consistency with full intersection
        inter = a.intersection(b)
        nonempty = inter.complement or len(inter.values) > 0
        assert nonempty == expected


class TestRequirements:
    def test_add_tightens(self):
        rs = Requirements([req(IN, "a", "b")])
        rs.add(req(IN, "b", "c"))
        assert rs.get("key").value_list() == ["b"]

    def test_get_undefined_is_exists(self):
        rs = Requirements()
        assert rs.get("anything").operator() == EXISTS

    def test_intersects_ok(self):
        a = Requirements([req(IN, "a", "b")])
        b = Requirements([req(IN, "b")])
        assert a.intersects(b) is None

    def test_intersects_conflict(self):
        a = Requirements([req(IN, "a")])
        b = Requirements([req(IN, "b")])
        assert a.intersects(b) is not None

    def test_intersects_notin_leniency(self):
        # both sides NotIn with empty intersection is forgiven
        a = Requirements([req(NOT_IN, "a")])
        b = Requirements([Requirement("key", DOES_NOT_EXIST)])
        # existing NotIn + incoming DoesNotExist -> forgiven
        assert a.intersects(b) is None

    def test_compatible_custom_label_undefined_rejected(self):
        node = Requirements()  # node defines nothing
        pod = Requirements([Requirement("custom", IN, ["x"])])
        assert node.compatible(pod) is not None

    def test_compatible_well_known_undefined_allowed(self):
        node = Requirements()
        pod = Requirements([Requirement("topology.kubernetes.io/zone", IN, ["z1"])])
        assert node.compatible(pod, allow_undefined=WELL_KNOWN_LABELS) is None

    def test_compatible_custom_label_notin_ok(self):
        node = Requirements()
        pod = Requirements([Requirement("custom", NOT_IN, ["x"])])
        assert node.compatible(pod) is None

    def test_label_normalization(self):
        r = Requirement("beta.kubernetes.io/arch", IN, ["amd64"])
        assert r.key == "kubernetes.io/arch"

    def test_labels_projection(self):
        rs = Requirements([Requirement("node.kubernetes.io/instance-type", IN, ["m5.large"])])
        assert rs.labels()["node.kubernetes.io/instance-type"] == "m5.large"

    def test_hostname_not_projected(self):
        rs = Requirements([Requirement("kubernetes.io/hostname", IN, ["h1"])])
        assert "kubernetes.io/hostname" not in rs.labels()

    def test_has_min_values(self):
        assert not Requirements([req(IN, "a")]).has_min_values()
        assert Requirements([req(IN, "a", min_values=1)]).has_min_values()
