"""Metrics docs-drift guard (ISSUE 9 satellite, the test_fault_docs
pattern): every metric name registered in code must have a row in
README's metrics reference table. A new series landed without
documentation is a failing build, not a dashboard surprise.

Registrations are extracted from the AST of every module under
karpenter_tpu/ (calls shaped `REGISTRY.counter|gauge|histogram("name",
...)`), so the guard tracks the source of truth without importing the
whole tree.
"""

import ast
import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "karpenter_tpu"
README = REPO / "README.md"

_METHODS = {"counter", "gauge", "histogram"}


def registered_metrics() -> dict[str, str]:
    """{metric name: relative module path} for every REGISTRY
    registration in the package."""
    out: dict[str, str] = {}
    for path in sorted(PKG.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHODS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "REGISTRY"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            out[node.args[0].value] = str(path.relative_to(REPO))
    return out


def _table_rows() -> list[str]:
    return [
        line for line in README.read_text().splitlines()
        if line.strip().startswith("|")
    ]


def test_every_registered_metric_has_a_readme_table_row():
    rows = _table_rows()
    missing = []
    for name, module in sorted(registered_metrics().items()):
        pattern = re.compile(r"^\|\s*`" + re.escape(name) + r"`\s*\|")
        if not any(pattern.match(row.strip()) for row in rows):
            missing.append(f"{name} ({module})")
    assert not missing, (
        "metrics registered in code without a row in README's metrics "
        f"reference table: {missing}"
    )


def test_readme_table_names_no_phantom_metrics():
    """The reverse direction: a README row claiming a karpenter_*
    metric that no code registers is stale documentation."""
    known = set(registered_metrics())
    phantom = []
    for row in _table_rows():
        m = re.match(r"^\|\s*`(karpenter_[a-z0-9_]+)`\s*\|", row.strip())
        if m and m.group(1) not in known:
            phantom.append(m.group(1))
    assert not phantom, (
        f"README metrics table rows with no code registration: {phantom}"
    )


def test_guard_reads_the_real_registrations():
    """Self-check: a refactor that moves the registry must not
    green-wash the guard by emptying the extraction."""
    names = set(registered_metrics())
    assert {
        "karpenter_nodeclaims_created_total",
        "karpenter_operator_last_tick_timestamp_seconds",
        "karpenter_operator_tick_duration_seconds",
        "karpenter_operator_step_duration_seconds",
        "karpenter_solver_phase_duration_seconds",
    } <= names
    assert len(names) >= 55
