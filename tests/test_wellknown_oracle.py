"""Custom-label operator matrix oracle.

The reference's "Well Known Labels" / "Scheduling Logic" contexts
(provisioning/scheduling/suite_test.go:932-1105): how each node-
affinity operator behaves against a label the NodePool does and does
not define, end to end through provisioning — plus the co-scheduling
consequences (compatible pods share a node, incompatible pods split)
and the Exists-does-not-overwrite rule.
"""

import pytest

from karpenter_tpu.cloudprovider.fake import make_instance_type
from karpenter_tpu.kube.objects import (
    Affinity,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
)
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod

LABEL = "example.com/tier"


def affinity_pod(name, op, values=(), key=LABEL):
    pod = mk_pod(name=name, cpu=0.5)
    pod.spec.affinity = Affinity(
        node_affinity=NodeAffinity(
            required=(
                NodeSelectorTerm(
                    match_expressions=(
                        NodeSelectorRequirement(
                            key=key, operator=op, values=tuple(values)
                        ),
                    )
                ),
            )
        )
    )
    return pod


def env_with_pool(pool_labels=None):
    env = Environment(types=[make_instance_type("c8", cpu=8)])
    pool = mk_nodepool("default")
    if pool_labels:
        pool.spec.template.labels.update(pool_labels)
    env.kube.create(pool)
    return env


class TestUndefinedKeyOperators:
    """suite_test.go:932-970 — the pool does NOT define the label."""

    @pytest.mark.parametrize(
        "op,values,schedules",
        [
            ("In", ["gold"], False),        # :932
            ("NotIn", ["gold"], True),      # :941
            ("Exists", [], False),          # :951
            ("DoesNotExist", [], True),     # :960
        ],
    )
    def test_operator_vs_undefined_key(self, op, values, schedules):
        env = env_with_pool()
        results = env.provision(affinity_pod("p", op, values))
        assert (results.scheduled_count == 1) == schedules
        assert (len(env.kube.nodes()) == 1) == schedules


class TestDefinedKeyOperators:
    """suite_test.go:979-1047 — the pool defines tier=gold."""

    @pytest.mark.parametrize(
        "op,values,schedules",
        [
            ("In", ["gold"], True),          # :979 matching value
            ("In", ["silver"], False),       # :1026 different value
            ("NotIn", ["gold"], False),      # :991 matching value
            ("NotIn", ["silver"], True),     # :1037 different value
            ("Exists", [], True),            # :1002
            ("DoesNotExist", [], False),     # :1014
        ],
    )
    def test_operator_vs_defined_key(self, op, values, schedules):
        env = env_with_pool({LABEL: "gold"})
        results = env.provision(affinity_pod("p", op, values))
        assert (results.scheduled_count == 1) == schedules

    def test_unconstrained_pod_ignores_pool_label(self):
        # suite_test.go:970 — a pod with no matching selector still
        # schedules onto the labeled pool
        env = env_with_pool({LABEL: "gold"})
        results = env.provision(mk_pod(cpu=0.5))
        assert results.scheduled_count == 1


class TestCoScheduling:
    def test_compatible_pods_share_a_node(self):
        # suite_test.go:1049 — In['gold'] and Exists agree: one node
        env = env_with_pool({LABEL: "gold"})
        env.provision(
            affinity_pod("a", "In", ["gold"]),
            affinity_pod("b", "Exists"),
        )
        assert len(env.kube.nodes()) == 1
        assert env.all_pods_bound()

    def test_incompatible_pods_split_nodes(self):
        # suite_test.go:1069 — In['gold'] and In['silver'] on a pool
        # whose template leaves the label free: two nodes, each
        # labeled for its pod
        env = Environment(types=[make_instance_type("c8", cpu=8)])
        pool = mk_nodepool("default")
        pool.spec.template.spec.requirements = [
            # pool admits both tiers; each claim resolves to one
            __import__(
                "karpenter_tpu.apis.v1.nodeclaim", fromlist=["RequirementSpec"]
            ).RequirementSpec(
                key=LABEL, operator="In", values=["gold", "silver"]
            )
        ]
        env.kube.create(pool)
        env.provision(
            affinity_pod("a", "In", ["gold"]),
            affinity_pod("b", "In", ["silver"]),
        )
        nodes = env.kube.nodes()
        assert len(nodes) == 2
        assert env.all_pods_bound()
        # each node materializes its pod's tier (launch.go:131 label
        # resolution -> registration sync)
        assert sorted(n.metadata.labels[LABEL] for n in nodes) == [
            "gold",
            "silver",
        ]

    def test_three_way_empty_intersection_splits(self):
        # In[g,s] / In[s,b] / In[g,b] intersect pairwise but jointly
        # empty — the decode-time incremental tightening must split
        # them instead of launching a claim whose tier requirement
        # collapses to DoesNotExist
        from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec
        from karpenter_tpu.cloudprovider.fake import make_instance_type

        env = Environment(types=[make_instance_type("c8", cpu=8)])
        pool = mk_nodepool("default")
        pool.spec.template.spec.requirements = [
            RequirementSpec(
                key=LABEL, operator="In",
                values=["gold", "silver", "bronze"],
            )
        ]
        env.kube.create(pool)
        results = env.provision(
            affinity_pod("gs", "In", ["gold", "silver"]),
            affinity_pod("sb", "In", ["silver", "bronze"]),
            affinity_pod("gb", "In", ["gold", "bronze"]),
        )
        assert results.scheduled_count == 3
        assert env.all_pods_bound()
        for claim in env.kube.node_claims():
            tier = [r for r in claim.spec.requirements if r.key == LABEL]
            assert tier and tier[0].operator == "In" and tier[0].values

    def test_gt_bound_survives_onto_claim(self):
        # a numeric Gt template requirement must reach the created
        # claim as Gt, not collapse to Exists (the provider re-checks
        # it at launch)
        from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec
        from karpenter_tpu.cloudprovider.fake import make_instance_type

        env = Environment(
            types=[
                make_instance_type(
                    "big", cpu=8,
                    extra_labels={"example.com/size": "4"},
                ),
            ]
        )
        pool = mk_nodepool("default")
        pool.spec.template.spec.requirements = [
            RequirementSpec(
                key="example.com/size", operator="Gt", values=("2",)
            )
        ]
        env.kube.create(pool)
        results = env.provision(mk_pod(cpu=0.5))
        assert results.scheduled_count == 1
        claim = env.kube.node_claims()[0]
        size = [r for r in claim.spec.requirements
                if r.key == "example.com/size"]
        assert size and size[0].operator == "Gt"
        assert list(size[0].values) == ["2"]

    def test_capacity_type_split_on_byo_node(self):
        # a BYO node without a capacity-type label leaves the key open:
        # a spot-requiring and an on-demand-requiring pod must not
        # share it (the reference's ExistingNode.Add tightens per pod)
        from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
        from karpenter_tpu.kube.objects import (
            Node,
            NodeCondition,
            NodeStatus,
            ObjectMeta,
        )

        env = Environment(types=[make_instance_type("c8", cpu=8)])
        env.kube.create(mk_nodepool("default"))
        env.kube.create(Node(
            metadata=ObjectMeta(
                name="byo",
                labels={
                    "kubernetes.io/arch": "amd64",
                    "kubernetes.io/os": "linux",
                    "kubernetes.io/hostname": "byo",
                },
            ),
            status=NodeStatus(
                capacity={"cpu": 8.0, "memory": 32 * GIB, "pods": 110.0},
                allocatable={"cpu": 8.0, "memory": 32 * GIB, "pods": 110.0},
                conditions=[NodeCondition(type="Ready", status="True")],
            ),
        ))
        spot = mk_pod(
            name="spot", cpu=0.5,
            node_selector={"karpenter.sh/capacity-type": "spot"},
        )
        od = mk_pod(
            name="od", cpu=0.5,
            node_selector={"karpenter.sh/capacity-type": "on-demand"},
        )
        results = env.provision(spot, od)
        assert results.scheduled_count == 2
        byo_pods = results.existing_assignments.get("byo", [])
        assert len(byo_pods) <= 1

    def test_exists_does_not_overwrite_value(self):
        # suite_test.go:1090 — pod A pins tier=gold on the claim; pod
        # B's Exists must join that node without widening the value
        env = Environment(types=[make_instance_type("c8", cpu=8)])
        pool = mk_nodepool("default")
        pool.spec.template.spec.requirements = [
            __import__(
                "karpenter_tpu.apis.v1.nodeclaim", fromlist=["RequirementSpec"]
            ).RequirementSpec(
                key=LABEL, operator="In", values=["gold", "silver"]
            )
        ]
        env.kube.create(pool)
        results = env.provision(
            affinity_pod("a", "In", ["gold"]),
            affinity_pod("b", "Exists"),
        )
        assert results.scheduled_count == 2
        assert len(results.new_node_plans) == 1
        claim = env.kube.node_claims()[0]
        tier = [
            r for r in claim.spec.requirements if r.key == LABEL
        ]
        assert tier and list(tier[0].values) == ["gold"]
