"""Requirement algebra property fuzz.

The dense solver, the claim tightening, the drift detector and the
compat matrix all ride on `Requirement`/`Requirements` set algebra
(pkg/scheduling/requirement.go / requirements.go semantics). This
suite checks the algebra against a brute-force model: every
requirement denotes a subset of a small finite universe (plus "the
label is absent"), and each operation must match its set-theoretic
meaning exactly.

Randomized but deterministic (seeded), mirroring the reference's
property-heavy requirement_test.go/requirements_test.go families.
"""

import random

import pytest

from karpenter_tpu.scheduling.requirement import (
    DOES_NOT_EXIST,
    EXISTS,
    GT,
    IN,
    LT,
    NOT_IN,
    Requirement,
)

# the value universe: a few strings plus numerics so Gt/Lt engage
UNIVERSE = ["0", "1", "2", "5", "9", "a", "b"]


def denote(req: Requirement) -> set:
    """The subset of UNIVERSE a requirement allows."""
    return {v for v in UNIVERSE if req.has(v)}


def random_requirement(rng: random.Random) -> Requirement:
    op = rng.choice([IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT])
    if op in (IN, NOT_IN):
        k = rng.randint(1, 4)
        return Requirement("k", op, rng.sample(UNIVERSE, k))
    if op in (GT, LT):
        return Requirement("k", op, [rng.choice(["0", "1", "2", "5", "9"])])
    return Requirement("k", op, [])


@pytest.mark.parametrize("seed", range(40))
def test_intersection_matches_set_semantics(seed):
    rng = random.Random(seed)
    a = random_requirement(rng)
    b = random_requirement(rng)
    got = denote(a.intersection(b))
    want = denote(a) & denote(b)
    assert got == want, (repr(a), repr(b), got, want)


@pytest.mark.parametrize("seed", range(40))
def test_has_intersection_agrees_with_intersection(seed):
    rng = random.Random(seed + 1000)
    a = random_requirement(rng)
    b = random_requirement(rng)
    # has_intersection is allocation-free; it may only differ from the
    # materialized intersection OUTSIDE the finite universe (complement
    # sets are infinite), so only assert the implication that matters:
    # a non-empty denoted intersection must be detected
    if denote(a) & denote(b):
        assert a.has_intersection(b), (repr(a), repr(b))


@pytest.mark.parametrize("seed", range(40))
def test_intersection_commutes_and_is_idempotent(seed):
    rng = random.Random(seed + 2000)
    a = random_requirement(rng)
    b = random_requirement(rng)
    ab = denote(a.intersection(b))
    ba = denote(b.intersection(a))
    assert ab == ba
    assert denote(a.intersection(a)) == denote(a)


@pytest.mark.parametrize("seed", range(40))
def test_intersection_associates(seed):
    rng = random.Random(seed + 3000)
    a, b, c = (random_requirement(rng) for _ in range(3))
    left = denote(a.intersection(b).intersection(c))
    right = denote(a.intersection(b.intersection(c)))
    assert left == right


def roundtrip(req: Requirement) -> Requirement:
    """Serialize through spec_entries() — the claim-tightening path —
    and reconstruct by intersecting the emitted entries, exactly as
    Requirements.add does when a claim spec is parsed back."""
    rebuilt = None
    for op, values, min_values in req.spec_entries():
        entry = Requirement("k", op, values, min_values=min_values)
        rebuilt = entry if rebuilt is None else rebuilt.intersection(entry)
    assert rebuilt is not None
    return rebuilt


@pytest.mark.parametrize("seed", range(40))
def test_operator_roundtrip_preserves_denotation(seed):
    # serializing a requirement to claim spec entries and parsing them
    # back must not change what it allows — including Gt/Lt bounds,
    # which emit as their own entries
    rng = random.Random(seed + 4000)
    a = random_requirement(rng)
    rebuilt = roundtrip(a)
    assert denote(rebuilt) == denote(a), (repr(a), a.spec_entries())


@pytest.mark.parametrize("seed", range(40))
def test_intersection_roundtrip_preserves_denotation(seed):
    # intersections produce the hard shapes a single constructor never
    # does (NotIn + bounds on one requirement); the round-trip must
    # carry those exactly
    rng = random.Random(seed + 5000)
    a = random_requirement(rng).intersection(random_requirement(rng))
    rebuilt = roundtrip(a)
    assert denote(rebuilt) == denote(a), (repr(a), a.spec_entries())


@pytest.mark.parametrize("seed", range(20))
def test_roundtrip_preserves_min_values(seed):
    rng = random.Random(seed + 6000)
    a = random_requirement(rng)
    a.min_values = rng.randint(1, 3)
    rebuilt = roundtrip(a)
    assert rebuilt.min_values == a.min_values
    assert denote(rebuilt) == denote(a)
