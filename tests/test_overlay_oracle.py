"""NodeOverlay oracle suite, ported from the reference's nodeoverlay
suite_test.go families: price adjustments (absolute and percentage),
capacity injection, requirement-scoped application, multi-overlay
weight resolution, and non-overlapping coexistence.
"""

import pytest

from karpenter_tpu.apis.v1alpha1.nodeoverlay import (
    COND_OVERLAY_VALIDATION,
    NodeOverlay,
    NodeOverlayController,
    NodeOverlaySpec,
    OverlayCloudProvider,
    adjusted_price,
)
from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec
from karpenter_tpu.cloudprovider.fake import (
    GIB,
    FakeCloudProvider,
    make_instance_type,
)
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.kube.objects import ObjectMeta


def _types():
    return [
        make_instance_type("small", cpu=2, memory=8 * GIB, price=1.0),
        make_instance_type("big", cpu=16, memory=64 * GIB, price=8.0,
                           arch="arm64"),
    ]


def _store(*overlays):
    kube = KubeClient()
    for i, ov in enumerate(overlays):
        if not ov.metadata.name or ov.metadata.name.startswith("pool-"):
            ov.metadata.name = f"ov-{i}"
        kube.create(ov)
    provider = OverlayCloudProvider(FakeCloudProvider(_types()), kube)
    NodeOverlayController(kube, provider).reconcile()
    return kube, provider


def _prices(provider, name):
    return sorted(
        o.price for it in provider.get_instance_types(None)
        if it.name == name for o in it.offerings
    )


class TestPriceAdjustments:
    def test_zero_overlays_identity(self):
        # suite_test.go:114
        kube, provider = _store()
        base = FakeCloudProvider(_types())
        assert _prices(provider, "small") == sorted(
            o.price for it in base.get_instance_types(None)
            if it.name == "small" for o in it.offerings
        )

    @pytest.mark.parametrize("change,base,expected", [
        ("+0.5", 1.0, 1.5),
        ("-0.25", 1.0, 0.75),
        ("+50%", 2.0, 3.0),
        ("-10%", 2.0, 1.8),
    ])
    def test_adjustment_math(self, change, base, expected):
        # types.go:369-401 AdjustedPrice
        assert adjusted_price(base, change) == pytest.approx(expected)

    def test_adjustment_never_negative(self):
        assert adjusted_price(1.0, "-5.0") == 0.0

    def test_percentage_adjustment_applies_through_provider(self):
        kube, provider = _store(
            NodeOverlay(spec=NodeOverlaySpec(price_adjustment="-50%"))
        )
        base = sorted(
            o.price for it in FakeCloudProvider(_types()).get_instance_types(None)
            if it.name == "small" for o in it.offerings
        )
        got = _prices(provider, "small")
        assert got == pytest.approx([p * 0.5 for p in base])


class TestRequirementScoping:
    def test_overlay_applies_only_to_selected_types(self):
        # suite_test.go:1825/1989: requirement-scoped overlays leave
        # non-matching types untouched
        overlay = NodeOverlay(spec=NodeOverlaySpec(
            price="0.05",
            requirements=[RequirementSpec(
                key="kubernetes.io/arch", operator="In", values=("arm64",)
            )],
        ))
        kube, provider = _store(overlay)
        assert set(_prices(provider, "big")) == {0.05}
        assert 0.05 not in set(_prices(provider, "small"))


class TestCapacityInjection:
    def test_capacity_adds_extended_resource(self):
        # suite_test.go:2017
        overlay = NodeOverlay(spec=NodeOverlaySpec(
            capacity={"example.com/accelerator": 2.0},
        ))
        kube, provider = _store(overlay)
        for it in provider.get_instance_types(None):
            assert it.capacity.get("example.com/accelerator") == 2.0

    def test_capacity_from_multiple_nonconflicting_overlays(self):
        # suite_test.go:2047: disjoint capacity keys both apply
        a = NodeOverlay(metadata=ObjectMeta(name="a"), spec=NodeOverlaySpec(
            capacity={"example.com/a": 1.0}))
        b = NodeOverlay(metadata=ObjectMeta(name="b"), spec=NodeOverlaySpec(
            capacity={"example.com/b": 2.0}))
        kube, provider = _store(a, b)
        assert a.status_conditions.is_true(COND_OVERLAY_VALIDATION)
        assert b.status_conditions.is_true(COND_OVERLAY_VALIDATION)
        for it in provider.get_instance_types(None):
            assert it.capacity.get("example.com/a") == 1.0
            assert it.capacity.get("example.com/b") == 2.0


class TestWeightResolution:
    def test_higher_weight_wins_price(self):
        # suite_test.go:2218
        low = NodeOverlay(metadata=ObjectMeta(name="low"),
                          spec=NodeOverlaySpec(weight=1, price="2.0"))
        high = NodeOverlay(metadata=ObjectMeta(name="high"),
                           spec=NodeOverlaySpec(weight=9, price="0.5"))
        kube, provider = _store(low, high)
        assert set(_prices(provider, "small")) == {0.5}

    def test_mutually_exclusive_requirements_both_apply(self):
        # suite_test.go:898: same weight, disjoint selectors -> no
        # conflict, each scope gets its own price
        amd = NodeOverlay(metadata=ObjectMeta(name="amd"), spec=NodeOverlaySpec(
            weight=5, price="0.1",
            requirements=[RequirementSpec(
                key="kubernetes.io/arch", operator="In", values=("amd64",))],
        ))
        arm = NodeOverlay(metadata=ObjectMeta(name="arm"), spec=NodeOverlaySpec(
            weight=5, price="0.2",
            requirements=[RequirementSpec(
                key="kubernetes.io/arch", operator="In", values=("arm64",))],
        ))
        kube, provider = _store(amd, arm)
        assert amd.status_conditions.is_true(COND_OVERLAY_VALIDATION)
        assert arm.status_conditions.is_true(COND_OVERLAY_VALIDATION)
        assert set(_prices(provider, "small")) == {0.1}
        assert set(_prices(provider, "big")) == {0.2}
