"""Eviction-queue backoff + operator binding re-queue tests.

The reference's eviction queue retries PDB-blocked (429) evictions
through an exponential rate limiter (terminator/eviction.go); the
operator re-provisions pods whose planned claim never materialized.
"""

import time

from karpenter_tpu.kube.objects import (
    LabelSelector,
    ObjectMeta,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
)
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.lifecycle.termination import (
    EVICT_BACKOFF_BASE_SECONDS,
    EVICT_BACKOFF_MAX_SECONDS,
    EvictionQueue,
)
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def _blocked_env():
    env = Environment(types=[make_instance_type("c8", cpu=8, memory=32 * GIB)])
    env.kube.create(mk_nodepool("default"))
    pod = mk_pod(cpu=0.5, labels={"app": "web"})
    env.provision(pod)
    env.kube.create(
        PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb"),
            spec=PodDisruptionBudgetSpec(
                selector=LabelSelector.of({"app": "web"}), max_unavailable=0
            ),
        )
    )
    return env, env.kube.get_pod("default", pod.metadata.name)


class TestEvictionBackoff:
    def test_blocked_eviction_backs_off_exponentially(self):
        env, pod = _blocked_env()
        q = EvictionQueue(env.kube)
        t0 = 1000.0
        assert not q.evict(pod, now=t0)
        assert "pdb" in q.blocked[pod.key]
        # within the backoff window nothing is attempted (attempt count
        # unchanged even though the PDB would still block)
        assert not q.evict(pod, now=t0 + EVICT_BACKOFF_BASE_SECONDS / 2)
        assert q._attempts[pod.key] == 1
        # after the window the retry happens and doubles the backoff
        assert not q.evict(pod, now=t0 + EVICT_BACKOFF_BASE_SECONDS * 1.5)
        assert q._attempts[pod.key] == 2
        # backoff saturates at the cap
        for i in range(12):
            q.evict(pod, now=t0 + 100.0 + 20.0 * i)
        assert (
            q._retry_at[pod.key] - (t0 + 100.0 + 20.0 * 11)
            <= EVICT_BACKOFF_MAX_SECONDS + 1e-9
        )

    def test_force_bypasses_backoff_and_clears_state(self):
        env, pod = _blocked_env()
        q = EvictionQueue(env.kube)
        assert not q.evict(pod, now=1000.0)
        assert q.evict(pod, now=1000.01, force=True)
        assert pod.key not in q._attempts
        assert pod.key not in q.blocked

    def test_prune_drops_vanished_pods(self):
        env, pod = _blocked_env()
        q = EvictionQueue(env.kube)
        q.evict(pod, now=1000.0)
        assert pod.key in q.blocked and pod.key in q._retry_at
        env.kube.delete(pod, now=1000.0)
        assert pod.key not in {p.key for p in env.kube.pods()}
        q.prune()
        assert pod.key not in q.blocked
        assert pod.key not in q._retry_at
        assert pod.key not in q._attempts


class TestBindingRequeue:
    def test_claim_death_requeues_pods_through_batcher(self):
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.kube.client import KubeClient

        kube = KubeClient()
        cloud = KwokCloudProvider(
            kube, types=[make_instance_type("c8", cpu=8, memory=32 * GIB)]
        )
        op = Operator(kube=kube, cloud_provider=cloud)
        kube.create(mk_nodepool("default"))
        kube.create(mk_pod(name="orphan", cpu=1.0))
        now = time.time()
        op.provisioner.batcher.trigger(now=now)
        results = op.provisioner.reconcile(now=now + 30)
        assert results.new_node_plans
        op._pending_bindings.append(results)
        # kill the claim before any node materializes (ICE analogue)
        claim = kube.get_node_claim(results.new_node_plans[0].claim_name)
        kube.delete(claim, now=now + 30)
        kube.remove_finalizer(claim, claim.metadata.finalizers[0]) if (
            claim.metadata.finalizers
        ) else None
        op.provisioner.batcher.reset()
        op._bind_pending()
        # the pod is still pending and the batcher was re-triggered so
        # the next tick re-provisions it
        assert not op._pending_bindings
        assert op.provisioner.batcher._pending
