"""Node-repair circuit breaker boundary (ISSUE 8 satellite).

`lifecycle/garbagecollection.NodeHealthController` abstains when MORE
than 20% of the cluster is unhealthy (node/health/controller.go's
circuit breaker). The boundary semantics are exact and worth pinning:

- unhealthy fraction EXACTLY at the threshold (20%) -> breaker stays
  closed, repairs proceed;
- one node past it -> breaker opens, and an open breaker leaves every
  node untouched (no claim deletions, not even for the unhealthy
  ones);
- the single-node cluster escape hatch (`len(nodes) > 1`) repairs a
  100%-unhealthy singleton.
"""

import time

from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.cloudprovider.types import RepairPolicy
from karpenter_tpu.lifecycle.garbagecollection import (
    UNHEALTHY_CLUSTER_THRESHOLD,
    NodeHealthController,
)
from karpenter_tpu.kube.objects import NodeCondition
from karpenter_tpu.operator.options import FeatureGates, Options
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod

POLICY = RepairPolicy(
    condition_type="BadDevice", condition_status="True",
    toleration_duration=60.0,
)


def _cluster(n_nodes: int):
    """n one-pod nodes with repair enabled."""
    env = Environment(
        types=[make_instance_type("c4", cpu=4, memory=16 * GIB)]
    )
    env.kube.create(mk_nodepool("default"))
    env.provision(
        *[mk_pod(name=f"p-{i}", cpu=2.0) for i in range(n_nodes)],
        now=0.0,
    )
    assert len(env.kube.nodes()) == n_nodes
    env.cloud._repair_policies = [POLICY]
    controller = NodeHealthController(
        env.kube, env.cloud,
        Options(feature_gates=FeatureGates(node_repair=True)),
    )
    return env, controller


def _mark_unhealthy(env, count: int, since: float = 0.0):
    nodes = sorted(env.kube.nodes(), key=lambda n: n.metadata.name)
    for node in nodes[:count]:
        node.status.conditions.append(NodeCondition(
            type="BadDevice", status="True",
            last_transition_time=since,
        ))
        env.kube.touch(node)


class TestRepairBreakerBoundary:
    def test_exactly_at_threshold_repairs(self):
        """1/5 unhealthy = 20% exactly: NOT strictly greater than the
        threshold, so the breaker stays closed and the node repairs."""
        env, controller = _cluster(5)
        _mark_unhealthy(env, 1)
        assert 1 / 5 == UNHEALTHY_CLUSTER_THRESHOLD
        repaired = controller.reconcile(now=100.0)
        assert len(repaired) == 1
        deleting = [
            c for c in env.kube.node_claims()
            if c.metadata.deletion_timestamp is not None
        ]
        assert len(deleting) == 1

    def test_one_past_threshold_opens_breaker(self):
        """2/5 unhealthy = 40% > 20%: the breaker opens and NOTHING is
        touched — no claim gains a deletion timestamp, unhealthy nodes
        included."""
        env, controller = _cluster(5)
        _mark_unhealthy(env, 2)
        before = {
            c.metadata.name: c.metadata.deletion_timestamp
            for c in env.kube.node_claims()
        }
        repaired = controller.reconcile(now=100.0)
        assert repaired == []
        after = {
            c.metadata.name: c.metadata.deletion_timestamp
            for c in env.kube.node_claims()
        }
        assert after == before, "open breaker must leave nodes untouched"

    def test_breaker_open_is_not_sticky(self):
        """The breaker is a per-reconcile verdict: once the unhealthy
        fraction drops back to the threshold, repairs resume."""
        env, controller = _cluster(5)
        _mark_unhealthy(env, 2)
        assert controller.reconcile(now=100.0) == []
        # one node recovers: its condition flips away from the policy
        nodes = sorted(env.kube.nodes(), key=lambda n: n.metadata.name)
        nodes[0].status.conditions = [
            c for c in nodes[0].status.conditions
            if c.type != "BadDevice"
        ]
        env.kube.touch(nodes[0])
        assert len(controller.reconcile(now=101.0)) == 1

    def test_singleton_cluster_repairs_despite_full_unhealthy(self):
        """len(nodes) > 1 gates the breaker: a 100%-unhealthy
        single-node cluster still repairs (abstaining forever would
        wedge it)."""
        env, controller = _cluster(1)
        _mark_unhealthy(env, 1)
        assert len(controller.reconcile(now=100.0)) == 1

    def test_toleration_duration_gates_eligibility(self):
        """A condition younger than the policy's toleration never
        counts as unhealthy — neither for repair nor for the breaker
        denominator."""
        env, controller = _cluster(5)
        _mark_unhealthy(env, 1, since=90.0)  # 10s old vs 60s toleration
        assert controller.reconcile(now=100.0) == []
