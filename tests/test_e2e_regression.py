"""Regression-suite depth, ported from test/suites/regression: drift
budget families (empty / non-empty delete / replace / fully-blocking /
scheduled-window), drift protection when replacements never
register/initialize or PDBs are unhealthy, expiration replacing a node
while rescheduling all pods, and runaway guards under sustained churn
with consolidation enabled.
"""

import time

from karpenter_tpu.apis.v1.labels import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.apis.v1.nodeclaim import COND_DRIFTED
from karpenter_tpu.apis.v1.nodepool import Budget, REASON_DRIFTED
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.kube.objects import (
    LabelSelector,
    ObjectMeta,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
)
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def types():
    return [
        make_instance_type("c4", cpu=4, memory=16 * GIB, price=2.0),
        make_instance_type("c8", cpu=8, memory=32 * GIB, price=4.0),
    ]


def make_env(budgets=None):
    env = Environment(types=types())
    pool = mk_nodepool("default")
    pool.spec.disruption.consolidate_after = "0s"
    if budgets is not None:
        pool.spec.disruption.budgets = budgets
    env.kube.create(pool)
    return env


def mark_all_drifted(env, now):
    """Induce REAL drift: bump the pool template so the stored
    nodepool-hash annotations no longer match — the conditions
    controller then marks every claim Drifted itself (and keeps it
    marked across recomputes, unlike a hand-set condition)."""
    pool = env.kube.get_node_pool("default")
    pool.spec.template.labels["drift-rev"] = str(now)
    env.kube.update(pool)
    env.conditions.reconcile_all(now=now)


class TestDriftBudgets:
    def _fleet(self, env, n_nodes, pods_per_node=1):
        pods = []
        for _ in range(n_nodes):
            batch = [mk_pod(cpu=2.0, memory=GIB) for _ in range(pods_per_node)]
            env.provision(*batch)
            pods.extend(batch)
        assert len(env.kube.nodes()) == n_nodes
        return pods

    def test_budget_paces_nonempty_drift_roll(self):
        """'should respect budgets for non-empty replace drift': one
        drifted node rolls per round under nodes=1."""
        env = make_env(budgets=[Budget(nodes="1")])
        self._fleet(env, 3)
        now = time.time() + 60
        mark_all_drifted(env, now)
        command = env.reconcile_disruption(now=now)
        assert command is not None and command.reason == REASON_DRIFTED
        assert len(command.candidates) == 1
        # two originals remain this round (plus any replacement)
        drifted_left = sum(
            1 for c in env.kube.node_claims()
            if c.status_conditions.is_true(COND_DRIFTED)
        )
        assert drifted_left >= 2

    def test_fully_blocking_budget_stops_drift(self):
        """'should not allow drift if the budget is fully blocking'."""
        env = make_env(budgets=[Budget(nodes="0")])
        self._fleet(env, 2)
        now = time.time() + 60
        mark_all_drifted(env, now)
        command = env.reconcile_disruption(now=now)
        assert command is None
        assert len(env.kube.nodes()) == 2

    def test_scheduled_window_blocks_outside_window(self):
        """'fully blocking during a scheduled time': a 0-node budget
        active in a cron window pins the fleet inside that window."""
        import datetime

        now = time.time() + 60
        # timezone.utc, not datetime.UTC: the UTC alias only exists on
        # py3.11+ and this suite must pass on 3.10
        hour = datetime.datetime.fromtimestamp(now, datetime.timezone.utc).hour
        env = make_env(budgets=[
            Budget(nodes="0", schedule=f"* {hour} * * *", duration="1h"),
        ])
        self._fleet(env, 2)
        mark_all_drifted(env, now)
        assert env.reconcile_disruption(now=now) is None
        # outside the window (2h later) the default budget applies
        later = now + 2 * 3600
        mark_all_drifted(env, later)
        env.pod_events.reconcile_all(now=later)
        env.conditions.reconcile_all(now=later)
        command = env.disruption.reconcile(now=later)
        assert command is not None

    def test_empty_drifted_nodes_roll_without_replacements(self):
        """'should respect budgets for empty drift': empty drifted
        nodes delete (no replacement) under the budget pace."""
        env = make_env(budgets=[Budget(nodes="1")])
        pods = self._fleet(env, 2)
        for pod in pods:
            env.kube.delete(env.kube.get_pod("default", pod.metadata.name))
        now = time.time() + 60
        mark_all_drifted(env, now)
        command = env.reconcile_disruption(now=now)
        assert command is not None
        assert command.replacement_count == 0
        assert len(env.kube.nodes()) == 1


class TestDriftProtection:
    def test_drifted_node_kept_while_replacement_unregistered(self):
        """'should not disrupt a drifted node if the replacement node
        never registers': the candidate holds until the replacement
        initializes; the command eventually rolls back."""
        env = make_env()
        pod = mk_pod(cpu=2.0, memory=GIB)
        env.provision(pod)
        env.cloud.registration_delay = 10_000.0
        now = time.time() + 60
        mark_all_drifted(env, now)
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        command = env.disruption.reconcile(now=now)
        assert command is not None and command.reason == REASON_DRIFTED
        for step in range(5):
            env.lifecycle.reconcile_all(now=now + step)
            env.disruption.queue.reconcile(now=now + step)
        # the drifted claim is still alive — never deleted ahead of its
        # replacement's initialization
        victim = command.candidates[0].state_node.node_claim
        live = env.kube.get_node_claim(victim.metadata.name)
        assert live is not None and live.metadata.deletion_timestamp is None
        assert env.all_pods_bound()

    def test_drift_blocked_by_unhealthy_pdb(self):
        """'should not drift any nodes if their PodDisruptionBudgets
        are unhealthy'."""
        env = make_env()
        pod = mk_pod(cpu=2.0, memory=GIB, labels={"app": "guarded"})
        env.provision(pod)
        env.kube.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb"),
            spec=PodDisruptionBudgetSpec(
                selector=LabelSelector.of({"app": "guarded"}),
                min_available=1,
            ),
        ))
        now = time.time() + 60
        mark_all_drifted(env, now)
        command = env.reconcile_disruption(now=now)
        assert command is None
        assert len(env.kube.nodes()) == 1


class TestExpirationRoll:
    def test_expired_node_replaced_single_node_all_pods(self):
        """'should replace expired node with a single node and schedule
        all pods': expiry force-deletes the claim; its pods reschedule
        together onto one replacement."""
        env = Environment(types=types())
        pool = mk_nodepool("default")
        pool.spec.template.spec.expire_after = "1h"
        env.kube.create(pool)
        pods = [mk_pod(cpu=1.0, memory=GIB) for _ in range(3)]
        start = time.time()
        env.provision(*pods, now=start)
        assert len(env.kube.nodes()) == 1
        first_node = env.kube.nodes()[0].metadata.name
        later = start + 3700
        for _ in range(6):
            env.expiration.reconcile_all(now=later)
            env.reconcile_disruption(now=later)
            later += 2
        nodes = env.kube.nodes()
        assert len(nodes) == 1
        assert nodes[0].metadata.name != first_node
        assert env.all_pods_bound()


class TestRunawayGuards:
    def test_no_runaway_with_consolidation_under_churn(self):
        """chaos_test.go 'Runaway Scale-Up' with consolidation on:
        sustained create/delete churn must not grow the fleet beyond
        the workload's true demand."""
        env = make_env()
        now = time.time()
        peak = 0
        for round_i in range(6):
            pods = [mk_pod(cpu=2.0, memory=GIB) for _ in range(4)]
            env.provision(*pods, now=now)
            peak = max(peak, len(env.kube.nodes()))
            # half the workload leaves; consolidation reacts
            for pod in pods[:2]:
                env.kube.delete(env.kube.get_pod("default", pod.metadata.name))
            now += 120
            env.reconcile_disruption(now=now)
            now += 10
        # demand never exceeds 4 pods x 2cpu = 8cpu = 2 c4 nodes (or 1
        # c8); churn must not accumulate capacity beyond a small factor
        assert len(env.kube.nodes()) <= 4
        assert peak <= 6

    def test_scale_to_zero_and_back(self):
        env = make_env()
        pods = [mk_pod(cpu=2.0, memory=GIB) for _ in range(4)]
        env.provision(*pods)
        assert env.kube.nodes()
        for pod in pods:
            env.kube.delete(env.kube.get_pod("default", pod.metadata.name))
        now = time.time() + 120
        for _ in range(4):
            env.reconcile_disruption(now=now)
            now += 5
        assert not env.kube.nodes()
        # and back up
        env.provision(mk_pod(cpu=2.0, memory=GIB), now=now)
        assert len(env.kube.nodes()) == 1
        assert env.all_pods_bound()


class TestPerfSmoke:
    """The regression perf smoke (test/suites/regression/
    perf_test.go:36-80): 100 replicas through provision, a full drift
    roll, and a full expiration roll — interval-timed, with generous
    wall bounds as the regression tripwire."""

    REPLICAS = 100

    def _fleet(self):
        env = make_env()
        pods = [mk_pod(name=f"w-{i}", cpu=1.0) for i in range(self.REPLICAS)]
        t0 = time.perf_counter()
        env.provision(*pods)
        provision_s = time.perf_counter() - t0
        bound = [p for p in env.kube.pods() if p.spec.node_name]
        assert len(bound) == self.REPLICAS
        return env, provision_s

    def test_provision_100_replicas(self):
        env, provision_s = self._fleet()
        assert provision_s < 30.0, f"provisioning took {provision_s:.1f}s"
        assert env.kube.nodes(), "no nodes launched"

    def test_drift_roll_100_replicas(self):
        env, _ = self._fleet()
        before = {c.metadata.name for c in env.kube.node_claims()}
        now = time.time() + 120
        mark_all_drifted(env, now)
        t0 = time.perf_counter()
        for i in range(120):
            if time.perf_counter() - t0 > 60.0:
                break  # the wall bound below reports the regression
            now += 11
            env.reconcile_disruption(now=now)
            claims = [c for c in env.kube.node_claims()
                      if c.metadata.deletion_timestamp is None]
            if claims and not (before & {c.metadata.name for c in claims}):
                break
        drift_s = time.perf_counter() - t0
        live = [c for c in env.kube.node_claims()
                if c.metadata.deletion_timestamp is None]
        assert live and not (before & {c.metadata.name for c in live}), \
            "drift roll never completed"
        bound = [p for p in env.kube.pods()
                 if p.spec.node_name and not p.is_terminal()]
        assert len(bound) == self.REPLICAS, "pods lost during the roll"
        assert drift_s < 60.0, f"drift roll took {drift_s:.1f}s"

    def test_expiration_roll_100_replicas(self):
        env, _ = self._fleet()
        pool = env.kube.get_node_pool("default")
        pool.spec.template.spec.expire_after = "1h"
        env.kube.touch(pool)
        # propagate expireAfter onto existing claims the way hygiene
        # does, then jump past the lifetime
        for claim in env.kube.node_claims():
            claim.spec.expire_after = "1h"
        before = {c.metadata.name for c in env.kube.node_claims()}
        base = min(c.metadata.creation_timestamp
                   for c in env.kube.node_claims())
        now = base + 3601
        t0 = time.perf_counter()
        for i in range(120):
            if time.perf_counter() - t0 > 60.0:
                break  # the wall bound below reports the regression
            env.expiration.reconcile_all(now=now)
            env.reconcile_disruption(now=now)
            now += 11
            claims = [c for c in env.kube.node_claims()
                      if c.metadata.deletion_timestamp is None]
            if claims and not (before & {c.metadata.name for c in claims}):
                break
        expire_s = time.perf_counter() - t0
        live = [c for c in env.kube.node_claims()
                if c.metadata.deletion_timestamp is None]
        assert live and not (before & {c.metadata.name for c in live}), \
            "expiration roll never completed"
        bound = [p for p in env.kube.pods()
                 if p.spec.node_name and not p.is_terminal()]
        assert len(bound) == self.REPLICAS, "pods lost during the roll"
        assert expire_s < 60.0, f"expiration roll took {expire_s:.1f}s"
