"""Disruption orchestration depth: per-reason budgets, concurrent
command isolation, replacement-failure rollback, and retry deadlines.

Ported scenario families: disruption/budgets (per-reason budget caps,
helpers.go:231-280 + nodepool.go:345-389), orchestration queue
(queue.go:137-246 waitOrTerminate, rollback on replacement death,
retry deadline), and the cross-reason method ordering
(controller.go:98-112).
"""

import time

from karpenter_tpu.apis.v1.labels import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.apis.v1.nodeclaim import COND_DRIFTED
from karpenter_tpu.apis.v1.nodepool import (
    Budget,
    REASON_DRIFTED,
    REASON_EMPTY,
    REASON_UNDERUTILIZED,
)
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.disruption.engine import COMMAND_TIMEOUT_SECONDS
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def consolidation_types():
    return [
        make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0),
        make_instance_type("c4", cpu=4, memory=16 * GIB, price=3.0),
        make_instance_type("c8", cpu=8, memory=32 * GIB, price=5.0),
    ]


def make_env(budgets=None, consolidate_after="0s"):
    env = Environment(types=consolidation_types())
    pool = mk_nodepool("default")
    pool.spec.disruption.consolidate_after = consolidate_after
    if budgets is not None:
        pool.spec.disruption.budgets = budgets
    env.kube.create(pool)
    return env


def empty_nodes(env, count):
    """Provision `count` pods one at a time (one small node each) then
    delete the pods, leaving empty consolidatable nodes."""
    pods = []
    for _ in range(count):
        pod = mk_pod(cpu=1.0, memory=2 * GIB)
        env.provision(pod)
        pods.append(pod)
    for pod in pods:
        env.kube.delete(env.kube.get_pod("default", pod.metadata.name))
    return pods


class TestPerReasonBudgets:
    def test_reason_scoped_budget_caps_only_that_reason(self):
        """A zero budget scoped to Underutilized leaves Empty free
        (nodepool.go:345-367 reasons filter)."""
        env = make_env(budgets=[
            Budget(nodes="0", reasons=[REASON_UNDERUTILIZED]),
        ])
        empty_nodes(env, 2)
        command = env.reconcile_disruption(now=time.time() + 60)
        assert command is not None and command.reason == REASON_EMPTY
        assert not env.kube.nodes()

    def test_empty_scoped_zero_budget_blocks_emptiness(self):
        """With consolidation policy WhenEmpty (so no Underutilized
        method can pick the nodes up under ITS budget), a zero Empty
        budget pins the empty nodes."""
        env = Environment(types=consolidation_types())
        pool = mk_nodepool("default")
        pool.spec.disruption.consolidate_after = "0s"
        pool.spec.disruption.consolidation_policy = "WhenEmpty"
        pool.spec.disruption.budgets = [
            Budget(nodes="0", reasons=[REASON_EMPTY]),
        ]
        env.kube.create(pool)
        empty_nodes(env, 2)
        command = env.reconcile_disruption(now=time.time() + 60)
        assert command is None
        assert len(env.kube.nodes()) == 2

    def test_consolidation_may_delete_empty_nodes_under_its_own_budget(self):
        """An Empty-scoped zero budget does NOT stop the Underutilized
        methods from retiring empty nodes — each method consumes its
        own reason's budget (controller.go:98-112 + helpers.go:231)."""
        env = make_env(budgets=[
            Budget(nodes="0", reasons=[REASON_EMPTY]),
        ])
        empty_nodes(env, 2)
        command = env.reconcile_disruption(now=time.time() + 60)
        assert command is not None
        assert command.reason == REASON_UNDERUTILIZED
        assert not env.kube.nodes()

    def test_unscoped_budget_caps_all_reasons(self):
        env = make_env(budgets=[Budget(nodes="1")])
        empty_nodes(env, 3)
        now = time.time() + 60
        command = env.reconcile_disruption(now=now)
        assert command is not None and command.reason == REASON_EMPTY
        # only one node may go this round
        assert len(command.candidates) == 1
        assert len(env.kube.nodes()) == 2

    def test_percentage_budget_rounds_up(self):
        """'10%' of 3 nodes allows ceil(0.3) = 1 disruption
        (nodepool.go MaxUnavailable semantics)."""
        env = make_env(budgets=[Budget(nodes="34%")])
        empty_nodes(env, 3)
        command = env.reconcile_disruption(now=time.time() + 60)
        assert command is not None
        assert len(command.candidates) == 2  # ceil(0.34 * 3) = 2

    def test_multiple_budgets_minimum_wins(self):
        env = make_env(budgets=[
            Budget(nodes="2"),
            Budget(nodes="1", reasons=[REASON_EMPTY]),
        ])
        empty_nodes(env, 3)
        command = env.reconcile_disruption(now=time.time() + 60)
        assert command is not None and command.reason == REASON_EMPTY
        assert len(command.candidates) == 1

    def test_drift_budget_blocks_drift_only(self):
        env = make_env(budgets=[
            Budget(nodes="0", reasons=[REASON_DRIFTED]),
        ])
        pod = mk_pod(cpu=1.0, memory=2 * GIB)
        env.provision(pod)
        claim = env.kube.node_claims()[0]
        claim.status_conditions.set_true(COND_DRIFTED, now=time.time())
        command = env.disruption.reconcile(now=time.time() + 60)
        # drift blocked by its zero budget; nothing else eligible
        assert command is None or command.reason != REASON_DRIFTED
        assert env.kube.get_node_claim(claim.metadata.name) is not None


class TestMethodOrdering:
    def test_emptiness_wins_over_consolidation(self):
        """controller.go:98-112: the first successful Method ends the
        round — empty nodes go via Emptiness even when consolidation
        could also act."""
        env = make_env()
        pods = [mk_pod(cpu=1.0, memory=2 * GIB) for _ in range(2)]
        for pod in pods:
            env.provision(pod)
        env.kube.delete(env.kube.get_pod("default", pods[0].metadata.name))
        now = time.time() + 60
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        command = env.disruption.reconcile(now=now)
        assert command is not None and command.reason == REASON_EMPTY
        assert len(command.candidates) == 1

    def test_one_command_per_round(self):
        env = make_env()
        empty_nodes(env, 3)
        now = time.time() + 60
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        first = env.disruption.reconcile(now=now)
        assert first is not None
        # the same reconcile call never starts a second command; the
        # queue holds exactly one active command
        assert len(env.disruption.queue.active) <= 1


class TestReplacementFailureRollback:
    def test_replacement_launch_failure_rolls_back(self):
        """queue.go:137-246: replacements that die (ICE -> lifecycle
        deletes the claim) roll the command back — candidates untainted
        and still alive."""
        env = make_env()
        pods = []
        for _ in range(3):
            pod = mk_pod(cpu=1.0, memory=2 * GIB)
            env.provision(pod)
            pods.append(pod)
        now = time.time() + 120
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        # every future create fails with ICE
        from karpenter_tpu.cloudprovider.types import InsufficientCapacityError

        env.cloud.next_create_error = InsufficientCapacityError("ICE")
        command = env.disruption.reconcile(now=now)
        assert command is not None and command.replacement_count >= 1
        # lifecycle processes the replacement claim: launch fails, the
        # claim dies; the queue sees 'failed' and rolls back
        env.lifecycle.reconcile_all(now=now)
        env.disruption.queue.reconcile(now=now)
        assert command not in env.disruption.queue.active
        for candidate in command.candidates:
            claim = env.kube.get_node_claim(
                candidate.state_node.node_claim.metadata.name
            )
            assert claim is not None
            assert claim.metadata.deletion_timestamp is None
            node = candidate.state_node.node
            assert not any(
                t.key == DISRUPTED_NO_SCHEDULE_TAINT.key
                for t in node.spec.taints
            )

    def test_command_timeout_rolls_back(self):
        """A command whose replacements never initialize rolls back at
        the retry deadline (queue.go:86)."""
        env = make_env()
        pods = []
        now = time.time()
        for _ in range(3):
            pod = mk_pod(cpu=1.0, memory=2 * GIB)
            env.provision(pod, now=now)
            pods.append(pod)
        # replacements launched from here on never register
        env.cloud.registration_delay = 10_000.0
        now += 120
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        command = env.disruption.reconcile(now=now)
        assert command is not None
        # replacements stay unregistered (huge registration delay);
        # past the deadline the queue gives up
        late = now + COMMAND_TIMEOUT_SECONDS + 1
        env.disruption.queue.reconcile(now=late)
        assert command not in env.disruption.queue.active
        for candidate in command.candidates:
            node = candidate.state_node.node
            assert not any(
                t.key == DISRUPTED_NO_SCHEDULE_TAINT.key
                for t in node.spec.taints
            )

    def test_rollback_then_retry_succeeds(self):
        """After a rollback, a later round recomputes and executes."""
        env = make_env()
        pods = []
        for _ in range(3):
            pod = mk_pod(cpu=1.0, memory=2 * GIB)
            env.provision(pod)
            pods.append(pod)
        now = time.time() + 120
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        from karpenter_tpu.cloudprovider.types import InsufficientCapacityError

        env.cloud.next_create_error = InsufficientCapacityError("ICE")
        first = env.disruption.reconcile(now=now)
        assert first is not None
        env.lifecycle.reconcile_all(now=now)
        env.disruption.queue.reconcile(now=now)
        assert first not in env.disruption.queue.active
        # provider recovers; next rounds consolidate successfully
        later = now + 30
        for _ in range(4):
            env.reconcile_disruption(now=later)
            later += 5
        assert len(env.kube.nodes()) < 3
        assert env.all_pods_bound()


class TestCandidateProtection:
    def test_in_flight_candidates_not_recandidated(self):
        """Nodes already marked by an active command are not offered to
        the next round's methods (helpers.go deleting exclusion)."""
        env = make_env()
        now = time.time()
        for _ in range(3):
            env.provision(mk_pod(cpu=1.0, memory=2 * GIB), now=now)
        env.cloud.registration_delay = 10_000.0
        now += 120
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        first = env.disruption.reconcile(now=now)
        assert first is not None
        # replacements can't initialize (registration delay), command
        # stays active; a second reconcile must not build a command
        # from the same marked candidates
        second = env.disruption.reconcile(now=now + 11)
        if second is not None:
            first_names = {c.state_node.name for c in first.candidates}
            second_names = {c.state_node.name for c in second.candidates}
            assert not (first_names & second_names)

    def test_nominated_node_not_a_candidate(self):
        """A node holding a nomination window is not disruptable
        (statenode.go Nominate)."""
        env = make_env(consolidate_after="0s")
        pod = mk_pod(cpu=1.0, memory=2 * GIB)
        env.provision(pod)
        env.kube.delete(env.kube.get_pod("default", pod.metadata.name))
        now = time.time() + 60
        state = env.cluster.node_for_name(env.kube.nodes()[0].metadata.name)
        state.nominate(now=now)
        env.pod_events.reconcile_all(now=now)
        env.conditions.reconcile_all(now=now)
        command = env.disruption.reconcile(now=now)
        assert command is None
        assert len(env.kube.nodes()) == 1
