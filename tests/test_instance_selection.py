"""Instance-selection suite: price ordering, requirement filtering,
minValues flexibility floors, truncation, extended resources.

Models provisioning/scheduling/instance_selection_test.go and
cloudprovider/types.go:221-334 (OrderByPrice / SatisfiesMinValues /
Truncate)."""

import pytest

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    CAPACITY_TYPE_ON_DEMAND,
    INSTANCE_TYPE_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.cloudprovider.types import (
    order_by_price,
    satisfies_min_values,
    truncate,
)
from karpenter_tpu.apis.v1.nodeclaim import RequirementSpec
from karpenter_tpu.kube.objects import ObjectMeta
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.solver.solver import solve
from karpenter_tpu.testing import Environment, mk_nodepool, mk_pod


def catalog():
    return [
        make_instance_type("tiny", cpu=2, memory=4 * GIB, price=0.5),
        make_instance_type("mid", cpu=8, memory=32 * GIB, price=2.0),
        make_instance_type("big", cpu=32, memory=128 * GIB, price=8.0),
        make_instance_type("gpu", cpu=8, memory=32 * GIB, price=10.0,
                           extra_resources={"example.com/gpu": 4.0}),
        make_instance_type("arm", cpu=8, memory=32 * GIB, price=1.5,
                           arch="arm64"),
    ]


class TestSelection:
    def test_cheapest_fitting_type_launches(self):
        env = Environment(types=catalog())
        env.kube.create(mk_nodepool("p"))
        env.provision(mk_pod(cpu=1.0))
        node = env.kube.nodes()[0]
        assert node.metadata.labels[INSTANCE_TYPE_LABEL] == "tiny"

    def test_arch_requirement_filters(self):
        env = Environment(types=catalog())
        env.kube.create(mk_nodepool("p"))
        pod = mk_pod(cpu=1.0)
        pod.spec.node_selector = {"kubernetes.io/arch": "arm64"}
        env.provision(pod)
        assert env.kube.nodes()[0].metadata.labels[INSTANCE_TYPE_LABEL] == "arm"

    def test_instance_type_selector(self):
        env = Environment(types=catalog())
        env.kube.create(mk_nodepool("p"))
        pod = mk_pod(cpu=1.0)
        pod.spec.node_selector = {INSTANCE_TYPE_LABEL: "mid"}
        env.provision(pod)
        assert env.kube.nodes()[0].metadata.labels[INSTANCE_TYPE_LABEL] == "mid"

    def test_extended_resource_routes_to_gpu_type(self):
        env = Environment(types=catalog())
        env.kube.create(mk_nodepool("p"))
        pod = mk_pod(cpu=1.0)
        pod.spec.containers[0].requests["example.com/gpu"] = 2.0
        env.provision(pod)
        assert env.kube.nodes()[0].metadata.labels[INSTANCE_TYPE_LABEL] == "gpu"

    def test_pods_capacity_forces_extra_nodes(self):
        # the 'pods' resource caps how many pods fit regardless of cpu
        types = [make_instance_type("p4", cpu=32, memory=64 * GIB, pods=4,
                                    price=1.0)]
        pool = mk_nodepool("p")
        pods = [mk_pod(name=f"tiny-{i}", cpu=0.05) for i in range(9)]
        sol = solve(pods, [(pool, types)])
        assert not sol.unschedulable
        assert len(sol.new_nodes) == 3

    def test_on_demand_requirement_skips_spot(self):
        env = Environment(types=catalog())
        env.kube.create(mk_nodepool("p"))
        pod = mk_pod(cpu=1.0)
        pod.spec.node_selector = {CAPACITY_TYPE_LABEL: CAPACITY_TYPE_ON_DEMAND}
        env.provision(pod)
        node = env.kube.nodes()[0]
        assert node.metadata.labels[CAPACITY_TYPE_LABEL] == "on-demand"

    def test_order_by_price_respects_requirements(self):
        types = catalog()
        reqs = Requirements([
            Requirement(CAPACITY_TYPE_LABEL, IN, [CAPACITY_TYPE_ON_DEMAND])
        ])
        ordered = order_by_price(types, reqs)
        prices = [
            min(o.price for o in it.offerings
                if o.capacity_type == "on-demand")
            for it in ordered
        ]
        assert prices == sorted(prices)


def _pool_with_min_values(n):
    pool = mk_nodepool("p")
    pool.spec.template.spec.requirements = [
        RequirementSpec(
            key=INSTANCE_TYPE_LABEL,
            operator="Exists",
            min_values=n,
        )
    ]
    return pool


class TestMinValues:

    def test_satisfies_min_values(self):
        types = catalog()
        reqs = Requirements([
            Requirement(INSTANCE_TYPE_LABEL, "Exists", [], min_values=3)
        ])
        count, err = satisfies_min_values(types, reqs)
        assert err is None and count >= 3
        reqs6 = Requirements([
            Requirement(INSTANCE_TYPE_LABEL, "Exists", [], min_values=6)
        ])
        _, err = satisfies_min_values(types, reqs6)
        assert err is not None

    def test_truncate_honors_min_values(self):
        types = catalog()
        reqs = Requirements([
            Requirement(INSTANCE_TYPE_LABEL, "Exists", [], min_values=2)
        ])
        out = truncate(types, reqs, max_items=2)
        assert len(out) == 2
        with pytest.raises(Exception):
            truncate(types, reqs, max_items=1)

    def test_claim_keeps_min_values_flexibility(self):
        env = Environment(types=catalog())
        env.kube.create(_pool_with_min_values(2))
        env.provision(mk_pod(cpu=1.0))
        claim = env.kube.node_claims()[0]
        type_req = next(
            r for r in claim.spec.requirements if r.key == INSTANCE_TYPE_LABEL
            and r.operator == IN
        )
        assert len(type_req.values) >= 2

    def test_unsatisfiable_min_values_blocks(self):
        env = Environment(types=catalog())
        env.kube.create(_pool_with_min_values(10))
        env.provision(mk_pod(cpu=1.0))
        assert not env.kube.node_claims()


class TestMinValuesTightening:
    """A pod selector can shrink a pool's In set below its minValues
    floor even when the raw pool requirements stay satisfiable — the
    floors must be checked against the TIGHTENED requirement set
    (nodeclaim.go:146,425-433), and a BestEffort relaxation lowers the
    floor to the satisfiable count (nodeclaim.go:147-150)."""

    TIER = "example.com/tier"

    def _env(self, policy):
        from karpenter_tpu.operator.options import Options

        types = []
        for i in range(3):
            it = make_instance_type(f"mv-{i}", cpu=4, memory=8 * GIB,
                                    price=1.0 + i * 0.1)
            # every type covers BOTH tier values, so the raw pool
            # floor is satisfiable on any compatible subset
            it.requirements.add(Requirement(self.TIER, IN, ["a", "b"]))
            types.append(it)
        env = Environment(types=types)
        env.provisioner.options = Options(min_values_policy=policy)
        pool = mk_nodepool("p")
        pool.spec.template.spec.requirements = [
            RequirementSpec(key=self.TIER, operator=IN, values=("a", "b"),
                            min_values=2)
        ]
        env.kube.create(pool)
        return env

    def test_strict_rejects_pod_tightened_floor(self):
        env = self._env("Strict")
        env.provision(mk_pod(cpu=1.0, node_selector={self.TIER: "a"}))
        # the claim would serialize tier In [a] with minValues 2 —
        # admission-invalid; Strict must reject the plan instead
        assert not env.kube.node_claims()

    def test_strict_allows_unconstrained_pod(self):
        env = self._env("Strict")
        env.provision(mk_pod(cpu=1.0))
        assert env.kube.node_claims()

    def test_best_effort_lowers_floor_and_annotates(self):
        from karpenter_tpu.apis.v1.labels import (
            NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION,
        )

        env = self._env("BestEffort")
        env.provision(mk_pod(cpu=1.0, node_selector={self.TIER: "a"}))
        claims = env.kube.node_claims()
        assert claims, "BestEffort must still launch"
        claim = claims[0]
        tier_req = next(
            r for r in claim.spec.requirements
            if r.key == self.TIER and r.operator == IN
        )
        # floor lowered to exactly the satisfiable count (one tier
        # value survives the pod selector), not dropped outright
        assert tier_req.min_values == 1
        assert (
            claim.metadata.annotations.get(
                NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION
            )
            == "true"
        )
        pod = env.kube.pods()[0]
        assert pod.spec.node_name, "pod must bind to the relaxed node"


class TestTruncation:
    def test_max_instance_types_truncation(self):
        from karpenter_tpu.provisioning.scheduler import MAX_INSTANCE_TYPES

        many = [
            make_instance_type(f"t-{i}", cpu=4, memory=8 * GIB,
                               price=1.0 + i * 0.001)
            for i in range(MAX_INSTANCE_TYPES + 50)
        ]
        env = Environment(types=many)
        env.kube.create(mk_nodepool("p"))
        env.provision(mk_pod(cpu=1.0))
        claim = env.kube.node_claims()[0]
        type_req = next(
            r for r in claim.spec.requirements
            if r.key == INSTANCE_TYPE_LABEL and r.operator == IN
        )
        assert len(type_req.values) <= MAX_INSTANCE_TYPES
        # cheapest survives truncation (truncate is price-ordered)
        assert "t-0" in type_req.values

    def test_best_effort_min_values_relaxes_with_annotation(self):
        from karpenter_tpu.apis.v1.labels import (
            NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION,
        )
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.provisioning.provisioner import Provisioner

        env = Environment(types=catalog())
        env.kube.create(_pool_with_min_values(10))
        prov = Provisioner(
            env.kube, env.cluster, env.cloud,
            options=Options(min_values_policy="BestEffort"),
        )
        env.kube.create(mk_pod(cpu=1.0))
        prov.create_node_claims(prov.schedule())
        claims = env.kube.node_claims()
        assert len(claims) == 1
        assert claims[0].metadata.annotations.get(
            NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION
        ) == "true"


class TestMinValuesOperatorMatrix:
    """instance_selection_test.go minValues × operator family: Gt/Lt
    carry minValues floors too, and multiple operators on one key take
    the MAX of their floors."""

    def _solve_with_requirements(self, requirement_specs, n_types=10):
        types = [
            make_instance_type(f"m{i}", cpu=2 * (i + 1),
                               memory=(8 + 4 * i) * GIB,
                               price=1.0 + 0.5 * i,
                               extra_labels={"tier": str(i)})
            for i in range(n_types)
        ]
        env = Environment(types=types)
        pool = mk_nodepool("default")
        pool.spec.template.spec.requirements = [
            RequirementSpec(key=key, operator=op, values=tuple(values),
                            min_values=mv)
            for key, op, values, mv in requirement_specs
        ]
        env.kube.create(pool)
        env.provision(mk_pod(cpu=0.5))
        return env

    def test_min_values_with_gt_satisfied(self):
        # "should schedule respecting the minValues in Gt operator":
        # tier > 2 leaves 7 types; floor of 3 is satisfiable
        env = self._solve_with_requirements([
            ("tier", "Gt", ("2",), 3),
        ])
        claims = env.kube.node_claims()
        assert len(claims) == 1
        node = env.kube.nodes()[0]
        assert int(node.metadata.labels["tier"]) > 2

    def test_min_values_with_gt_unsatisfiable_fails(self):
        # "scheduler should fail if the minValues in Gt operator is
        # not satisfied": tier > 8 leaves 1 type < floor of 3
        env = self._solve_with_requirements([
            ("tier", "Gt", ("8",), 3),
        ])
        assert env.kube.node_claims() == []

    def test_min_values_with_lt_satisfied(self):
        env = self._solve_with_requirements([
            ("tier", "Lt", ("5",), 3),
        ])
        claims = env.kube.node_claims()
        assert len(claims) == 1
        node = env.kube.nodes()[0]
        assert int(node.metadata.labels["tier"]) < 5

    def test_min_values_with_lt_unsatisfiable_fails(self):
        env = self._solve_with_requirements([
            ("tier", "Lt", ("2",), 5),
        ])
        assert env.kube.node_claims() == []

    def test_max_of_min_values_across_operators_same_key(self):
        # "max of the minValues of In and NotIn operators": In floor 2,
        # NotIn floor 4 -> effective floor 4; the value set (5 types
        # after NotIn) satisfies it
        env = self._solve_with_requirements([
            ("tier", "In", tuple(str(i) for i in range(6)), 2),
            ("tier", "NotIn", ("0",), 4),
        ])
        assert len(env.kube.node_claims()) == 1

    def test_max_of_min_values_unsatisfiable_fails(self):
        # In floor 2 ok, NotIn floor 5 but only 2 values survive
        env = self._solve_with_requirements([
            ("tier", "In", ("1", "2", "3"), 2),
            ("tier", "NotIn", ("1",), 5),
        ])
        assert env.kube.node_claims() == []

    def test_multiple_keys_with_min_values(self):
        # "should schedule and respect multiple requirement keys with
        # minValues"
        env = self._solve_with_requirements([
            ("tier", "In", tuple(str(i) for i in range(6)), 3),
            (INSTANCE_TYPE_LABEL, "Exists", (), 4),
        ])
        assert len(env.kube.node_claims()) == 1
