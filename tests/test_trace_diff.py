"""tools/trace_report.py --diff (ISSUE 13 satellite): per-span-name
count/p50/p99 delta between two /debug/traces payloads or bench
trace_summary blocks, with the --threshold exit-1 CI gate."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from trace_report import diff_report, main, stats_of  # noqa: E402

from karpenter_tpu import tracing  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_ring():
    tracing.clear()
    yield
    tracing.clear()


def _ring_payload(span_seconds: float) -> dict:
    clock = iter([0.0, 0.0, span_seconds, span_seconds + 0.1])
    with tracing.trace("tick", clock=lambda: next(clock)):
        with tracing.span("solve.compile"):
            pass
    payload = {"traces": tracing.traces()}
    tracing.clear()
    return payload


class TestStatsOf:
    def test_traces_payload(self):
        stats = stats_of(_ring_payload(0.5))
        assert stats["solve.compile"]["p50_s"] == 0.5

    def test_bare_list(self):
        stats = stats_of(_ring_payload(0.5)["traces"])
        assert "tick" in stats

    def test_bench_artifact_prefixes_arms(self):
        bench = {"detail": {
            "reserved_50k": {"trace_summary": {"spans": {
                "tick": {"count": 3, "p50_s": 0.1, "p99_s": 0.2},
            }, "traces_sampled": 3, "ring_capacity": 64}},
            "mixed_10k": {"wall_s": 1.0},   # no summary: skipped
        }}
        stats = stats_of(bench)
        assert set(stats) == {"reserved_50k/tick"}

    def test_bare_trace_summary_block(self):
        block = {"spans": {"tick": {"count": 1, "p50_s": 0.1,
                                    "p99_s": 0.1}},
                 "traces_sampled": 1, "ring_capacity": 64}
        assert set(stats_of(block)) == {"tick"}


class TestDiff:
    def test_delta_table_and_gate(self):
        base = {"solve.compile": {"count": 4, "p50_s": 0.100,
                                  "p99_s": 0.200}}
        cur = {"solve.compile": {"count": 4, "p50_s": 0.140,
                                 "p99_s": 0.210}}
        table, regressions = diff_report(base, cur, threshold=0.25)
        assert "solve.compile" in table and "+40.0%" in table
        assert len(regressions) == 1 and "p50_s" in regressions[0]
        # below threshold: report only
        _, regressions = diff_report(base, cur, threshold=0.5)
        assert not regressions
        # no threshold: never gates
        _, regressions = diff_report(base, cur, threshold=None)
        assert not regressions

    def test_one_sided_spans_reported_not_gated(self):
        base = {"gone": {"count": 1, "p50_s": 1.0, "p99_s": 1.0}}
        cur = {"new": {"count": 1, "p50_s": 1.0, "p99_s": 1.0}}
        table, regressions = diff_report(base, cur, threshold=0.01)
        assert "only in baseline" in table and "only in current" in table
        assert not regressions

    def test_main_exit_codes(self, tmp_path, capsys):
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(_ring_payload(0.1)))
        b.write_text(json.dumps(_ring_payload(0.5)))
        rc = main(["trace_report.py", "--diff", str(a), str(b),
                   "--threshold", "0.25"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "REGRESSIONS" in out and "solve.compile" in out
        # improvement direction passes
        assert main(["trace_report.py", "--diff", str(b), str(a),
                     "--threshold", "0.25"]) == 0
        # no threshold: report-only mode always exits 0
        assert main(["trace_report.py", "--diff", str(a), str(b)]) == 0
