#!/usr/bin/env python3
"""Render a saved flight-recorder trace ring as a per-span latency
table — or diff two of them.

Input: the JSON an operator serves at /debug/traces (`{"traces":
[...]}`), a bare list of trace dicts, or a bench JSON whose arms carry
`trace_summary` blocks — from a file argument or stdin. Output: one
aligned table per source — span name, count, total, p50, p99, max —
the same digest bench artifacts embed per arm (tracing.span_stats).

    curl -s localhost:8080/debug/traces | python tools/trace_report.py
    python tools/trace_report.py ring.json
    python tools/trace_report.py BENCH_r06.json   # per-arm summaries

`--diff A.json B.json` prints the per-span-name count/p50/p99 delta
table between two payloads (any accepted shape on either side; bench
artifacts contribute every arm's spans as `arm/span`). With
`--threshold 0.25` the tool exits 1 when any span's p50 or p99 grew
past the relative threshold — the CI gate:

    python tools/trace_report.py --diff r05_ring.json r06_ring.json \\
        --threshold 0.25
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from karpenter_tpu.tracing import span_stats  # noqa: E402


def _fmt_table(stats: dict[str, dict]) -> str:
    if not stats:
        return "(no spans)"
    headers = ("span", "count", "total_s", "p50_s", "p99_s", "max_s")
    rows = [
        (name, str(s["count"]), f"{s['total_s']:.6f}",
         f"{s['p50_s']:.6f}", f"{s['p99_s']:.6f}", f"{s['max_s']:.6f}")
        for name, s in stats.items()
    ]
    widths = [
        max(len(h), *(len(r[i]) for r in rows))
        for i, h in enumerate(headers)
    ]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "  ".join("-" * w for w in widths),
    ]
    for row in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)


def report(payload) -> str:
    """Dispatch on the payload shape (see module docstring)."""
    if isinstance(payload, list):
        return _fmt_table(span_stats(payload))
    if "traces" in payload:
        traces = payload["traces"]
        out = [_fmt_table(span_stats(traces))]
        ids = sorted({t["trace_id"] for t in traces})
        out.append(f"\n{len(traces)} trace(s), {len(ids)} id(s)")
        return "\n".join(out)
    # bench JSON: arms carrying trace_summary blocks
    detail = payload.get("detail", payload)
    sections = []
    for arm, body in detail.items():
        if isinstance(body, dict) and "trace_summary" in body:
            summary = body["trace_summary"]
            # wrapped shape {spans, traces_sampled, ring_capacity};
            # bare per-span dicts accepted for older artifacts
            stats = summary.get("spans", summary)
            header = f"== {arm} =="
            if "traces_sampled" in summary:
                header += (
                    f" ({summary['traces_sampled']} trace(s) sampled,"
                    f" ring capacity {summary['ring_capacity']})"
                )
            sections.append(f"{header}\n{_fmt_table(stats)}")
    if not sections:
        return "(no traces or trace_summary blocks found)"
    return "\n\n".join(sections)


def stats_of(payload) -> dict[str, dict]:
    """One flat {span_name: stats} mapping from any accepted payload
    shape — the diff's per-side input. Bench artifacts contribute
    every arm's summary spans as `arm/span` so two rounds diff arm by
    arm."""
    if isinstance(payload, list):
        return span_stats(payload)
    if "traces" in payload:
        return span_stats(payload["traces"])
    if "spans" in payload and not any(
        isinstance(v, dict) and "trace_summary" in v
        for v in payload.values() if isinstance(v, dict)
    ):
        # a bare trace_summary block ({spans, traces_sampled, ...})
        return dict(payload["spans"])
    detail = payload.get("detail", payload)
    out: dict[str, dict] = {}
    for arm, body in detail.items():
        if isinstance(body, dict) and "trace_summary" in body:
            summary = body["trace_summary"]
            for name, stats in summary.get("spans", summary).items():
                out[f"{arm}/{name}"] = stats
    return out


def diff_report(
    base: dict[str, dict], cur: dict[str, dict],
    threshold: float | None = None,
) -> tuple[str, list[str]]:
    """-> (rendered delta table, regression lines). A regression is a
    p50 or p99 relative increase past `threshold` on a span present
    in both payloads (None: report only, never gate)."""
    names = sorted(set(base) | set(cur))
    headers = ("span", "count", "p50_s", "p99_s")
    rows = []
    regressions: list[str] = []
    for name in names:
        b, c = base.get(name), cur.get(name)
        if b is None or c is None:
            side = "current" if b is None else "baseline"
            rows.append((name, f"only in {side}", "-", "-"))
            continue
        cells = [f"{b['count']} -> {c['count']}"]
        for key in ("p50_s", "p99_s"):
            bv, cv = b.get(key), c.get(key)
            if not isinstance(bv, (int, float)) or not isinstance(
                cv, (int, float)
            ):
                cells.append("-")
                continue
            if bv > 0:
                rel = cv / bv - 1.0
                cells.append(f"{bv:.6f} -> {cv:.6f} ({rel:+.1%})")
                if threshold is not None and rel > threshold:
                    regressions.append(
                        f"{name}.{key}: {bv:.6f}s -> {cv:.6f}s "
                        f"({rel:+.1%})"
                    )
            else:
                cells.append(f"{bv:.6f} -> {cv:.6f}")
        rows.append((name, *cells))
    if not rows:
        return "(no spans on either side)", regressions
    widths = [
        max(len(h), *(len(str(r[i])) for r in rows))
        for i, h in enumerate(headers)
    ]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "  ".join("-" * w for w in widths),
    ]
    for row in rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(row, widths)))
    return "\n".join(lines), regressions


def _load(path: str):
    with open(path) as fh:
        return json.load(fh)


def main(argv: list[str]) -> int:
    if "--diff" in argv:
        import argparse

        parser = argparse.ArgumentParser(
            description="diff two trace payloads per span name"
        )
        parser.add_argument("--diff", nargs=2, metavar=("BASE", "CURRENT"))
        parser.add_argument(
            "--threshold", type=float, default=None,
            help="relative p50/p99 increase that exits 1 (omit to "
            "report without gating)",
        )
        args = parser.parse_args(argv[1:])
        table, regressions = diff_report(
            stats_of(_load(args.diff[0])), stats_of(_load(args.diff[1])),
            threshold=args.threshold,
        )
        print(table)
        if regressions:
            print(
                f"\nREGRESSIONS past {args.threshold:.0%} "
                f"({args.diff[0]} -> {args.diff[1]}):"
            )
            for line in regressions:
                print("  " + line)
            return 1
        if args.threshold is not None:
            print(f"\nno span regressions past {args.threshold:.0%}")
        return 0
    if len(argv) > 1:
        payload = _load(argv[1])
    else:
        payload = json.load(sys.stdin)
    print(report(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
