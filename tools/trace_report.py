#!/usr/bin/env python3
"""Render a saved flight-recorder trace ring as a per-span latency
table.

Input: the JSON an operator serves at /debug/traces (`{"traces":
[...]}`), a bare list of trace dicts, or a bench JSON whose arms carry
`trace_summary` blocks — from a file argument or stdin. Output: one
aligned table per source — span name, count, total, p50, p99, max —
the same digest bench artifacts embed per arm (tracing.span_stats).

    curl -s localhost:8080/debug/traces | python tools/trace_report.py
    python tools/trace_report.py ring.json
    python tools/trace_report.py BENCH_r06.json   # per-arm summaries
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from karpenter_tpu.tracing import span_stats  # noqa: E402


def _fmt_table(stats: dict[str, dict]) -> str:
    if not stats:
        return "(no spans)"
    headers = ("span", "count", "total_s", "p50_s", "p99_s", "max_s")
    rows = [
        (name, str(s["count"]), f"{s['total_s']:.6f}",
         f"{s['p50_s']:.6f}", f"{s['p99_s']:.6f}", f"{s['max_s']:.6f}")
        for name, s in stats.items()
    ]
    widths = [
        max(len(h), *(len(r[i]) for r in rows))
        for i, h in enumerate(headers)
    ]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "  ".join("-" * w for w in widths),
    ]
    for row in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)


def report(payload) -> str:
    """Dispatch on the payload shape (see module docstring)."""
    if isinstance(payload, list):
        return _fmt_table(span_stats(payload))
    if "traces" in payload:
        traces = payload["traces"]
        out = [_fmt_table(span_stats(traces))]
        ids = sorted({t["trace_id"] for t in traces})
        out.append(f"\n{len(traces)} trace(s), {len(ids)} id(s)")
        return "\n".join(out)
    # bench JSON: arms carrying trace_summary blocks
    detail = payload.get("detail", payload)
    sections = []
    for arm, body in detail.items():
        if isinstance(body, dict) and "trace_summary" in body:
            summary = body["trace_summary"]
            # wrapped shape {spans, traces_sampled, ring_capacity};
            # bare per-span dicts accepted for older artifacts
            stats = summary.get("spans", summary)
            header = f"== {arm} =="
            if "traces_sampled" in summary:
                header += (
                    f" ({summary['traces_sampled']} trace(s) sampled,"
                    f" ring capacity {summary['ring_capacity']})"
                )
            sections.append(f"{header}\n{_fmt_table(stats)}")
    if not sections:
        return "(no traces or trace_summary blocks found)"
    return "\n\n".join(sections)


def main(argv: list[str]) -> int:
    if len(argv) > 1:
        with open(argv[1]) as fh:
            payload = json.load(fh)
    else:
        payload = json.load(sys.stdin)
    print(report(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
