"""Randomized convergence soak (not part of the CI suite).

Drives a full Operator through thousands of ticks of adversarial churn
(pod create/delete, PDB flap, pool-template drift, provider ICE
injection), then drains with no faults and requires TOTAL convergence:
zero unbound pods, zero deleting claims, zero stale disrupted taints,
an empty orchestration queue, and claims == provider instances.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/soak.py <seed> \
        <churn_wall_seconds> <drain_wall_seconds>

Round-5 findings fixed via this harness: the emptiness-eats-replacement
livelock, deleting-object requeue wedges, the pending-pod backstop, and
the planned-placement binding hold (plans must be HELD until the
drained pods actually come free — dropping them while pods were still
bound pre-eviction made every drain re-solve from scratch and
oscillate). Seeds 7/11/23/42 all drain to total convergence at full
scale.
"""

import random, sys, time
from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import InsufficientCapacityError
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.testing import mk_nodepool, mk_pod
from karpenter_tpu.kube.objects import (LabelSelector, ObjectMeta,
    PodDisruptionBudget, PodDisruptionBudgetSpec)

seed = int(sys.argv[1]); budget = float(sys.argv[2]); drain_budget = float(sys.argv[3])
rng = random.Random(seed)
kube = KubeClient()
types = [make_instance_type("c2", cpu=2, memory=8*GIB, price=2.0),
         make_instance_type("c4", cpu=4, memory=16*GIB, price=3.0),
         make_instance_type("c8", cpu=8, memory=32*GIB, price=5.0)]
cloud = KwokCloudProvider(kube, types=types)
op = Operator(kube, cloud)
pool = mk_nodepool("default")
pool.spec.disruption.consolidate_after = "30s"
kube.create(pool)
now = time.time(); pdb = None; created = 0; start = time.time()
for tick in range(6000):
    if time.time() - start > budget: break
    now += rng.choice([1.0, 2.0, 11.0])
    r = rng.random()
    if r < 0.30:
        created += 1
        kube.create(mk_pod(name=f"w-{created}", cpu=rng.choice([0.3,0.5,1.0,1.9,3.5]),
                           labels={"app": rng.choice(["a","b","c"])}))
    elif r < 0.50:
        live = [p for p in kube.pods() if not p.is_terminal() and p.metadata.deletion_timestamp is None]
        if live: kube.delete(rng.choice(live))
    elif r < 0.55:
        if pdb is None:
            pdb = PodDisruptionBudget(metadata=ObjectMeta(name="pdb"),
                spec=PodDisruptionBudgetSpec(selector=LabelSelector.of({"app": "a"}),
                                             max_unavailable=rng.choice([0,1])))
            kube.create(pdb)
        else:
            kube.delete(pdb); pdb = None
    elif r < 0.58:
        pool.spec.template.labels["rev"] = str(tick); kube.touch(pool)
    elif r < 0.62:
        cloud.next_create_error = InsufficientCapacityError("flaky zone")
    op.step(now=now)
if pdb is not None: kube.delete(pdb)
converged = None
drain_start = time.time()
i = -1
for i in range(3000):
    if time.time() - drain_start > drain_budget: break
    now += 11; op.step(now=now)
    live = [p for p in kube.pods() if not p.is_terminal() and p.metadata.deletion_timestamp is None]
    unbound = [p for p in live if not p.spec.node_name]
    deleting = [c for c in kube.node_claims() if c.metadata.deletion_timestamp is not None]
    tainted = [n for n in kube.nodes()
               if any(t.key == "karpenter.sh/disrupted" for t in n.spec.taints)
               and n.metadata.deletion_timestamp is None]
    if not unbound and not deleting and not tainted and not op.disruption.queue.active:
        converged = i; break
ok = converged is not None and len(kube.node_claims()) == len(cloud.list())
print(f"seed={seed} ticks={tick} drain_ticks={i} converged_at={converged} claims={len(kube.node_claims())} instances={len(cloud.list())} {'OK' if ok else 'FAIL'}")
if not ok:
    live = [p for p in kube.pods() if not p.is_terminal() and p.metadata.deletion_timestamp is None]
    print("unbound:", [p.metadata.name for p in live if not p.spec.node_name][:5])
    print("deleting:", [c.metadata.name for c in kube.node_claims() if c.metadata.deletion_timestamp is not None][:5])
    print("queue:", [(c.reason, round(now-c.started_at)) for c in op.disruption.queue.active])
sys.exit(0 if ok else 1)
