"""Soak CLIs: the deterministic scenario flywheel, plus the legacy
randomized convergence soak (neither is part of the tier-1 CI suite).

Flywheel mode (default) replays a composed scenario trace against the
full reactive Operator under accelerated injected time and exits with
the judge's verdict — byte-identical across runs of the same
spec + seed (karpenter_tpu/scenarios/):

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/soak.py \
        [--spec smoke|flywheel] [--seed N] [--duration SECONDS] \
        [--faults EXTRA_FAULT_ENTRIES] [--out report.json]

Exit code 0 when the judge passes, 1 when any observability plane
fails (the report names the failing planes), 2 on usage errors.
`--faults` appends raw KARPENTER_FAULTS entries to the composed spec —
the regression-injection knob (e.g. `exec_delay@crash_tick:*=2s#lag`
burns the tick-latency SLO and must flip the verdict to FAIL).

Legacy mode is the original randomized wall-clock churn soak (seeded
random pod churn, PDB flap, pool drift, ICE injection, then fault-free
drain to TOTAL convergence):

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/soak.py legacy \
        <seed> <churn_wall_seconds> <drain_wall_seconds>

Round-5 findings fixed via the legacy harness: the
emptiness-eats-replacement livelock, deleting-object requeue wedges,
the pending-pod backstop, and the planned-placement binding hold.
Seeds 7/11/23/42 all drain to total convergence at full scale.
"""

import argparse
import dataclasses
import json
import sys


def flywheel_main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="soak.py", description="deterministic scenario-flywheel soak"
    )
    parser.add_argument("--spec", choices=("smoke", "flywheel"),
                        default="flywheel",
                        help="scenario preset (default: flywheel)")
    parser.add_argument("--seed", type=int, default=None,
                        help="override the preset's seed")
    parser.add_argument("--duration", type=float, default=None,
                        help="override the trace horizon, virtual seconds")
    parser.add_argument("--faults", default=None,
                        help="extra KARPENTER_FAULTS entries appended to "
                             "the composed spec (comma-separated)")
    parser.add_argument("--out", default=None,
                        help="write the full verdict artifact here (JSON)")
    args = parser.parse_args(argv)

    from karpenter_tpu.scenarios import flywheel_spec, run_soak, smoke_spec

    preset = smoke_spec if args.spec == "smoke" else flywheel_spec
    kwargs = {}
    if args.seed is not None:
        kwargs["seed"] = args.seed
    if args.duration is not None:
        kwargs["duration_s"] = args.duration
    spec = preset(**kwargs)
    if args.faults:
        extra = tuple(e.strip() for e in args.faults.split(",") if e.strip())
        spec = dataclasses.replace(
            spec, name=spec.name + "_injected",
            faults=spec.faults + extra,
        )

    report = run_soak(spec)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
    planes = report["planes"]
    print(f"scenario={report['scenario']} seed={report['seed']} "
          f"digest={report['report_digest'][:16]} "
          f"{'PASS' if report['pass'] else 'FAIL'}")
    for name in sorted(planes):
        plane = planes[name]
        print(f"  {name}: {'pass' if plane['pass'] else 'FAIL'}")
    if not report["pass"]:
        print("failing planes:", ", ".join(report["failures"]))
        slo = planes["slo"]
        if slo["budget_exhausted"]:
            print("  slo budget exhausted:",
                  ", ".join(slo["budget_exhausted"]),
                  "burn:", slo["whole_run_burn"])
        if planes["leaks"]["leaks"]:
            print("  leaks:", "; ".join(planes["leaks"]["leaks"]))
    return 0 if report["pass"] else 1


def legacy_main(argv) -> int:
    import random
    import time

    from karpenter_tpu.cloudprovider.fake import GIB, make_instance_type
    from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
    from karpenter_tpu.cloudprovider.types import InsufficientCapacityError
    from karpenter_tpu.kube.client import KubeClient
    from karpenter_tpu.kube.objects import (
        LabelSelector,
        ObjectMeta,
        PodDisruptionBudget,
        PodDisruptionBudgetSpec,
    )
    from karpenter_tpu.operator.operator import Operator
    from karpenter_tpu.testing import mk_nodepool, mk_pod

    seed = int(argv[0])
    budget = float(argv[1])
    drain_budget = float(argv[2])
    rng = random.Random(seed)
    kube = KubeClient()
    types = [make_instance_type("c2", cpu=2, memory=8 * GIB, price=2.0),
             make_instance_type("c4", cpu=4, memory=16 * GIB, price=3.0),
             make_instance_type("c8", cpu=8, memory=32 * GIB, price=5.0)]
    cloud = KwokCloudProvider(kube, types=types)
    op = Operator(kube, cloud)
    pool = mk_nodepool("default")
    pool.spec.disruption.consolidate_after = "30s"
    kube.create(pool)
    now = time.time()
    pdb = None
    created = 0
    start = time.time()
    tick = 0
    for tick in range(6000):
        if time.time() - start > budget:
            break
        now += rng.choice([1.0, 2.0, 11.0])
        r = rng.random()
        if r < 0.30:
            created += 1
            kube.create(mk_pod(
                name=f"w-{created}",
                cpu=rng.choice([0.3, 0.5, 1.0, 1.9, 3.5]),
                labels={"app": rng.choice(["a", "b", "c"])},
            ))
        elif r < 0.50:
            live = [p for p in kube.pods() if not p.is_terminal()
                    and p.metadata.deletion_timestamp is None]
            if live:
                kube.delete(rng.choice(live))
        elif r < 0.55:
            if pdb is None:
                pdb = PodDisruptionBudget(
                    metadata=ObjectMeta(name="pdb"),
                    spec=PodDisruptionBudgetSpec(
                        selector=LabelSelector.of({"app": "a"}),
                        max_unavailable=rng.choice([0, 1]),
                    ),
                )
                kube.create(pdb)
            else:
                kube.delete(pdb)
                pdb = None
        elif r < 0.58:
            pool.spec.template.labels["rev"] = str(tick)
            kube.touch(pool)
        elif r < 0.62:
            cloud.next_create_error = InsufficientCapacityError("flaky zone")
        op.step(now=now)
    if pdb is not None:
        kube.delete(pdb)
    converged = None
    drain_start = time.time()
    i = -1
    for i in range(3000):
        if time.time() - drain_start > drain_budget:
            break
        now += 11
        op.step(now=now)
        live = [p for p in kube.pods() if not p.is_terminal()
                and p.metadata.deletion_timestamp is None]
        unbound = [p for p in live if not p.spec.node_name]
        deleting = [c for c in kube.node_claims()
                    if c.metadata.deletion_timestamp is not None]
        tainted = [n for n in kube.nodes()
                   if any(t.key == "karpenter.sh/disrupted"
                          for t in n.spec.taints)
                   and n.metadata.deletion_timestamp is None]
        if (not unbound and not deleting and not tainted
                and not op.disruption.queue.active):
            converged = i
            break
    ok = converged is not None and (
        len(kube.node_claims()) == len(cloud.list())
    )
    print(f"seed={seed} ticks={tick} drain_ticks={i} "
          f"converged_at={converged} claims={len(kube.node_claims())} "
          f"instances={len(cloud.list())} {'OK' if ok else 'FAIL'}")
    if not ok:
        live = [p for p in kube.pods() if not p.is_terminal()
                and p.metadata.deletion_timestamp is None]
        print("unbound:",
              [p.metadata.name for p in live if not p.spec.node_name][:5])
        print("deleting:",
              [c.metadata.name for c in kube.node_claims()
               if c.metadata.deletion_timestamp is not None][:5])
        print("queue:", [(c.reason, round(now - c.started_at))
                         for c in op.disruption.queue.active])
    return 0 if ok else 1


if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] == "legacy":
        if len(argv) != 4:
            print("usage: soak.py legacy <seed> <churn_wall_seconds> "
                  "<drain_wall_seconds>", file=sys.stderr)
            sys.exit(2)
        sys.exit(legacy_main(argv[1:]))
    sys.exit(flywheel_main(argv))
