#!/usr/bin/env python
"""Diff two bench artifacts and gate on regressions (ISSUE 11).

    python tools/bench_compare.py BASELINE.json CURRENT.json \
        [--threshold 0.25] [--scenarios reserved_50k,steady_state_churn] \
        [--gap-tolerance 0.01] [--mem-tolerance 512]

Compares, per scenario present in BOTH artifacts' detail:
- wall-clock keys (lower is better): wall_s, p50_s, p99_s, and every
  *_wall_s / *_p50_s variant a scenario reports;
- pods_per_sec (higher is better);
- gap_vs_lp by absolute delta (--gap-tolerance);
- peak_rss_mb and the per-arm device-telemetry peaks by absolute MB
  delta (--mem-tolerance, null-tolerant on either side);
- the live_operator block's tick and disruption-scan walls (ISSUE 15),
  relative like the wall keys but null-tolerant like the gap keys (a
  side without the live arm reports loudly, never gates);
- the soak_flywheel verdict block (ISSUE 18): a FAILING current
  verdict always gates (the soak is deterministic, so a FAIL is a
  real regression, not jitter), a pass->fail flip gates, per-SLI
  burn-minutes gate by absolute delta (--soak-burn-tolerance) and the
  verdict-histogram distance by absolute delta (--soak-dist-tolerance);
  a side missing the arm reports loudly, never gates.

Exit codes: 0 = no regression past the threshold, 1 = at least one
regression, 2 = an artifact could not be parsed. A regression is a
relative change past --threshold in the bad direction; improvements
are reported but never gate. Scenarios present in only one artifact
are listed and skipped (a new arm is not a regression; a VANISHED
scenario is reported loudly but doesn't gate — arms can be disabled
per round via BENCH_SCENARIOS).

Accepted artifact shapes:
- the bench's own JSON line ({"metric", "value", "detail": {...}});
- the driver wrapper ({"parsed": {...}} or a "tail" string whose last
  parsable JSON object line is the bench output).
"""

from __future__ import annotations

import argparse
import json
import sys

# lower-is-better wall keys compared when present in both runs
WALL_KEYS = (
    "wall_s", "p50_s", "p99_s",
    "incremental_p50_s", "full_resolve_p50_s",
    "batched_probe_wall_s", "reference_wall_s", "global_repack_wall_s",
    "provision_wall_s", "p50_tick_s", "p99_tick_s",
    "full_staging_wall_s", "unsharded_wall_s",
)
# higher-is-better throughput key
RATE_KEY = "pods_per_sec"
# lower-is-better optimality keys (ISSUE 12): compared as ABSOLUTE
# deltas (a gap is already a ratio; relative-change gating would make
# a 0.1% -> 0.3% move a "200% regression"), gated by --gap-tolerance
GAP_KEYS = ("gap_vs_lp",)
# lower-is-better memory keys (ISSUE 13): host peak RSS plus the
# device-telemetry roll-ups, gated by --mem-tolerance in the same
# absolute-delta style as the gap keys (MB — RSS jitters a few percent
# per run, and percent-of-gigabytes gating would page on noise).
# Null-tolerant: a side without the key (pre-ISSUE-13 artifact,
# CPU-only host with no device stats) is reported, never gated.
MEM_KEYS = ("peak_rss_mb",)
# lower-is-better wall keys nested under a scenario's live_operator
# block (ISSUE 15): gated RELATIVE like WALL_KEYS, but null-tolerant
# like the gap keys — a side whose live arm didn't run (BENCH_LIVE_PODS
# = 0, pre-ISSUE artifact) is reported loudly, never gated
LIVE_WALL_KEYS = (
    "incremental_tick_p50_s", "full_reconcile_p50_s",
    "disruption_scan_wall_s",
)
# the same keys nested one level down in the per-arm device_telemetry
# block (telemetry.snapshot() keeps scalar roll-ups at its top level
# exactly so this gate can read them without walking the detail),
# mapped to the scope field that must read "arm" on BOTH sides before
# the key gates — process-scoped peaks accumulate every earlier arm,
# so a delta would fire on arm ordering, not memory
# sharded-state-plane scale walls (ISSUE 16): top-level keys of the
# live_operator_100k scenario, gated RELATIVE like WALL_KEYS but
# null-tolerant and LOUD like LIVE_WALL_KEYS — a side that skipped the
# arm (BENCH_LIVE_PODS=0, pre-ISSUE artifact) is reported, never gated
SCALE_WALL_KEYS = (
    "tick_p50_s_100k", "tick_p99_s_100k", "tick_p50_s_10k",
)
# arrival->bind latency percentiles (ISSUE 17): the reactive
# placement headline SLI, reported by the sustained_arrival_stream
# arm both at a scenario's top level and nested under its per-arm
# blocks (LATENCY_ARMS). Gated RELATIVE like WALL_KEYS — a latency
# regression is a ratio problem, not an absolute one — but
# null-tolerant and LOUD like the scale walls: a side without the
# arm (BENCH_ARRIVAL_PODS=0, pre-ISSUE artifact) is reported, never
# gated
LATENCY_KEYS = ("pod_to_bind_p50_s", "pod_to_bind_p99_s")
LATENCY_ARMS = ("reactive", "periodic")
DEVICE_MEM_KEYS = {
    "compiled_peak_temp_mb": "compiled_scope",
    "device_peak_in_use_mb": "device_scope",
}
# the scenario-flywheel soak verdict block (ISSUE 18): nested under a
# scenario as `soak` (the soak_flywheel bench arm). Gated
# null-tolerant-but-LOUD like LATENCY_KEYS — a side without the arm is
# reported, never gated — but the verdict itself is binary: a current
# run whose judge FAILED gates even with no baseline at all, and a
# pass -> fail flip gates regardless of any tolerance. burn-minutes
# per SLI gate on absolute delta (--soak-burn-tolerance, minutes of
# error budget — the soak is deterministic, so the tolerance absorbs
# intended spec growth, not noise), the verdict-histogram distance on
# absolute delta (--soak-dist-tolerance)
SOAK_BLOCK = "soak"


def load_detail(path: str) -> dict:
    """Scenario detail dict from any accepted artifact shape, or a
    raised ValueError naming what was wrong."""
    with open(path) as fh:
        data = json.load(fh)
    if isinstance(data, dict) and isinstance(data.get("detail"), dict):
        return data["detail"]
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        parsed = data["parsed"]
        if isinstance(parsed.get("detail"), dict):
            return parsed["detail"]
    if isinstance(data, dict) and isinstance(data.get("tail"), str):
        tail = data["tail"]
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                candidate = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(candidate.get("detail"), dict):
                return candidate["detail"]
        # salvage mode: the driver truncates `tail` to its last N
        # chars, so the bench JSON line is often cut at the FRONT while
        # its later scenario objects are intact (every recorded round
        # since r03 looks like this). Extract each complete
        # `"name": {...}` object individually.
        salvaged = _salvage_scenarios(tail)
        if salvaged:
            return salvaged
        raise ValueError(
            f"{path}: driver wrapper carries no parsed bench JSON "
            "(tail truncated past salvage and 'parsed' missing)"
        )
    raise ValueError(f"{path}: no scenario detail found")


def _salvage_scenarios(tail: str) -> dict:
    """Complete `"name": {...}` objects recoverable from a truncated
    JSON fragment: balanced-brace extraction per candidate, keeping
    dicts that parse and carry at least one numeric field. Nested
    braces inside a scenario (device_steps, trace_summary) are handled
    by the depth walk; a scenario cut by the truncation simply fails
    json.loads and is skipped."""
    import re

    out: dict = {}
    for match in re.finditer(r'"([a-z][a-z0-9_]*)":\s*\{', tail):
        name = match.group(1)
        start = match.end() - 1
        depth = 0
        for i in range(start, len(tail)):
            if tail[i] == "{":
                depth += 1
            elif tail[i] == "}":
                depth -= 1
                if depth == 0:
                    try:
                        obj = json.loads(tail[start : i + 1])
                    except json.JSONDecodeError:
                        break
                    if isinstance(obj, dict) and any(
                        isinstance(v, (int, float)) for v in obj.values()
                    ):
                        out[name] = obj
                    break
        else:
            continue
    # wrapper noise that is not a scenario
    for key in ("backend_provenance", "detail", "parsed", "device_steps",
                "trace_summary", "fault_schedule", "resilience"):
        out.pop(key, None)
    return out


def _mem_value(arm: dict, key: str):
    """A memory key's numeric value from an arm, looking through the
    device_telemetry block for the device keys; None when absent or
    null (the null-tolerant contract)."""
    if key in MEM_KEYS:
        return arm.get(key) if isinstance(arm.get(key), (int, float)) else None
    dt = arm.get("device_telemetry")
    if isinstance(dt, dict) and isinstance(dt.get(key), (int, float)):
        return dt[key]
    return None


def _mem_scope(arm: dict, key: str) -> str:
    """The scope stamped next to a memory key: "arm" means the value
    covers only that arm's work and may gate; anything else (process
    watermark, pre-scope artifact) is report-only."""
    if key in MEM_KEYS:
        return arm.get("peak_rss_scope", "")
    dt = arm.get("device_telemetry")
    if isinstance(dt, dict):
        return str(dt.get(DEVICE_MEM_KEYS[key], ""))
    return ""


def _compare_mem(name: str, b: dict, c: dict, mem_tolerance: float,
                 lines: list[str], regressions: list[str]) -> None:
    for key in MEM_KEYS + tuple(DEVICE_MEM_KEYS):
        bv, cv = _mem_value(b, key), _mem_value(c, key)
        if bv is None:
            if cv is not None:
                # the first round after telemetry lands: no baseline
                # to gate against, but the new peak must be VISIBLE
                lines.append(
                    f"  {name}.{key}: null -> {cv:.1f}MB "
                    "(new key; not gated)"
                )
            continue
        if cv is None:
            lines.append(
                f"  {name}.{key}: {bv:.1f}MB -> null "
                "(telemetry unavailable; not gated)"
            )
            continue
        if _mem_scope(b, key) != "arm" or _mem_scope(c, key) != "arm":
            # a process-lifetime watermark accumulates every earlier
            # arm; gating it would fire on arm ordering, not memory
            lines.append(
                f"  {name}.{key}: {bv:.1f}MB -> {cv:.1f}MB "
                "(process-scoped peak; not gated)"
            )
            continue
        delta = cv - bv
        tag = f"{name}.{key}: {bv:.1f}MB -> {cv:.1f}MB ({delta:+.1f}MB)"
        if delta > mem_tolerance:
            regressions.append(tag)
        else:
            lines.append("  " + tag)


def _compare_soak(name: str, b: dict, c: dict, burn_tolerance: float,
                  dist_tolerance: float, lines: list[str],
                  regressions: list[str]) -> None:
    """Gate the soak_flywheel judge verdict (ISSUE 18). The soak is
    fully deterministic (trace + faults + injected clock all seeded),
    so unlike the wall gates there is no jitter to absorb: a FAILING
    current verdict gates unconditionally, a pass->fail flip gates,
    and the burn/distance tolerances exist only to let intentional
    spec growth through without a baseline refresh."""
    bs = b.get(SOAK_BLOCK) if isinstance(b.get(SOAK_BLOCK), dict) else None
    cs = c.get(SOAK_BLOCK) if isinstance(c.get(SOAK_BLOCK), dict) else None
    if bs is None and cs is None:
        return
    if cs is None:
        lines.append(
            f"  {name}.soak: verdict -> null "
            "(soak arm unavailable; not gated)"
        )
        return
    cur_pass = cs.get("pass")
    failures = ", ".join(cs.get("failures") or ()) or "unknown plane"
    if cur_pass is False:
        # the judge already named the failing plane; no baseline needed
        regressions.append(
            f"{name}.soak: judge verdict FAIL ({failures})"
        )
    if bs is None:
        lines.append(
            f"  {name}.soak: null -> "
            f"{'pass' if cur_pass else 'FAIL'} (new arm; verdict-only gate)"
        )
        return
    if bs.get("pass") is True and cur_pass is False:
        regressions.append(
            f"{name}.soak: verdict pass -> FAIL ({failures})"
        )
    elif bs.get("pass") != cur_pass:
        lines.append(
            f"  {name}.soak: verdict "
            f"{'pass' if bs.get('pass') else 'FAIL'} -> "
            f"{'pass' if cur_pass else 'FAIL'}"
        )
    bb = bs.get("burn_minutes") if isinstance(
        bs.get("burn_minutes"), dict) else {}
    cb = cs.get("burn_minutes") if isinstance(
        cs.get("burn_minutes"), dict) else {}
    for sli in sorted(set(bb) | set(cb)):
        bv, cv = bb.get(sli), cb.get(sli)
        if not isinstance(bv, (int, float)):
            if isinstance(cv, (int, float)) and cv > 0:
                lines.append(
                    f"  {name}.soak.burn_minutes.{sli}: null -> "
                    f"{cv:.2f}min (new SLI; not gated)"
                )
            continue
        if not isinstance(cv, (int, float)):
            lines.append(
                f"  {name}.soak.burn_minutes.{sli}: {bv:.2f}min -> null "
                "(SLI unavailable; not gated)"
            )
            continue
        delta = cv - bv
        tag = (
            f"{name}.soak.burn_minutes.{sli}: {bv:.2f}min -> "
            f"{cv:.2f}min ({delta:+.2f}min abs)"
        )
        if delta > burn_tolerance:
            regressions.append(tag)
        elif bv or cv:
            lines.append("  " + tag)
    bv = bs.get("verdict_histogram_distance")
    cv = cs.get("verdict_histogram_distance")
    if isinstance(bv, (int, float)) and not isinstance(cv, (int, float)):
        lines.append(
            f"  {name}.soak.verdict_histogram_distance: {bv:.4f} -> "
            "null (no expectation envelope; not gated)"
        )
    elif not isinstance(bv, (int, float)) and isinstance(cv, (int, float)):
        lines.append(
            f"  {name}.soak.verdict_histogram_distance: null -> "
            f"{cv:.4f} (new key; not gated)"
        )
    elif isinstance(bv, (int, float)) and isinstance(cv, (int, float)):
        delta = cv - bv
        tag = (
            f"{name}.soak.verdict_histogram_distance: {bv:.4f} -> "
            f"{cv:.4f} ({delta:+.4f} abs)"
        )
        if delta > dist_tolerance:
            regressions.append(tag)
        else:
            lines.append("  " + tag)


def compare(
    base: dict, cur: dict, threshold: float, scenarios=None,
    gap_tolerance: float = 0.01, mem_tolerance: float = 512.0,
    soak_burn_tolerance: float = 1.0, soak_dist_tolerance: float = 0.1,
) -> tuple[list[str], list[str]]:
    """-> (report lines, regression lines). A regression is a wall
    increase or pods/sec decrease past `threshold` relative change, a
    gap_vs_lp increase past `gap_tolerance` absolute, or a memory-peak
    increase past `mem_tolerance` MB absolute. A gap/memory key
    present in the baseline but null in the current run (bound or
    telemetry machinery went missing) is reported loudly but does not
    gate — the wall/rate keys still cover the scenario."""
    lines: list[str] = []
    regressions: list[str] = []
    meta = {"backend", "backend_provenance"}
    base = {k: v for k, v in base.items() if k not in meta}
    cur = {k: v for k, v in cur.items() if k not in meta}
    names = sorted(set(base) & set(cur))
    if scenarios:
        names = [n for n in names if n in scenarios]
        missing = [n for n in scenarios if n not in names]
        for name in missing:
            lines.append(f"  {name}: requested but absent from one side")
    for name in sorted(set(base) ^ set(cur)):
        side = "baseline" if name in base else "current"
        lines.append(f"  {name}: only in {side} (skipped)")
    for name in names:
        b, c = base[name], cur[name]
        if not isinstance(b, dict) or not isinstance(c, dict):
            continue
        if "error" in b or "error" in c:
            lines.append(f"  {name}: errored arm (skipped)")
            continue
        for key in WALL_KEYS:
            bv, cv = b.get(key), c.get(key)
            if not isinstance(bv, (int, float)) or not isinstance(
                cv, (int, float)
            ) or bv <= 0:
                continue
            rel = cv / bv - 1.0
            tag = f"{name}.{key}: {bv:.3f}s -> {cv:.3f}s ({rel:+.1%})"
            if rel > threshold:
                regressions.append(tag)
            else:
                lines.append("  " + tag)
        bv, cv = b.get(RATE_KEY), c.get(RATE_KEY)
        if isinstance(bv, (int, float)) and isinstance(
            cv, (int, float)
        ) and bv > 0:
            rel = cv / bv - 1.0
            tag = (
                f"{name}.{RATE_KEY}: {bv:,.0f} -> {cv:,.0f} ({rel:+.1%})"
            )
            if rel < -threshold:
                regressions.append(tag)
            else:
                lines.append("  " + tag)
        blo, clo = b.get("live_operator"), c.get("live_operator")
        if isinstance(blo, dict) or isinstance(clo, dict):
            for key in LIVE_WALL_KEYS:
                bv = blo.get(key) if isinstance(blo, dict) else None
                cv = clo.get(key) if isinstance(clo, dict) else None
                if not isinstance(bv, (int, float)) or bv <= 0:
                    if isinstance(cv, (int, float)):
                        lines.append(
                            f"  {name}.live_operator.{key}: null -> "
                            f"{cv:.3f}s (new key; not gated)"
                        )
                    continue
                if not isinstance(cv, (int, float)):
                    lines.append(
                        f"  {name}.live_operator.{key}: {bv:.3f}s -> "
                        "null (live arm unavailable; not gated)"
                    )
                    continue
                rel = cv / bv - 1.0
                tag = (
                    f"{name}.live_operator.{key}: {bv:.3f}s -> "
                    f"{cv:.3f}s ({rel:+.1%})"
                )
                if rel > threshold:
                    regressions.append(tag)
                else:
                    lines.append("  " + tag)
        for key in SCALE_WALL_KEYS:
            bv, cv = b.get(key), c.get(key)
            if key not in b and key not in c:
                continue
            if not isinstance(bv, (int, float)) or bv <= 0:
                if isinstance(cv, (int, float)):
                    lines.append(
                        f"  {name}.{key}: null -> {cv:.3f}s "
                        "(new key; not gated)"
                    )
                continue
            if not isinstance(cv, (int, float)):
                lines.append(
                    f"  {name}.{key}: {bv:.3f}s -> null "
                    "(scale arm unavailable; not gated)"
                )
                continue
            rel = cv / bv - 1.0
            tag = f"{name}.{key}: {bv:.3f}s -> {cv:.3f}s ({rel:+.1%})"
            if rel > threshold:
                regressions.append(tag)
            else:
                lines.append("  " + tag)
        for arm in (None,) + LATENCY_ARMS:
            ba = b if arm is None else b.get(arm)
            ca = c if arm is None else c.get(arm)
            if not isinstance(ba, dict) and not isinstance(ca, dict):
                continue
            for key in LATENCY_KEYS:
                bv = ba.get(key) if isinstance(ba, dict) else None
                cv = ca.get(key) if isinstance(ca, dict) else None
                if bv is None and cv is None:
                    continue
                label = f"{name}.{key}" if arm is None else (
                    f"{name}.{arm}.{key}"
                )
                if not isinstance(bv, (int, float)) or bv <= 0:
                    if isinstance(cv, (int, float)):
                        lines.append(
                            f"  {label}: null -> {cv:.3f}s "
                            "(new key; not gated)"
                        )
                    continue
                if not isinstance(cv, (int, float)):
                    lines.append(
                        f"  {label}: {bv:.3f}s -> null "
                        "(arrival arm unavailable; not gated)"
                    )
                    continue
                rel = cv / bv - 1.0
                tag = f"{label}: {bv:.3f}s -> {cv:.3f}s ({rel:+.1%})"
                if rel > threshold:
                    regressions.append(tag)
                else:
                    lines.append("  " + tag)
        for gkey in GAP_KEYS:
            bv, cv = b.get(gkey), c.get(gkey)
            if not isinstance(bv, (int, float)):
                continue
            if not isinstance(cv, (int, float)):
                lines.append(
                    f"  {name}.{gkey}: {bv:.4f} -> null "
                    "(bound unavailable; not gated)"
                )
                continue
            delta = cv - bv
            tag = f"{name}.{gkey}: {bv:.4f} -> {cv:.4f} ({delta:+.4f} abs)"
            if delta > gap_tolerance:
                regressions.append(tag)
            else:
                lines.append("  " + tag)
        _compare_mem(name, b, c, mem_tolerance, lines, regressions)
        _compare_soak(name, b, c, soak_burn_tolerance,
                      soak_dist_tolerance, lines, regressions)
    # a current-only scenario is normally skipped (a new arm is not a
    # regression), but a soak verdict is a pass/fail judgement, not a
    # comparison — a FAILING judge gates even without any baseline
    for name in sorted(set(cur) - set(base)):
        c = cur[name]
        if not isinstance(c, dict) or "error" in c:
            continue
        if scenarios and name not in scenarios:
            continue
        _compare_soak(name, {}, c, soak_burn_tolerance,
                      soak_dist_tolerance, lines, regressions)
    return lines, regressions


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="gate bench results against a baseline artifact"
    )
    parser.add_argument("baseline")
    parser.add_argument("current")
    parser.add_argument(
        "--threshold", type=float, default=0.25,
        help="relative regression gate (default 0.25 — CPU bench "
        "walls jitter; tighten on dedicated hardware)",
    )
    parser.add_argument(
        "--scenarios", default="",
        help="comma list restricting the gate (default: every "
        "scenario present in both artifacts)",
    )
    parser.add_argument(
        "--gap-tolerance", type=float, default=0.01,
        help="absolute gap_vs_lp increase allowed before gating "
        "(default 0.01 = one point of optimality gap; the gap is "
        "solver-deterministic, so the knob absorbs master-LP stall "
        "jitter, not machine load)",
    )
    parser.add_argument(
        "--mem-tolerance", type=float, default=512.0,
        help="absolute peak-memory increase in MB allowed before "
        "gating (default 512 — covers peak_rss_mb and the per-arm "
        "device-telemetry peaks; same absolute-delta style as "
        "--gap-tolerance, null-tolerant on either side)",
    )
    parser.add_argument(
        "--soak-burn-tolerance", type=float, default=1.0,
        help="absolute per-SLI error-budget burn increase in minutes "
        "allowed before the soak gate fires (default 1.0; the soak is "
        "deterministic, so the knob absorbs intended scenario growth, "
        "not noise)",
    )
    parser.add_argument(
        "--soak-dist-tolerance", type=float, default=0.1,
        help="absolute verdict-histogram distance increase allowed "
        "before the soak gate fires (default 0.1 of total-variation "
        "distance against the spec's expectation envelope)",
    )
    parser.add_argument(
        "--quiet", action="store_true",
        help="print regressions only",
    )
    args = parser.parse_args(argv)
    try:
        base = load_detail(args.baseline)
        cur = load_detail(args.current)
    except (OSError, ValueError, json.JSONDecodeError) as err:
        print(f"bench_compare: {err}", file=sys.stderr)
        return 2
    wanted = (
        {s.strip() for s in args.scenarios.split(",") if s.strip()}
        or None
    )
    lines, regressions = compare(
        base, cur, args.threshold, wanted,
        gap_tolerance=args.gap_tolerance,
        mem_tolerance=args.mem_tolerance,
        soak_burn_tolerance=args.soak_burn_tolerance,
        soak_dist_tolerance=args.soak_dist_tolerance,
    )
    if not args.quiet and lines:
        print("compared (within threshold):")
        for line in lines:
            print(line)
    if regressions:
        print(
            f"REGRESSIONS past {args.threshold:.0%} "
            f"({args.baseline} -> {args.current}):"
        )
        for line in regressions:
            print("  " + line)
        return 1
    print(f"no regressions past {args.threshold:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
