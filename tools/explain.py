#!/usr/bin/env python3
"""Render a /debug/explain payload — or a bench artifact's
explain_summary blocks — as human-readable text.

Input (file argument or stdin):

- a pod explanation (`/debug/explain?pod=<key>`): the elimination
  funnel as an arrow chain, the relaxation steps burned, the error;
- a node verdict (`/debug/explain?node=<name>`): the kept/consolidated
  verdict with its evidence (LP certificate numbers, prices, vetoes);
- a whole tick record (`/debug/explain?tick=<trace_id>`): every pod
  and node verdict of that tick;
- the bare /debug/explain digest;
- a bench JSON whose arms carry `explain_summary` blocks: one verdict
  histogram table per arm.

    curl -s 'localhost:8080/debug/explain?pod=default/web-0' \\
        | python tools/explain.py
    python tools/explain.py BENCH_r06.json
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from karpenter_tpu.explain import funnel as funnel_mod  # noqa: E402


def _fmt_counts(counts: dict[str, int]) -> str:
    if not counts:
        return "  (none)"
    width = max(len(k) for k in counts)
    return "\n".join(
        f"  {k.ljust(width)}  {v}" for k, v in sorted(counts.items())
    )


def _render_pod(payload: dict) -> str:
    head = f"pod {payload.get('pod', '?')}"
    if payload.get("trace_id"):
        head += f"  (tick {payload['trace_id']})"
    verdict = payload.get("verdict")
    if verdict:
        head += f"  verdict={verdict}"
    return head + "\n" + funnel_mod.render(payload)


def _render_node(payload: dict) -> str:
    lines = [f"node {payload.get('node', '?')}"
             f"  (tick {payload.get('trace_id', '?')})"]
    lines.append(f"verdict: {payload.get('verdict', '?')}")
    for key in sorted(payload):
        if key in ("node", "trace_id", "verdict"):
            continue
        lines.append(f"  {key}: {payload[key]}")
    return "\n".join(lines)


def _render_record(payload: dict) -> str:
    lines = [f"tick {payload.get('trace_id', '?')}: "
             f"{len(payload.get('pods', {}))} pod verdict(s), "
             f"{len(payload.get('nodes', {}))} node verdict(s), "
             f"{len(payload.get('lp', []))} LP summar(ies)"]
    for key, rec in sorted(payload.get("pods", {}).items()):
        lines.append(f"\npod {key}:")
        lines.append(funnel_mod.render(rec))
    for name, rec in sorted(payload.get("nodes", {}).items()):
        extra = ", ".join(
            f"{k}={v}" for k, v in sorted(rec.items()) if k != "verdict"
        )
        lines.append(
            f"\nnode {name}: {rec.get('verdict', '?')}"
            + (f"  ({extra})" if extra else "")
        )
    for lp in payload.get("lp", []):
        groups = ", ".join(
            f"g{g['group']}@{g['dual']}" for g in lp.get("binding_groups", [])
        )
        lines.append(
            f"\nlp solve: bound={lp.get('bound')} binding=[{groups}] "
            f"cap_duals={lp.get('reservation_cap_duals')}"
        )
    return "\n".join(lines)


def _render_summary(name: str, summary: dict) -> str:
    lines = [f"== {name} ==",
             f"ticks={summary.get('ticks', 0)} "
             f"pods={summary.get('pods_recorded', 0)} "
             f"nodes={summary.get('nodes_recorded', 0)} "
             f"funnel_depth_p50={summary.get('funnel_depth_p50')}"]
    lines.append("verdicts:")
    lines.append(_fmt_counts(summary.get("verdicts", {})))
    lines.append("pod codes:")
    lines.append(_fmt_counts(summary.get("pod_codes", {})))
    return "\n".join(lines)


def report(payload: dict) -> str:
    """Dispatch on the payload shape (see module docstring)."""
    if "pod" in payload and "pods" not in payload:
        return _render_pod(payload)
    if "node" in payload and "nodes" not in payload:
        return _render_node(payload)
    if "pods" in payload and "nodes" in payload:
        return _render_record(payload)
    if "digest" in payload:
        return (
            f"{len(payload.get('ticks', []))} tick record(s); last: "
            + json.dumps(payload["digest"], sort_keys=True)
        )
    # bench JSON: arms carrying explain_summary blocks
    detail = payload.get("detail", payload)
    sections = [
        _render_summary(arm, body["explain_summary"])
        for arm, body in detail.items()
        if isinstance(body, dict) and "explain_summary" in body
    ]
    if not sections:
        return "(no explanation or explain_summary blocks found)"
    return "\n\n".join(sections)


def main(argv: list[str]) -> int:
    if len(argv) > 1:
        with open(argv[1]) as fh:
            payload = json.load(fh)
    else:
        payload = json.load(sys.stdin)
    print(report(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
