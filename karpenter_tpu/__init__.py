"""karpenter-tpu: a TPU-native node-autoscaling framework.

A from-scratch rebuild of the capabilities of Karpenter
(sigs.k8s.io/karpenter): just-in-time node provisioning driven by
unschedulable pods, price-aware bin-packing over cloud instance-type
catalogs, and continuous fleet disruption (emptiness / drift /
expiration / consolidation) under disruption budgets.

Where the reference runs its two hot paths (the provisioning
bin-packing loop and the consolidation search) as sequential in-process
Go heuristics, this framework formulates them as batched JAX/XLA
programs: pod x instance-type x offering feasibility is evaluated as
dense mask algebra on TPU, and the packing loop is a `lax.scan` whose
per-step work is vectorized over nodes and instance types.

Layer map (mirrors SURVEY.md section 1):
  apis/          NodePool / NodeClaim / NodeOverlay API types
  scheduling/    Requirement set-algebra, taints, hostports, volumes
  cloudprovider/ CloudProvider SPI, InstanceType/Offering model,
                 fake + kwok-style simulated providers
  kube/          in-memory API substrate (objects, watch, patch)
  state/         in-memory cluster mirror (Cluster, StateNode)
  solver/        the TPU solver: dense encodings + batched packing
  provisioning/  batcher, provisioner, scheduler orchestration
  disruption/    emptiness / drift / consolidation engine
  lifecycle/     nodeclaim launch/register/initialize, termination
  operator/      runtime wiring, options
"""

__version__ = "0.1.0"
