"""Preemption-aware provisioning: higher priority nominates victims.

Priority admission (provisioning/priority.py) decides WHO waits when
demand exceeds capacity at solve time. But a pending higher-priority
pod can also arrive AFTER lower-priority pods already bound — the
solve finds no launchable or existing capacity (pool limits, catalog
exhaustion) and the pod would wait behind workload it outranks. The
kube-scheduler answers this with preemption
(pkg/scheduler/framework/preemption); this controller is its analogue
on the provisioning side:

- **Who may preempt**: a pending pod with a capacity-class failure
  from the last solve, positive resolved priority, and a PriorityClass
  whose `preemptionPolicy` is not `Never`.
- **Who may be a victim**: a bound, evictable pod of STRICTLY lower
  priority — never equal or higher — that is not a daemon/mirror pod,
  not do-not-disrupt, and whose PodDisruptionBudgets allow the
  eviction (the whole victim SET is budgeted per PDB via
  `utils/pdb.py`, not just the first victim; the eviction subresource
  re-checks server-side).
- **Ordering** (the drain-after-replace discipline borrowed from
  disruption/interruption.py, transposed to pods): the landing is
  secured BEFORE anything is killed — the victim node is nominated
  (its state node's nomination window keeps consolidation off it, the
  preemptor's `status.nominatedNodeName` records the plan the way the
  kube-scheduler does), the preemptor's binding plan is handed to the
  operator's pending-binding queue, and only then are the victims
  evicted through the termination layer's EvictionQueue (PDB 429
  backoff and workload-owner rebirth semantics included). Displaced
  victims rebirth pending and re-enter the next solve, where priority
  admission sheds them if the overload persists — by policy, not by
  race.
- **Node choice** is deterministic: among feasible nodes the one with
  the smallest (highest victim priority, victim count, name) wins —
  evict the least important, fewest pods, stable tie-break.

Preemptors with machinery the fit check cannot model (topology
constraints, host ports, volumes, DRA) are skipped — the full
scheduler path owns those, and a wrong preemption is strictly worse
than a waiting pod.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional, Sequence

from karpenter_tpu.apis.v1.labels import DO_NOT_DISRUPT_ANNOTATION
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.metrics.store import (
    PREEMPTION_EVICTIONS,
    PREEMPTION_NOMINATIONS,
)
from karpenter_tpu.provisioning.priority import CAPACITY_ERRORS
from karpenter_tpu.provisioning.scheduler import SchedulerResults
from karpenter_tpu.scheduling.priority import (
    class_map,
    default_class,
    preemption_allowed,
    resolve_pod_priorities,
    resolve_priority,
)
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.scheduling.taints import tolerates_pod
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.utils.pdb import PdbLimits

log = logging.getLogger("karpenter.preemption")

# at most this many preemptors act per reconcile — each eviction churns
# the cluster, and the next solve re-ranks anyway
MAX_PREEMPTIONS_ENV = "KARPENTER_PREEMPTION_MAX"
DEFAULT_MAX_PREEMPTIONS = 16

WELL_KNOWN = None  # resolved lazily (import cycle hygiene)


def _well_known():
    global WELL_KNOWN
    if WELL_KNOWN is None:
        from karpenter_tpu.apis.v1.labels import WELL_KNOWN_LABELS

        WELL_KNOWN = WELL_KNOWN_LABELS
    return WELL_KNOWN


class PreemptionController:
    def __init__(self, kube, cluster, provisioner, recorder=None):
        self.kube = kube
        self.cluster = cluster
        self.provisioner = provisioner
        self.recorder = recorder
        from karpenter_tpu.lifecycle.termination import EvictionQueue

        # the termination layer's queue: PDB 429 backoff + simulation-
        # substrate rebirth, exactly as drains evict
        self.evictions = EvictionQueue(kube, recorder=recorder)
        # per-reconcile PriorityClass view for resolved comparisons
        self._classes: dict = {}
        self._default = None

    # -- one reconcile --------------------------------------------------------

    def reconcile(
        self, results: Optional[SchedulerResults],
        now: Optional[float] = None,
    ) -> list[SchedulerResults]:
        """Act on the round's capacity failures. Returns binding plans
        (preemptor -> nominated node) for the operator's pending-
        binding queue — the landing rides the same machinery every
        other placement does."""
        now = time.time() if now is None else now
        if results is None or not results.errors:
            return []
        preemptors = self._preemptors(results)
        if not preemptors:
            return []
        classes = class_map(self.kube.list("PriorityClass"))
        # victim comparisons must use RESOLVED priorities too: a bound
        # pod whose priority exists only through its priorityClassName
        # (stamped onto a different object copy, or never solved by us
        # at all) would otherwise read as 0 and be preemptable by a
        # lower-actual-priority pod
        self._classes = classes
        self._default = default_class(classes.values())
        budget = int(os.environ.get(
            MAX_PREEMPTIONS_ENV, str(DEFAULT_MAX_PREEMPTIONS)
        ))
        pdb = PdbLimits(self.kube)
        plans: list[SchedulerResults] = []
        for pod in preemptors:
            if budget <= 0:
                break
            if not preemption_allowed(pod, classes):
                continue
            choice = self._choose_victims(pod, pdb)
            if choice is None:
                continue
            node, victims = choice
            if not self._execute(pod, node, victims, now):
                continue
            budget -= 1
            binding = SchedulerResults(
                new_node_plans=[],
                existing_assignments={node.name: [pod]},
            )
            plans.append(binding)
        return plans

    # -- selection ------------------------------------------------------------

    def _preemptors(self, results: SchedulerResults) -> list[Pod]:
        """Capacity-failed pending pods with positive priority, highest
        first (deterministic tie-break on key)."""
        out = []
        for key, error in results.errors.items():
            if error not in CAPACITY_ERRORS:
                continue
            pod = self.kube.get_pod(*key.split("/", 1))
            if pod is None or pod.is_terminal() or pod.spec.node_name:
                continue
            spec = pod.spec
            if (
                spec.volumes or spec.topology_spread_constraints
                or spec.injected_requirements
            ):
                continue
            aff = spec.affinity
            if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
                continue
            from karpenter_tpu.scheduling.hostports import pod_host_ports

            if pod_host_ports(pod):
                continue
            out.append(pod)
        resolve_pod_priorities(out, self.kube)
        out = [p for p in out if p.spec.priority > 0]
        out.sort(key=lambda p: (-p.spec.priority, p.key))
        return out

    def _priority(self, pod: Pod) -> int:
        """The pod's RESOLVED priority against this reconcile's class
        map (see reconcile); raw spec.priority when already stamped."""
        return resolve_priority(pod, self._classes, self._default)

    def _choose_victims(self, pod: Pod, pdb: PdbLimits):
        """The deterministic node + minimal victim set for one
        preemptor, or None when no node can be freed for it."""
        pod_reqs = Requirements.from_pod(pod, required_only=True)
        requests = resutil.pod_requests(pod)
        best = None
        best_score = None
        for node in sorted(self.cluster.nodes(), key=lambda n: n.name):
            if node.deleting() or node.node is None:
                continue
            if tolerates_pod(list(node.taints()), pod) is not None:
                continue
            node_reqs = Requirements.from_labels(node.labels())
            if not node_reqs.is_compatible(
                pod_reqs, allow_undefined=_well_known()
            ):
                continue
            victims = self._victims_on(node, pod, requests, pdb)
            if victims is None:
                continue
            score = (
                max(self._priority(v) for v in victims),
                len(victims),
                node.name,
            )
            if best_score is None or score < best_score:
                best, best_score = (node, victims), score
        return best

    def _victims_on(self, node, pod: Pod, requests, pdb: PdbLimits):
        """Minimal lower-priority victim set on one node that frees
        room for `pod`, lowest priorities evicted first; None when the
        node cannot be freed within the rules."""
        candidates = []
        for pod_key in node.pod_keys:
            victim = self.kube.get_pod(*pod_key.split("/", 1))
            if victim is None or victim.is_terminal() or victim.is_terminating():
                continue
            if victim.owner_kind() in ("DaemonSet", "Node"):
                continue
            # resolved comparison (see reconcile): a class-named bound
            # pod must rank at its class value, not the unstamped 0
            if resolve_priority(
                victim, self._classes, self._default
            ) >= pod.spec.priority:
                continue  # never equal or higher
            if (
                victim.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION)
                == "true"
            ):
                continue
            if pdb.can_evict(victim) is not None:
                continue
            candidates.append(victim)
        if not candidates:
            return None
        candidates.sort(key=lambda v: (self._priority(v), v.key))
        available = dict(node.available())
        chosen: list[Pod] = []
        per_pdb: dict[str, int] = {}
        for victim in candidates:
            if resutil.fits(requests, available):
                break
            # the whole victim set must stay within every selecting
            # PDB's remaining budget — can_evict above is per pod and
            # cannot see its siblings
            blocked = False
            for budget in pdb.matching(victim):
                used = per_pdb.get(budget.key, 0)
                if used + 1 > pdb.disruptions_allowed(budget):
                    blocked = True
                    break
            if blocked:
                continue
            for budget in pdb.matching(victim):
                per_pdb[budget.key] = per_pdb.get(budget.key, 0) + 1
            chosen.append(victim)
            available = resutil.merge(
                available, resutil.pod_requests(victim)
            )
        if not chosen or not resutil.fits(requests, available):
            return None
        return chosen

    # -- execution ------------------------------------------------------------

    def _execute(self, pod: Pod, node, victims: Sequence[Pod],
                 now: float) -> bool:
        """Nominate first, then evict — the landing is secured before
        anything is killed (the pod-level drain-after-replace)."""
        node.nominate(now=now)
        pod.status.nominated_node_name = node.name
        self.kube.touch(pod)
        PREEMPTION_NOMINATIONS.inc()
        from karpenter_tpu import explain

        if explain.active() is not None:
            # the preemption verdict: who landed where, at what
            # priority cutoff, over which victim set — queryable at
            # /debug/explain?pod=<preemptor or victim>
            explain.note_pod(
                pod.key, verdict="preempted-onto", node=node.name,
                cutoff_priority=int(pod.spec.priority),
                victims=sorted(v.key for v in victims),
            )
            for victim in victims:
                explain.note_pod(
                    victim.key, verdict="preemption-victim",
                    preemptor=pod.key, node=node.name,
                    victim_priority=self._priority(victim),
                )
        self._record(pod, node, victims, now)
        evicted = 0
        for victim in victims:
            # EvictionQueue: the eviction subresource (server-side PDB
            # re-check), 429 backoff, and workload-owner rebirth on the
            # simulation substrate — exactly how drains evict
            if self.evictions.evict(victim, now=now):
                evicted += 1
                PREEMPTION_EVICTIONS.inc({
                    "nodepool": node.nodepool_name() or "",
                })
            else:
                log.warning(
                    "preemption: eviction of %s for %s blocked "
                    "(PDB raced the plan); will retry next round",
                    victim.key, pod.key,
                )
        log.info(
            "preemption: %s (priority %d) nominated node %s; evicted "
            "%d/%d lower-priority victim(s)",
            pod.key, pod.spec.priority, node.name, evicted, len(victims),
        )
        return evicted > 0

    def _record(self, pod: Pod, node, victims: Sequence[Pod],
                now: float) -> None:
        if self.recorder is None:
            return
        from karpenter_tpu.events.recorder import Event

        self.recorder.publish(Event(
            kind="Pod", name=pod.metadata.name,
            namespace=pod.metadata.namespace, type="Normal",
            reason="Nominated",
            message=f"Pod should preempt onto node {node.name} "
                    f"({len(victims)} lower-priority victim(s))",
        ), now=now)
        for victim in victims:
            self.recorder.publish(Event(
                kind="Pod", name=victim.metadata.name,
                namespace=victim.metadata.namespace, type="Warning",
                reason="Preempted",
                message=f"Preempted by higher-priority pod {pod.key}",
            ), now=now)
