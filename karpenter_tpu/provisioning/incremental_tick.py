"""Incremental live tick: the provisioner's retained-state reconcile.

PR 1's `IncrementalPipeline` proved the warm-start math (4.3x on
50k-pod/1% churn) but lived as a library/bench surface; the live
reconcile loop still paid O(fleet) per tick — a deep-copied cluster
snapshot, a fresh `ExistingNodeInput` per node, a topology rebuild
over every bound pod, and an encode whose pseudo-config axis spanned
the whole fleet. This module promotes the incremental structure to THE
operator tick:

- **Retained state**: one `ExistingNodeInput` per live/in-flight node,
  built by the SAME `NodeInputBuilder` the full Scheduler uses, kept
  across rounds and refreshed only for keys the kube watch stream
  marked dirty (`DirtyTracker` with mapped keys: a Pod event dirties
  the node it is bound to; a NodeClaim event dirties both its claim
  key and its node). A 410-driven relist marks EVERYTHING dirty — the
  diff events of a relist cannot prove nothing else changed while the
  watch was stale, so lost continuity always costs one full rebuild,
  never a silent stale row.

- **Backstops**: strict eligibility gates route anything the batched
  fast path cannot express (topology, host ports, volumes, DRA,
  minValues pools, spot budgets, reservations) to the unchanged full
  Scheduler; a churn threshold (`KARPENTER_INCR_CHURN_MAX`) does the
  same when the dirty fraction says incrementality has nothing left to
  save.

- **Oracle audit**: on a sampled cadence (`KARPENTER_INCR_AUDIT_EVERY`)
  — and ALWAYS after fault-injector activity, crash recovery, or while
  on post-quarantine probation — the tick also runs the full Scheduler
  as a shadow and fingerprints both decision sets. Divergence
  quarantines the retained state (cleared, encoder cache busted,
  divergence recorded for replay) and serves the full-solve decision;
  the next tick rebuilds from scratch and must pass a probation audit
  before the cache is trusted again. The `incremental_poison`
  degradation rung (solver/resilience.py) records every quarantined
  serve, so a poisoned cache degrades to a full solve — never to a
  wrong fleet.

- **Chaos**: `cache_poison@incremental` (solver/faults.py) corrupts
  one retained capacity row deterministically; `operator_crash` fires
  at `crash_incr_solve` (dirty sets drained, solve not yet run) and
  `crash_incr_commit` (solved, plans not yet written) so the
  restart-chaos suite can kill the operator inside the incremental
  tick and assert the rebuilt cache converges.

Decision identity is the design invariant: on eligible ticks the
encode inputs (same builder, same ordering — live nodes in cluster
order, in-flight fewest-pods-first — same catalog sort, same residual
prune that provably preserves first-feasible order) match the full
Scheduler's, so the audit asserts equality, not a tolerance band.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import replace
from typing import Callable, Optional, Sequence

from karpenter_tpu.kube.dirty import DirtyTracker
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.metrics.store import (
    INCREMENTAL_AUDITS,
    INCREMENTAL_DIVERGENCE,
    INCREMENTAL_FINGERPRINT_AGE,
    INCREMENTAL_TICK,
    SCHEDULER_QUEUE_DEPTH,
    SCHEDULER_SCHEDULING_DURATION,
    SCHEDULER_UNSCHEDULABLE_PODS,
)
from karpenter_tpu.provisioning.scheduler import (
    NO_CAPACITY_ERROR,
    SOLVE_TIMEOUT_SECONDS,
    NodeInputBuilder,
    SchedulerResults,
    _pool_requirements,
    _state_node_key,
    finalize_plan,
    pool_spot_budget,
)
from karpenter_tpu.scheduling.hostports import pod_host_ports
from karpenter_tpu import tracing
from karpenter_tpu.solver import faults
from karpenter_tpu.solver.encode import encode, group_pods
from karpenter_tpu.solver.incremental import (
    _env_float,
    catalog_fingerprint,
)
from karpenter_tpu.solver.solver import solve_encoded
from karpenter_tpu.utils import resources as resutil

log = logging.getLogger("karpenter.incremental")

ENV_ENABLE = "KARPENTER_INCREMENTAL"
ENV_AUDIT_EVERY = "KARPENTER_INCR_AUDIT_EVERY"
ENV_CHURN_MAX = "KARPENTER_INCR_CHURN_MAX"

MAX_DIVERGENCE_RECORDS = 16
RETRY_ROUNDS = 16  # k-way-evicted re-solve bound, mirrors Scheduler._solve


def incremental_enabled() -> bool:
    """KARPENTER_INCREMENTAL gate, default ON (the live tick is the
    default path; the env knob is the operator's kill switch)."""
    return os.environ.get(ENV_ENABLE, "1").lower() not in (
        "0", "false", "off"
    )


def _pod_node_keys(event: str, pod) -> list[str]:
    """A Pod event dirties the node the pod is (or was) bound to —
    its usage row changed. Unbound pods touch no retained row."""
    return [pod.spec.node_name] if pod.spec.node_name else []


def _claim_keys(event: str, claim) -> list[str]:
    """A NodeClaim event dirties its claim key (the in-flight state
    key) AND its node's key once one materialized — registration moves
    the state key from claim name to node name, and both entries must
    refresh across that transition."""
    keys = [claim.metadata.name]
    if claim.status.node_name:
        keys.append(claim.status.node_name)
    return keys


def decision_fingerprint(results: SchedulerResults) -> tuple:
    """Name-insensitive identity of one scheduling decision: what the
    oracle audit diffs between the incremental and full paths. New
    plans are identified by (pool, resolved launch target, price, pod
    set); existing assignments by (state key, pod set); failures by
    (pod key, reason)."""
    new = []
    for plan in results.new_node_plans:
        it, off = plan.primary()
        new.append((
            plan.pool.metadata.name if plan.pool is not None else "",
            it.name if it is not None else "",
            (off.zone, off.capacity_type) if off is not None else ("", ""),
            round(float(plan.price), 6),
            tuple(sorted(p.key for p in plan.pods)),
        ))
    existing = sorted(
        (key, tuple(sorted(p.key for p in pods)))
        for key, pods in results.existing_assignments.items()
    )
    return (
        tuple(sorted(new)),
        tuple(existing),
        tuple(sorted(results.errors.items())),
    )


class IncrementalTickScheduler:
    """The provisioner's retained-state solve seam (see module doc).

    `tick(pods, pools_with_types)` returns SchedulerResults when the
    incremental path served (or the quarantine path served the
    full-solve decision), or None when the caller must route through
    the full Scheduler (ineligible tick / churn blow-out)."""

    def __init__(
        self,
        kube,
        cluster,
        compat_cache,
        make_scheduler: Callable,
        options=None,
        clock=None,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cache = compat_cache
        # factory(pools_with_types, metrics_controller) -> Scheduler —
        # the provisioner's own full-path construction, reused verbatim
        # for the shadow oracle so the audit compares against exactly
        # what the fallback path would have decided
        self._make_scheduler = make_scheduler
        self.options = options
        self.clock = clock if clock is not None else time.monotonic
        self.churn_max = _env_float(ENV_CHURN_MAX, 0.25)
        self.audit_every = int(_env_float(ENV_AUDIT_EVERY, 16))
        self._tracker = DirtyTracker(kube)
        self._tracker.watch("Node")
        self._tracker.watch("NodeClaim", key=_claim_keys)
        self._tracker.watch("Pod", key=_pod_node_keys)
        # any DaemonSet change invalidates every node's daemon reserve
        # and the per-pool overhead: one sentinel key = rebuild all
        self._tracker.watch("DaemonSet", key=lambda e, o: ["*"])
        # retained state
        self._inputs: dict = {}            # state key -> ExistingNodeInput
        self._order: list[str] = []        # Scheduler's existing-node order
        self._builder: Optional[NodeInputBuilder] = None
        self._builder_fp: Optional[tuple] = None
        self._daemon_overhead: dict = {}
        self._catalog_has_reserved = False
        # audit / quarantine state
        self._ticks = 0
        self._since_audit = 0
        self._age = 0                      # ticks since last full rebuild
        self._quarantined = False
        self._warm_pending = False   # cold bail taken; next tick warms
        self._force_audit: Optional[str] = None   # pending trigger
        self._last_fault_len = 0
        self._last_audit: dict = {}
        self.divergences: list[dict] = []
        self._counts = {"incremental": 0, "full_backstop": 0,
                        "quarantined": 0}

    # -- external triggers ----------------------------------------------------

    def on_recover(self) -> None:
        """Crash-recovery hook (Operator._recover): a predecessor's
        retained state died with it, and whatever THIS process has
        accumulated before recovery ran cannot be vouched for either.
        Rebuild from scratch and audit the first incremental tick."""
        self._invalidate(trigger="recovery")

    def _invalidate(self, trigger: str) -> None:
        self._inputs.clear()
        self._order = []
        if self._builder is not None:
            self._builder = None
            self._builder_fp = None
        self._tracker.clear()
        self._force_audit = trigger
        self._age = 0

    # -- tick -----------------------------------------------------------------

    def tick(
        self, pods: Sequence[Pod], pools_with_types,
    ) -> Optional[SchedulerResults]:
        if not incremental_enabled():
            tracing.annotate(path="full", reason="disabled")
            return None
        t0 = self.clock()
        self._ticks += 1
        # fault-injector activity since the last tick distrusts the
        # retained state enough to force an audit: injected kube
        # faults (conflicts, stale lists, watch drops) are exactly the
        # conditions under which dirty-set plumbing can miss a change
        inj = faults.get()
        fault_len = len(inj.snapshot_log()) if inj is not None else 0
        if fault_len != self._last_fault_len:
            self._last_fault_len = fault_len
            if self._force_audit is None:
                self._force_audit = "fault"

        reason = self._ineligible(pods, pools_with_types)
        if reason is not None:
            tracing.annotate(path="full_backstop", reason=reason)
            INCREMENTAL_TICK.inc({"path": "full_backstop", "reason": reason})
            self._counts["full_backstop"] += 1
            return None

        pools = self._sorted_pools(pools_with_types)
        cold = not self._inputs
        if (
            cold
            and not self._warm_pending
            # a quarantined (probation) or forced-audit tick must
            # rebuild AND audit now — deferring a tick would leave an
            # unaudited window after recovery/divergence
            and not self._quarantined
            and self._force_audit is None
            and any(not sn.deleting() for sn in self.cluster.nodes())
        ):
            # Cold cache against a live fleet: building every retained
            # input AND paying the full Scheduler's own per-node build
            # in one tick would double the first tick's cost — bail to
            # the full path untouched (<5% cold overhead is a
            # perf-floor guarantee) and warm on the NEXT tick, whose
            # sync is the one-time O(fleet) rebuild.
            self._warm_pending = True
            tracing.annotate(path="full_backstop", reason="cold")
            INCREMENTAL_TICK.inc({"path": "full_backstop",
                                  "reason": "cold"})
            self._counts["full_backstop"] += 1
            return None
        self._warm_pending = False
        churn = self._sync(pools)
        # the poison site fires AFTER sync so a corrupted row is not
        # immediately rebuilt away — the audit must catch it instead
        self._consume_poison()
        # crash window: dirty sets drained (their marks are GONE from
        # the tracker), solve not yet run — a restart must rebuild the
        # cache from the API, not resurrect the drained delta
        faults.fire("crash_incr_solve")
        if pods and not cold and churn > self.churn_max and (
            not self._quarantined
        ):
            tracing.annotate(path="full_backstop", reason="churn")
            INCREMENTAL_TICK.inc({"path": "full_backstop",
                                  "reason": "churn"})
            self._counts["full_backstop"] += 1
            return None

        from karpenter_tpu.solver import resilience

        resilience.pop_degraded()  # scope the report to THIS solve
        results, fallback = self._solve(pods, pools)
        degraded = resilience.pop_degraded()
        if results is not None and degraded:
            log.warning(
                "incremental solve served degraded via rung(s) %s",
                sorted(set(degraded)),
            )
            results.degraded_rungs = sorted(set(degraded))
        if results is None:
            # the solve left pods only the relaxation ladder can help:
            # hand the whole tick to the full path
            tracing.annotate(path="full_backstop", reason=fallback)
            INCREMENTAL_TICK.inc({"path": "full_backstop",
                                  "reason": fallback})
            self._counts["full_backstop"] += 1
            return None

        self._since_audit += 1
        audit_trigger = self._audit_trigger(pods)
        if audit_trigger is not None:
            ok, shadow = self._audit(pods, pools_with_types, results,
                                     audit_trigger)
            if not ok:
                # serve the full-solve decision; retained state is
                # already quarantined by _audit. The tick degraded
                # through the ladder's incremental_poison rung — make
                # that visible the same way backend degradations are.
                shadow.degraded_rungs = sorted(
                    set(shadow.degraded_rungs) | {"incremental_poison"}
                )
                faults.fire("crash_incr_commit")
                self._note_explanations(pods, shadow, pools_with_types)
                self._publish_solver_metrics(shadow, t0)
                tracing.annotate(path="quarantined",
                                 reason=audit_trigger)
                INCREMENTAL_TICK.inc({"path": "quarantined",
                                      "reason": audit_trigger})
                self._counts["quarantined"] += 1
                return shadow
            if self._quarantined:
                log.info("incremental cache leaves quarantine: "
                         "probation audit passed")
                self._quarantined = False

        self._age += 1
        INCREMENTAL_FINGERPRINT_AGE.set(float(self._age))
        # crash window: solved, plans not yet handed back for
        # NodeClaim writes
        faults.fire("crash_incr_commit")
        self._note_explanations(pods, results, pools_with_types)
        self._publish_solver_metrics(results, t0)
        tracing.annotate(
            path="incremental",
            reason="audited" if audit_trigger is not None else "steady",
        )
        INCREMENTAL_TICK.inc({
            "path": "incremental",
            "reason": "audited" if audit_trigger is not None else "steady",
        })
        self._counts["incremental"] += 1
        return results

    def _note_explanations(self, pods, results: SchedulerResults,
                           pools_with_types) -> None:
        """Explain-plane parity with the full path (ISSUE 14): a pod
        left unschedulable by the LIVE serve — incremental fast path
        or the quarantine tick's shadow decision — gets the same
        verdict + elimination funnel the full Scheduler would record,
        through the same module-level seam."""
        if not results.errors:
            return
        from karpenter_tpu.provisioning.scheduler import (
            note_unschedulable_explanations,
        )

        note_unschedulable_explanations(
            pods, results, self._sorted_pools(pools_with_types),
            list(self._inputs.values()), self._daemon_overhead,
        )

    def _publish_solver_metrics(self, results: SchedulerResults,
                                t0: float) -> None:
        """Scheduler-subsystem series parity: dashboards watching
        controller="provisioner" must keep reading the live solve no
        matter which path served it."""
        labels = {"controller": "provisioner"}
        SCHEDULER_SCHEDULING_DURATION.observe(self.clock() - t0, labels)
        SCHEDULER_QUEUE_DEPTH.set(0.0, labels)
        SCHEDULER_UNSCHEDULABLE_PODS.set(float(len(results.errors)), labels)

    # -- eligibility ----------------------------------------------------------

    def _ineligible(self, pods, pools_with_types) -> Optional[str]:
        """First reason this tick cannot ride the retained-state fast
        path, or None. Every gate here names machinery only the full
        Scheduler implements — the audit's equality claim holds only
        inside this envelope."""
        from karpenter_tpu.utils.pod import has_dra_requirements

        for pod in pods:
            spec = pod.spec
            if spec.priority or spec.priority_class_name:
                # priority-bearing ticks route to the full path: the
                # admission contract (Provisioner._enforce_priority_
                # admission) wraps the full Scheduler's results, and
                # the retained-state solve has no shed/cutoff
                # machinery. Conservative first cut — widening the
                # envelope to uniform-nonzero-priority ticks is a
                # follow-up once the oracle audit covers it.
                return "priority"
            if spec.volumes or spec.injected_requirements:
                return "volumes"
            if pod_host_ports(pod):
                return "host_ports"
            if spec.topology_spread_constraints:
                return "topology"
            aff = spec.affinity
            if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
                return "topology"
            if has_dra_requirements(pod):
                return "dra"
        if self.cluster.pods_with_anti_affinity():
            # live pods with required anti-affinity repel matching new
            # pods — only the Topology tracker models that
            return "anti_affinity"
        has_reserved = False
        for pool, types in pools_with_types:
            if _pool_requirements(pool).has_min_values():
                return "min_values"
            if pool_spot_budget(pool) != (1.0, 0):
                return "spot_budget"
            if not has_reserved:
                has_reserved = any(
                    o.is_reserved() for it in types for o in it.offerings
                )
        if has_reserved:
            # reservation budgets need the live reserved_in_use ledger
            return "reserved"
        return None

    @staticmethod
    def _sorted_pools(pools_with_types):
        # weight order, exactly as Scheduler.__init__ sorts
        return sorted(
            pools_with_types,
            key=lambda pt: (-pt[0].spec.weight, pt[0].metadata.name),
        )

    # -- retained-state sync --------------------------------------------------

    def _sync(self, pools) -> float:
        """Refresh the retained inputs from cluster state, O(dirty).
        Returns the churn fraction (rebuilt rows / fleet)."""
        rebuild_all = self._tracker.relisted(
            "Node", "NodeClaim", "Pod", "DaemonSet"
        )
        if self._tracker.drain("DaemonSet"):
            rebuild_all = True
        dirty = (
            self._tracker.drain("Node")
            | self._tracker.drain("NodeClaim")
            | self._tracker.drain("Pod")
        )
        fp = catalog_fingerprint(pools)
        if rebuild_all or fp != self._builder_fp or self._builder is None:
            # catalog moved (price flip, pool edit, type rebuild): the
            # builder pins the types it resolves min-admissible
            # allocatable from, and the per-pool daemon overhead hangs
            # off the pool templates — rebuild both. Retained NODE
            # inputs survive: they derive from node labels/usage, not
            # prices. rebuild_all (DaemonSet churn or a relist) must
            # ALSO rebuild the builder: it pins the daemonset list it
            # computes per-node reserves and per-pool overhead from,
            # and the catalog fingerprint cannot see daemonsets move.
            daemonsets = self.cluster.daemonsets()
            self._builder = NodeInputBuilder(
                pools, daemonsets,
                self.options.ignore_dra_requests
                if self.options is not None else True,
            )
            self._builder_fp = fp
            self._daemon_overhead = self._builder.daemon_overhead()
        if rebuild_all:
            self._inputs.clear()
            self._age = 0

        rebuilt = 0
        live: list[str] = []
        inflight: list[tuple[tuple, str]] = []
        seen: set[str] = set()
        for sn in self.cluster.nodes():
            if sn.deleting():
                continue
            key = _state_node_key(sn)
            if not key:
                continue
            seen.add(key)
            # in-flight/unlaunched entries are few and transition-heavy
            # (claim -> node identity, registration filling status):
            # rebuild them every tick instead of chasing edge cases.
            # Their rebuilds do NOT count toward churn — a scale-up
            # burst with many in-flight claims is exactly when the
            # incremental path saves the most, and counting the
            # always-rebuilt volatile rows would wedge it on the
            # churn backstop for the whole materialization window.
            volatile = sn.node is None or not sn.registered()
            if key not in self._inputs or key in dirty or volatile:
                self._builder.invalidate(key)
                self._inputs[key] = self._builder.existing_input(sn)
                if not volatile:
                    rebuilt += 1
            if sn.initialized():
                live.append(key)
            else:
                inflight.append(((len(sn.pod_keys), sn.name), key))
        for key in [k for k in self._inputs if k not in seen]:
            del self._inputs[key]
            self._builder.invalidate(key)
        inflight.sort()
        self._order = live + [key for _, key in inflight]
        return rebuilt / max(1, len(self._inputs))

    def _consume_poison(self) -> None:
        try:
            faults.fire("incremental")
        except faults.CachePoisonError as err:
            if not self._inputs:
                log.warning("cache_poison fired on an empty retained "
                            "state; nothing to corrupt (%s)", err)
                return
            victim = min(self._inputs)
            inp = self._inputs[victim]
            # phantom capacity: the corrupted row looks roomy, so the
            # incremental solve places pods the full solve would buy a
            # node for — a real stale-cache failure mode, deterministic
            self._inputs[victim] = replace(
                inp,
                available=resutil.merge(
                    inp.available, {"cpu": 1024.0, "memory": 2.0**42}
                ),
            )
            log.warning("fault injected: %s (corrupted retained row %s)",
                        err, victim)
            if self._force_audit is None:
                self._force_audit = "fault"

    # -- solve ----------------------------------------------------------------

    def _solve(
        self, pods: Sequence[Pod], pools,
    ) -> tuple[Optional[SchedulerResults], str]:
        """The batched fast path against the retained inputs. Returns
        (results, "") or (None, reason) when only the full path's
        relaxation ladder can finish the tick."""
        results = SchedulerResults(new_node_plans=[],
                                   existing_assignments={})
        if not pods:
            return results, ""
        work = dict(self._inputs)   # per-tick view; commits copy-on-write
        open_plans: list = []
        place = list(pods)
        still_failed: list[Pod] = []
        # same wall budget the full Scheduler's _solve enforces; a
        # blown budget hands the WHOLE tick to the full path, which
        # owns the TIMEOUT_ERROR semantics (stamping partial timeouts
        # here would make the audit's fingerprint comparison racy)
        deadline = self.clock() + SOLVE_TIMEOUT_SECONDS
        for _ in range(1 + RETRY_ROUNDS):
            if not place:
                break
            if self.clock() > deadline:
                return None, "timeout"
            groups = group_pods(place)
            chosen = self._pruned_keys(groups, work)
            enc = encode(
                groups, pools,
                [work[k] for k in chosen],
                self._daemon_overhead,
                compat_cache=self.cache,
            )
            sol = solve_encoded(enc)
            for a in sol.existing:
                key = chosen[a.existing_index]
                results.existing_assignments.setdefault(key, []).extend(
                    a.pods
                )
                inp = work[key]
                usage = resutil.requests_for_pods(a.pods)
                work[key] = replace(
                    inp,
                    available=resutil.positive(
                        resutil.subtract(inp.available, usage)
                    ),
                    pod_count=inp.pod_count + len(a.pods),
                )
                # the committed row is provisional until the pods bind;
                # rebuild it from cluster truth next tick
                self._tracker.mark("Node", key)
            open_plans.extend(sol.new_nodes)
            evicted_keys = {p.key for p in sol.evicted}
            still_failed.extend(
                p for p in sol.unschedulable if p.key not in evicted_keys
            )
            # k-way-evicted pods are schedulable alone: retry them
            # against the committed state (mirrors Scheduler._solve)
            place = list(sol.evicted)
        still_failed.extend(place)  # retry bound hit

        for pod in still_failed:
            aff = pod.spec.affinity
            if aff is not None and aff.node_affinity is not None:
                # the relaxation ladder could still place this pod
                # (drop preferred terms / trailing OR-terms) — that
                # machinery lives only in the full Scheduler
                return None, "relaxation"
            results.errors[pod.key] = NO_CAPACITY_ERROR

        for plan in open_plans:
            finalize_plan(plan)
            results.new_node_plans.append(plan)
        return results, ""

    def _pruned_keys(self, groups, work: dict) -> list[str]:
        """Residual prune (exact, from IncrementalPipeline): a node
        below the componentwise MINIMUM request over keys EVERY group
        demands can hold none of them, and nodes only fill during a
        solve — dropping it preserves first-feasible order while
        shrinking the bound axis to nodes with real headroom. Survivors
        keep `self._order` — the Scheduler's existing-node axis order
        (live nodes in cluster order, in-flight fewest-pods-first) —
        so placements stay byte-identical with the full path's."""
        min_req: dict[str, float] = {}
        req_counts: dict[str, int] = {}
        for g in groups:
            for k, v in g.resources.items():
                if v <= 0:
                    continue
                req_counts[k] = req_counts.get(k, 0) + 1
                have = min_req.get(k)
                min_req[k] = v if have is None else min(have, v)
        min_req = {
            k: v for k, v in min_req.items()
            if req_counts[k] == len(groups)
        }
        out = []
        for key in self._order:
            inp = work.get(key)
            if inp is None:
                continue
            if any(
                inp.available.get(k, 0.0) < v for k, v in min_req.items()
            ):
                continue
            out.append(key)
        return out

    # -- oracle audit ---------------------------------------------------------

    def _audit_trigger(self, pods) -> Optional[str]:
        if not pods:
            return None   # empty decisions compare trivially equal
        if self._quarantined:
            return "probation"
        if self._force_audit is not None:
            trigger = self._force_audit
            self._force_audit = None
            return trigger
        if self.audit_every > 0 and self._since_audit >= self.audit_every:
            return "cadence"
        return None

    def _audit(
        self, pods, pools_with_types, results: SchedulerResults,
        trigger: str,
    ) -> tuple[bool, SchedulerResults]:
        """Shadow full solve + decision fingerprint diff. On
        divergence: quarantine the retained state, record the episode
        for replay, and hand back the shadow decision."""
        self._since_audit = 0
        shadow = self._make_scheduler(
            pools_with_types, "incremental_audit"
        ).solve(list(pods))
        want = decision_fingerprint(shadow)
        got = decision_fingerprint(results)
        ok = want == got
        self._last_audit = {
            "verdict": "ok" if ok else "divergence",
            "trigger": trigger,
            "tick": self._ticks,
        }
        INCREMENTAL_AUDITS.inc(
            {"verdict": self._last_audit["verdict"], "trigger": trigger}
        )
        if ok:
            return True, shadow
        INCREMENTAL_DIVERGENCE.inc()
        inj = faults.get()
        record = {
            "tick": self._ticks,
            "trigger": trigger,
            "incremental": got,
            "full": want,
            # the fired-fault log up to the divergence: replaying the
            # same spec + seed + workload reproduces this episode
            # byte-identically (FaultInjector.snapshot_log)
            "fault_log": inj.snapshot_log() if inj is not None else [],
        }
        self.divergences.append(record)
        del self.divergences[:-MAX_DIVERGENCE_RECORDS]
        log.error(
            "incremental oracle audit diverged (trigger=%s); "
            "quarantining retained state and serving the full-solve "
            "decision", trigger,
        )
        from karpenter_tpu.solver import resilience

        resilience.note_incremental_poison()
        self._quarantined = True
        self._invalidate(trigger="quarantine")
        # probation (the _quarantined gate) owns the follow-up audits;
        # leaving the force flag set would fire one extra shadow solve
        # AFTER probation clears, with a trigger label outside the
        # metric's documented set
        self._force_audit = None
        self.cache.invalidate()
        return False, shadow

    # -- observability --------------------------------------------------------

    def state_fingerprint(self) -> str:
        """Stable hash of the retained inputs — readyz surfaces it so
        two replicas (or a pre/post-restart pair) can be compared."""
        import hashlib

        rows = sorted(
            (
                key,
                inp.pool_name,
                inp.pod_count,
                tuple(sorted(
                    (k, round(v, 6)) for k, v in inp.available.items()
                )),
            )
            for key, inp in self._inputs.items()
        )
        return hashlib.sha256(repr(rows).encode()).hexdigest()

    def status(self) -> dict:
        return {
            "enabled": incremental_enabled(),
            "quarantined": self._quarantined,
            "retained_nodes": len(self._inputs),
            "fingerprint": self.state_fingerprint(),
            "fingerprint_age_ticks": self._age,
            "last_audit": dict(self._last_audit),
            "divergences": len(self.divergences),
            "ticks": dict(self._counts),
        }
