"""Incremental live tick: the provisioner's retained-state reconcile.

PR 1's `IncrementalPipeline` proved the warm-start math (4.3x on
50k-pod/1% churn) but lived as a library/bench surface; the live
reconcile loop still paid O(fleet) per tick — a deep-copied cluster
snapshot, a fresh `ExistingNodeInput` per node, a topology rebuild
over every bound pod, and an encode whose pseudo-config axis spanned
the whole fleet. This module promotes the incremental structure to THE
operator tick:

- **Retained state**: one `ExistingNodeInput` per live/in-flight node,
  built by the SAME `NodeInputBuilder` the full Scheduler uses, kept
  across rounds and refreshed only for keys the kube watch stream
  marked dirty (`DirtyTracker` with mapped keys: a Pod event dirties
  the node it is bound to; a NodeClaim event dirties both its claim
  key and its node). Alongside each input row the tick retains the
  node's TOPOLOGY-DOMAIN columns (labels, taints, hostname) and its
  RESERVATION column (the reservation id the node consumes) —
  refreshed by the same dirty marks, so topology-spread and
  reservation-holding ticks ride the O(dirty) path too (ISSUE 15). A
  410-driven relist marks EVERYTHING dirty — the diff events of a
  relist cannot prove nothing else changed while the watch was stale,
  so lost continuity always costs one full rebuild, never a silent
  stale row.

- **Eligibility envelope** (ISSUE 15 widened it): the fast path now
  expresses topology-spread constraints (lowered through the same
  `solver/topo_batch` machinery the full Scheduler uses, against a
  Topology built from the retained domain columns), reservation
  budgets (the retained reservation ledger feeds the encode exactly
  as `Scheduler.reserved_in_use` does), and priority-bearing ticks
  (priority-major grouping is inherited from `group_pods`; a
  mixed-priority tick that hits a capacity failure — the only case
  the admission/shed machinery acts on — hands the whole tick to the
  full path, reason `priority`). Strict gates still route anything
  the batched path cannot express (pod affinity/anti-affinity, host
  ports, volumes, DRA, minValues pools, non-default spot budgets) to
  the unchanged full Scheduler; a churn threshold
  (`KARPENTER_INCR_CHURN_MAX`) does the same when the dirty fraction
  says incrementality has nothing left to save. Per-reason fallback
  counts are retained and surfaced in `readyz()["incremental"]
  ["fallbacks"]` so envelope regressions are visible at a glance.

- **Oracle audit**: on a sampled cadence (`KARPENTER_INCR_AUDIT_EVERY`)
  — and ALWAYS after fault-injector activity, crash recovery, the
  first tick that exercises a newly-widened envelope shape
  (`envelope` trigger), or while on post-quarantine probation — the
  tick also runs the full Scheduler as a shadow and fingerprints both
  decision sets. Divergence quarantines the retained state (cleared,
  encoder cache busted, divergence recorded for replay) and serves
  the full-solve decision; the next tick rebuilds from scratch and
  must pass a probation audit before the cache is trusted again. The
  `incremental_poison` degradation rung (solver/resilience.py)
  records every quarantined serve, so a poisoned cache degrades to a
  full solve — never to a wrong fleet.

- **Chaos**: `cache_poison@incremental` (solver/faults.py) corrupts
  one retained capacity row deterministically; `operator_crash` fires
  at `crash_incr_solve` (dirty sets drained, solve not yet run) and
  `crash_incr_commit` (solved, plans not yet written) so the
  restart-chaos suite can kill the operator inside the incremental
  tick and assert the rebuilt cache converges.

Decision identity is the design invariant: on eligible ticks the
encode inputs (same builder, same ordering — live nodes in cluster
order, in-flight fewest-pods-first — same catalog sort, same residual
prune that provably preserves first-feasible order, same topology
lowering fed from the retained domain columns) match the full
Scheduler's, so the audit asserts equality, not a tolerance band.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, replace
from typing import Callable, Optional, Sequence

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    HOSTNAME_LABEL,
    RESERVATION_ID_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.kube.dirty import DirtyTracker
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.metrics.store import (
    INCREMENTAL_AUDITS,
    INCREMENTAL_DIVERGENCE,
    INCREMENTAL_FINGERPRINT_AGE,
    INCREMENTAL_TICK,
    SCHEDULER_QUEUE_DEPTH,
    SCHEDULER_SCHEDULING_DURATION,
    SCHEDULER_UNSCHEDULABLE_PODS,
    STATE_SHARD_INVALIDATIONS,
)
from karpenter_tpu.state.shards import shard_of
from karpenter_tpu.provisioning.preferences import relax, relaxable
from karpenter_tpu.provisioning.scheduler import (
    NO_CAPACITY_ERROR,
    SOLVE_TIMEOUT_SECONDS,
    NodeInputBuilder,
    SchedulerResults,
    _pool_requirements,
    _state_node_key,
    finalize_plan,
    plan_domains,
    plan_pseudo_input,
    pool_spot_budget,
)
from karpenter_tpu.scheduling.hostports import pod_host_ports
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu import tracing
from karpenter_tpu.solver import faults, topo_batch
from karpenter_tpu.solver.encode import encode, group_pods
from karpenter_tpu.solver.incremental import (
    _env_float,
    _env_on,
    catalog_fingerprint,
)
from karpenter_tpu.solver.solver import solve_encoded
from karpenter_tpu.utils import resources as resutil

log = logging.getLogger("karpenter.incremental")

ENV_ENABLE = "KARPENTER_INCREMENTAL"
ENV_AUDIT_EVERY = "KARPENTER_INCR_AUDIT_EVERY"
ENV_CHURN_MAX = "KARPENTER_INCR_CHURN_MAX"
# micro-solve dual certificate (ISSUE 17): opt-in reduced-cost batch
# ordering, plus an optional certified-spend defer gate (0 = off)
ENV_MICRO_DUAL = "KARPENTER_MICRO_DUAL"
ENV_MICRO_SPEND_MAX = "KARPENTER_MICRO_DUAL_SPEND_MAX"

MAX_DIVERGENCE_RECORDS = 16
RETRY_ROUNDS = 16  # k-way-evicted re-solve bound, mirrors Scheduler._solve


class _EnvelopeEscape(Exception):
    """An admission-loop re-solve left the incremental envelope
    (timeout, topology fallback): the whole tick must hand over."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def incremental_enabled() -> bool:
    """KARPENTER_INCREMENTAL gate, default ON (the live tick is the
    default path; the env knob is the operator's kill switch)."""
    return os.environ.get(ENV_ENABLE, "1").lower() not in (
        "0", "false", "off"
    )


def _pod_node_keys(event: str, pod) -> list[str]:
    """A Pod event dirties the node the pod is (or was) bound to —
    its usage row changed. Unbound pods touch no retained row."""
    return [pod.spec.node_name] if pod.spec.node_name else []


def _claim_keys(event: str, claim) -> list[str]:
    """A NodeClaim event dirties its claim key (the in-flight state
    key) AND its node's key once one materialized — registration moves
    the state key from claim name to node name, and both entries must
    refresh across that transition."""
    keys = [claim.metadata.name]
    if claim.status.node_name:
        keys.append(claim.status.node_name)
    return keys


@dataclass
class _NodeMeta:
    """The retained non-capacity columns of one node: what the full
    Scheduler re-derives per round for topology-domain discovery,
    pod-domain mapping and the reservation ledger. Rebuilt exactly
    when the node's `ExistingNodeInput` row rebuilds (same dirty
    marks), so the two retained views cannot drift from each other."""

    name: str                     # node name ("" while claim-keyed)
    labels: dict[str, str]
    taints: tuple
    rid: str                      # reservation id consumed, "" if none
    node: object                  # the LIVE StateNode (pod_keys source)


def decision_fingerprint(results: SchedulerResults) -> tuple:
    """Name-insensitive identity of one scheduling decision: what the
    oracle audit diffs between the incremental and full paths. New
    plans are identified by (pool, resolved launch target, price, pod
    set); existing assignments by (state key, pod set); failures by
    (pod key, reason)."""
    new = []
    for plan in results.new_node_plans:
        it, off = plan.primary()
        new.append((
            plan.pool.metadata.name if plan.pool is not None else "",
            it.name if it is not None else "",
            (off.zone, off.capacity_type) if off is not None else ("", ""),
            round(float(plan.price), 6),
            tuple(sorted(p.key for p in plan.pods)),
        ))
    existing = sorted(
        (key, tuple(sorted(p.key for p in pods)))
        for key, pods in results.existing_assignments.items()
    )
    return (
        tuple(sorted(new)),
        tuple(existing),
        tuple(sorted(results.errors.items())),
    )


class IncrementalTickScheduler:
    """The provisioner's retained-state solve seam (see module doc).

    `tick(pods, pools_with_types)` returns SchedulerResults when the
    incremental path served (or the quarantine path served the
    full-solve decision), or None when the caller must route through
    the full Scheduler (ineligible tick / churn blow-out)."""

    def __init__(
        self,
        kube,
        cluster,
        compat_cache,
        make_scheduler: Callable,
        options=None,
        clock=None,
        plans_over_limits: Optional[Callable] = None,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cache = compat_cache
        # factory(pools_with_types, metrics_controller) -> Scheduler —
        # the provisioner's own full-path construction, reused verbatim
        # for the shadow oracle so the audit compares against exactly
        # what the fallback path would have decided
        self._make_scheduler = make_scheduler
        # Provisioner._plans_over_limits: the admission loop's limit
        # simulation, consumed by the in-envelope shed/cutoff loop
        # (priority.enforce_admission over the incremental core).
        self._plans_over_limits = plans_over_limits
        self.options = options
        self.clock = clock if clock is not None else time.monotonic
        self.churn_max = _env_float(ENV_CHURN_MAX, 0.25)
        # KARPENTER_INCR_AUDIT_EVERY is re-read per access (ISSUE 17
        # satellite): PR 16's bench needed a forced-audit probe because
        # the knob froze at construction. Assignment still pins it.
        self._audit_every_override: Optional[int] = None
        self._tracker = DirtyTracker(kube)
        self._tracker.watch("Node")
        self._tracker.watch("NodeClaim", key=_claim_keys)
        self._tracker.watch("Pod", key=_pod_node_keys)
        # any DaemonSet change invalidates every node's daemon reserve
        # and the per-pool overhead: one sentinel key = rebuild all
        self._tracker.watch("DaemonSet", key=lambda e, o: ["*"])
        # retained state
        self._inputs: dict = {}            # state key -> ExistingNodeInput
        self._meta: dict[str, _NodeMeta] = {}   # state key -> _NodeMeta
        self._order: list[str] = []        # Scheduler's existing-node order
        self._builder: Optional[NodeInputBuilder] = None
        self._builder_fp: Optional[tuple] = None
        self._daemon_overhead: dict = {}
        self._rsv_in_use: dict[str, int] = {}   # Scheduler.reserved_in_use
        self._has_reserved = False
        # audit / quarantine state
        self._ticks = 0
        self._since_audit = 0
        self._age = 0                      # ticks since last full rebuild
        self._quarantined = False
        self._warm_pending = False   # cold bail taken; next tick warms
        self._force_audit: Optional[str] = None   # pending trigger
        self._last_fault_len = 0
        self._last_audit: dict = {}
        self.divergences: list[dict] = []
        self._counts = {"incremental": 0, "full_backstop": 0,
                        "quarantined": 0, "micro": 0}
        # micro-solve plane (ISSUE 17): defer rollup + the retained
        # dual certificate the micro batch ordering/defer gate spends
        self._micro_defers: dict[str, int] = {}
        self._micro_active = False
        self._dual = None
        self._dual_stale = True
        # per-reason full-path fallback rollup (ISSUE 15 satellite):
        # readyz()["incremental"]["fallbacks"] surfaces it so envelope
        # regressions show up at a glance
        self._fallbacks: dict[str, int] = {}
        # which widened-envelope shapes this cache generation has
        # served — the FIRST tick of each shape forces an audit
        self._envelope_seen: set[str] = set()

    # -- knobs ----------------------------------------------------------------

    @property
    def audit_every(self) -> int:
        """Audit cadence, live from the environment on every read so
        bench arms and operators can retune a running scheduler; an
        explicit assignment (tests pinning the cadence) overrides the
        env until reassigned."""
        if self._audit_every_override is not None:
            return self._audit_every_override
        return int(_env_float(ENV_AUDIT_EVERY, 16))

    @audit_every.setter
    def audit_every(self, value) -> None:
        self._audit_every_override = None if value is None else int(value)

    # -- external triggers ----------------------------------------------------

    def on_recover(self) -> None:
        """Crash-recovery hook (Operator._recover): a predecessor's
        retained state died with it, and whatever THIS process has
        accumulated before recovery ran cannot be vouched for either.
        Rebuild from scratch and audit the first incremental tick."""
        self._invalidate(trigger="recovery")

    def _invalidate(self, trigger: str) -> None:
        self._inputs.clear()
        self._meta.clear()
        self._order = []
        if self._builder is not None:
            self._builder = None
            self._builder_fp = None
        self._tracker.clear()
        self._force_audit = trigger
        self._age = 0
        self._envelope_seen.clear()
        self._dual = None
        self._dual_stale = True

    # -- tick -----------------------------------------------------------------

    def _note_fallback(self, reason: str) -> None:
        tracing.annotate(path="full_backstop", reason=reason)
        INCREMENTAL_TICK.inc({"path": "full_backstop", "reason": reason})
        self._counts["full_backstop"] += 1
        self._fallbacks[reason] = self._fallbacks.get(reason, 0) + 1

    def _note_defer(self, reason: str) -> None:
        """Micro-solve defer (ISSUE 17): the envelope routed a
        debounced arrival batch to the NEXT FULL TICK — nothing solves
        now, the operator re-arms the batcher. Kept distinct from
        fallbacks so readyz separates 'the micro path punted' from
        'the periodic tick left the envelope'."""
        tracing.annotate(path="micro_defer", reason=reason)
        INCREMENTAL_TICK.inc({"path": "micro_defer", "reason": reason})
        self._micro_defers[reason] = self._micro_defers.get(reason, 0) + 1

    def tick(
        self, pods: Sequence[Pod], pools_with_types, micro: bool = False,
    ) -> Optional[SchedulerResults]:
        """One reconcile solve. `micro=True` is the event-driven
        sub-tick path (ISSUE 17): same retained inputs, same audits,
        but every condition the full-path Scheduler would have to
        finish (ineligible shapes, cold cache, churn blow-out,
        mixed-priority shedding, quarantine) DEFERS to the next full
        tick instead of falling through to a full solve — the micro
        path must never pay O(fleet)."""
        self._micro_active = micro
        if not incremental_enabled():
            if micro:
                self._note_defer("disabled")
            else:
                tracing.annotate(path="full", reason="disabled")
            return None
        if micro and self._quarantined:
            # quarantine falls back to PURE periodic ticks: probation
            # audits belong to the full cadence, not the arrival path
            self._note_defer("quarantined")
            return None
        t0 = self.clock()
        self._ticks += 1
        # fault-injector activity since the last tick distrusts the
        # retained state enough to force an audit: injected kube
        # faults (conflicts, stale lists, watch drops) are exactly the
        # conditions under which dirty-set plumbing can miss a change
        inj = faults.get()
        fault_len = len(inj.snapshot_log()) if inj is not None else 0
        if fault_len != self._last_fault_len:
            self._last_fault_len = fault_len
            if self._force_audit is None:
                self._force_audit = "fault"

        reason = self._ineligible(pods, pools_with_types)
        if reason is not None:
            if micro:
                self._note_defer(reason)
            else:
                self._note_fallback(reason)
            return None

        pools = self._sorted_pools(pools_with_types)
        cold = not self._inputs
        if micro and cold:
            # a cold cache has nothing retained to solve against; the
            # next full tick owns the one-time O(fleet) warm-up — the
            # micro path never pays it (and must not flip the
            # _warm_pending latch the full path's cold bail owns)
            self._note_defer("cold")
            return None
        if (
            cold
            and not self._warm_pending
            # a quarantined (probation) or forced-audit tick must
            # rebuild AND audit now — deferring a tick would leave an
            # unaudited window after recovery/divergence
            and not self._quarantined
            and self._force_audit is None
            and any(not sn.deleting() for sn in self.cluster.nodes())
        ):
            # Cold cache against a live fleet: building every retained
            # input AND paying the full Scheduler's own per-node build
            # in one tick would double the first tick's cost — bail to
            # the full path untouched (<5% cold overhead is a
            # perf-floor guarantee) and warm on the NEXT tick, whose
            # sync is the one-time O(fleet) rebuild.
            self._warm_pending = True
            self._note_fallback("cold")
            return None
        self._warm_pending = False
        # the FIRST tick exercising a newly-widened envelope shape
        # (topology spread / reservations / priority) since the cache
        # was (re)built earns a forced audit: the equality claim for
        # the new machinery is proven live before it is trusted
        shape = set()
        if any(p.spec.topology_spread_constraints for p in pods):
            shape.add("topology")
        if self._has_reserved:
            shape.add("reserved")
        if any(p.spec.priority for p in pods):
            shape.add("priority")
        if any(relaxable(p) for p in pods):
            shape.add("relax")
        if shape - self._envelope_seen:
            self._envelope_seen |= shape
            if self._force_audit is None and not self._quarantined:
                self._force_audit = "envelope"
        churn = self._sync(pools)
        # the poison site fires AFTER sync so a corrupted row is not
        # immediately rebuilt away — the audit must catch it instead
        self._consume_poison()
        # crash window: dirty sets drained (their marks are GONE from
        # the tracker), solve not yet run — a restart must rebuild the
        # cache from the API, not resurrect the drained delta
        faults.fire("crash_incr_solve")
        if pods and not cold and churn > self.churn_max and (
            not self._quarantined
        ):
            if micro:
                self._note_defer("churn")
            else:
                self._note_fallback("churn")
            return None
        if micro and self._dual is not None:
            spend_max = _env_float(ENV_MICRO_SPEND_MAX, 0.0)
            if spend_max > 0:
                try:
                    bound = self._dual.bound_for(group_pods(list(pods)))
                except Exception:
                    bound = 0.0
                if bound > spend_max:
                    # weak duality certifies the batch buys at least
                    # `bound` of fresh capacity — non-trivial spend is
                    # the full tick's call (its repack/consolidation
                    # machinery sees the whole fleet picture)
                    self._note_defer("dual_spend")
                    return None

        from karpenter_tpu.solver import resilience

        # pre-relax preference state, per relaxable pod: relax()
        # REPLACES spec.affinity / the spread-constraint list (never
        # mutates them in place), so holding the old references is a
        # faithful snapshot. The audit restores these before its
        # shadow solve — the oracle must replay the same ladder from
        # the same base, not solve already-relaxed pods.
        prefs = {
            p.key: (p.spec.affinity,
                    tuple(p.spec.topology_spread_constraints))
            for p in pods if relaxable(p)
        }
        resilience.pop_degraded()  # scope the report to THIS solve
        results, fallback = self._solve(pods, pools, micro=micro)
        degraded = resilience.pop_degraded()
        if results is not None and degraded:
            log.warning(
                "incremental solve served degraded via rung(s) %s",
                sorted(set(degraded)),
            )
            results.degraded_rungs = sorted(set(degraded))
        if results is None:
            # the solve left pods only the full path's machinery (the
            # relaxation ladder, the per-pod topology path, priority
            # admission) can finish: hand the whole tick over
            if micro:
                self._note_defer(fallback)
            else:
                self._note_fallback(fallback)
            return None

        self._since_audit += 1
        audit_trigger = self._audit_trigger(pods)
        if audit_trigger is not None:
            ok, shadow = self._audit(pods, pools_with_types, results,
                                     audit_trigger, prefs)
            if not ok:
                # serve the full-solve decision; retained state is
                # already quarantined by _audit. The tick degraded
                # through the ladder's incremental_poison rung — make
                # that visible the same way backend degradations are.
                shadow.degraded_rungs = sorted(
                    set(shadow.degraded_rungs) | {"incremental_poison"}
                )
                faults.fire("crash_incr_commit")
                self._note_explanations(pods, shadow, pools_with_types)
                self._publish_solver_metrics(shadow, t0)
                tracing.annotate(path="quarantined",
                                 reason=audit_trigger)
                INCREMENTAL_TICK.inc({"path": "quarantined",
                                      "reason": audit_trigger})
                self._counts["quarantined"] += 1
                return shadow
            if self._quarantined:
                log.info("incremental cache leaves quarantine: "
                         "probation audit passed")
                self._quarantined = False

        self._age += 1
        INCREMENTAL_FINGERPRINT_AGE.set(float(self._age))
        # crash window: solved, plans not yet handed back for
        # NodeClaim writes
        faults.fire("crash_incr_commit")
        self._note_explanations(pods, results, pools_with_types)
        self._publish_solver_metrics(results, t0)
        path = "micro" if micro else "incremental"
        reason = "audited" if audit_trigger is not None else "steady"
        tracing.annotate(path=path, reason=reason)
        INCREMENTAL_TICK.inc({"path": path, "reason": reason})
        self._counts[path] += 1
        return results

    def _note_explanations(self, pods, results: SchedulerResults,
                           pools_with_types) -> None:
        """Explain-plane parity with the full path (ISSUE 14): a pod
        left unschedulable by the LIVE serve — incremental fast path
        or the quarantine tick's shadow decision — gets the same
        verdict + elimination funnel the full Scheduler would record,
        through the same module-level seam."""
        if not results.errors:
            return
        from karpenter_tpu.provisioning.priority import PRIORITY_SHED_ERROR
        from karpenter_tpu.provisioning.scheduler import (
            note_unschedulable_explanations,
        )

        # shed pods already carry the richer "shed" verdict from the
        # in-envelope admission loop (stamped after the last re-solve,
        # matching the full path's note ordering) — renoting them here
        # would overwrite it with a generic "unschedulable"
        noted = results
        if any(e == PRIORITY_SHED_ERROR for e in results.errors.values()):
            noted = replace(
                results,
                errors={k: e for k, e in results.errors.items()
                        if e != PRIORITY_SHED_ERROR},
            )
        note_unschedulable_explanations(
            pods, noted, self._sorted_pools(pools_with_types),
            list(self._inputs.values()), self._daemon_overhead,
        )

    def _publish_solver_metrics(self, results: SchedulerResults,
                                t0: float) -> None:
        """Scheduler-subsystem series parity: dashboards watching
        controller="provisioner" must keep reading the live solve no
        matter which path served it."""
        labels = {"controller": "provisioner"}
        SCHEDULER_SCHEDULING_DURATION.observe(self.clock() - t0, labels)
        SCHEDULER_QUEUE_DEPTH.set(0.0, labels)
        SCHEDULER_UNSCHEDULABLE_PODS.set(float(len(results.errors)), labels)

    # -- eligibility ----------------------------------------------------------

    def _ineligible(self, pods, pools_with_types) -> Optional[str]:
        """First reason this tick cannot ride the retained-state fast
        path, or None. Every gate here names machinery only the full
        Scheduler implements — the audit's equality claim holds only
        inside this envelope. ISSUE 15 widened the envelope: topology
        SPREAD constraints, reservation-holding catalogs and
        priority-bearing pods are now expressible (pod affinity /
        anti-affinity, host ports, volumes, DRA, minValues and
        non-default spot budgets still route full)."""
        from karpenter_tpu.utils.pod import has_dra_requirements

        for pod in pods:
            spec = pod.spec
            if spec.volumes or spec.injected_requirements:
                return "volumes"
            if pod_host_ports(pod):
                return "host_ports"
            aff = spec.affinity
            if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
                return "topology"
            if has_dra_requirements(pod):
                return "dra"
        if self.cluster.pods_with_anti_affinity():
            # live pods with required anti-affinity repel matching new
            # pods — only the Topology tracker models that
            return "anti_affinity"
        has_reserved = False
        for pool, types in pools_with_types:
            if _pool_requirements(pool).has_min_values():
                return "min_values"
            if pool_spot_budget(pool) != (1.0, 0):
                return "spot_budget"
            if not has_reserved:
                has_reserved = any(
                    o.is_reserved() for it in types for o in it.offerings
                )
        if has_reserved and not self._allow_reserved():
            # the ReservedCapacity gate strips reserved offerings from
            # the catalog — a per-round InstanceType rebuild the
            # retained fingerprints cannot cache; route full (rare
            # configuration, not worth fast-pathing)
            return "reserved"
        self._has_reserved = has_reserved
        return None

    def _allow_reserved(self) -> bool:
        if self.options is None:
            return True
        return bool(self.options.feature_gates.reserved_capacity)

    @staticmethod
    def _sorted_pools(pools_with_types):
        # weight order, exactly as Scheduler.__init__ sorts
        return sorted(
            pools_with_types,
            key=lambda pt: (-pt[0].spec.weight, pt[0].metadata.name),
        )

    # -- retained-state sync --------------------------------------------------

    def _node_meta(self, sn) -> _NodeMeta:
        labels = dict(sn.labels())
        # rid is extracted UNCONDITIONALLY (two cheap reads): metas
        # survive catalog changes, so a meta rebuilt while the catalog
        # was temporarily reservation-free (an ICE window) must not
        # undercount the ledger once the reservation returns
        rid = _node_reservation_id(sn, labels)
        return _NodeMeta(
            name=sn.name,
            labels=labels,
            taints=tuple(sn.taints()),
            rid=rid,
            node=sn,
        )

    def _sync(self, pools) -> float:
        """Refresh the retained inputs from cluster state, O(dirty).
        Returns the churn fraction (rebuilt rows / fleet)."""
        # node-keyed kinds ride the SCOPED continuity latch: a 410 on
        # one shard's logical stream dirties only the retained keys
        # routed to that shard (None = unscoped relist: everything).
        # DaemonSet relists stay whole-cache — daemon reserves are
        # fleet-wide.
        shards = self._tracker.relisted_shards("Node", "NodeClaim", "Pod")
        rebuild_all = shards is None or self._tracker.relisted("DaemonSet")
        if self._tracker.drain("DaemonSet"):
            rebuild_all = True
        dirty = (
            self._tracker.drain("Node")
            | self._tracker.drain("NodeClaim")
            | self._tracker.drain("Pod")
        )
        if shards and not rebuild_all:
            dirty |= {k for k in self._inputs if shard_of(k) in shards}
            STATE_SHARD_INVALIDATIONS.inc({"layer": "incremental"})
        fp = catalog_fingerprint(pools)
        if rebuild_all or fp != self._builder_fp or self._builder is None:
            # catalog moved (price flip, pool edit, type rebuild): the
            # builder pins the types it resolves min-admissible
            # allocatable from, and the per-pool daemon overhead hangs
            # off the pool templates — rebuild both. Retained NODE
            # inputs survive: they derive from node labels/usage, not
            # prices. rebuild_all (DaemonSet churn or a relist) must
            # ALSO rebuild the builder: it pins the daemonset list it
            # computes per-node reserves and per-pool overhead from,
            # and the catalog fingerprint cannot see daemonsets move.
            daemonsets = self.cluster.daemonsets()
            self._builder = NodeInputBuilder(
                pools, daemonsets,
                self.options.ignore_dra_requests
                if self.options is not None else True,
            )
            self._builder_fp = fp
            self._daemon_overhead = self._builder.daemon_overhead()
            # a catalog move invalidates the dual certificate: its
            # duals were Farley-scaled against the OLD prices
            self._dual = None
            self._dual_stale = True
        if rebuild_all:
            self._inputs.clear()
            self._meta.clear()
            self._age = 0

        rebuilt = 0
        live: list[str] = []
        inflight: list[tuple[tuple, str]] = []
        seen: set[str] = set()
        deleting_rids: list[str] = []
        for sn in self.cluster.nodes():
            if sn.deleting():
                # a deleting node holds its reservation instance until
                # it is gone (reservationmanager.go) — the ledger must
                # count it even though no retained row exists for it
                if self._has_reserved:
                    rid = _node_reservation_id(sn, sn.labels())
                    if rid:
                        deleting_rids.append(rid)
                continue
            key = _state_node_key(sn)
            if not key:
                continue
            seen.add(key)
            # in-flight/unlaunched entries are few and transition-heavy
            # (claim -> node identity, registration filling status):
            # rebuild them every tick instead of chasing edge cases.
            # Their rebuilds do NOT count toward churn — a scale-up
            # burst with many in-flight claims is exactly when the
            # incremental path saves the most, and counting the
            # always-rebuilt volatile rows would wedge it on the
            # churn backstop for the whole materialization window.
            volatile = sn.node is None or not sn.registered()
            if key not in self._inputs or key in dirty or volatile:
                self._builder.invalidate(key)
                self._inputs[key] = self._builder.existing_input(sn)
                self._meta[key] = self._node_meta(sn)
                if not volatile:
                    rebuilt += 1
            if sn.initialized():
                live.append(key)
            else:
                inflight.append(((len(sn.pod_keys), sn.name), key))
        for key in [k for k in self._inputs if k not in seen]:
            del self._inputs[key]
            self._meta.pop(key, None)
            self._builder.invalidate(key)
        inflight.sort()
        self._order = live + [key for _, key in inflight]
        # the reservation ledger, exactly as Scheduler.__init__ builds
        # it: live usage (every node holding a reservation id, incl.
        # deleting ones) bounds how many more instances a round may
        # open. Retained rids for live rows; deleting rows scanned
        # fresh above (few). Reservation-free catalogs skip all of it.
        if self._has_reserved:
            rsv: dict[str, int] = {}
            for meta in self._meta.values():
                if meta.rid:
                    rsv[meta.rid] = rsv.get(meta.rid, 0) + 1
            for rid in deleting_rids:
                rsv[rid] = rsv.get(rid, 0) + 1
            self._rsv_in_use = rsv
        else:
            self._rsv_in_use = {}
        return rebuilt / max(1, len(self._inputs))

    def _consume_poison(self) -> None:
        try:
            faults.fire("incremental")
        except faults.CachePoisonError as err:
            if not self._inputs:
                log.warning("cache_poison fired on an empty retained "
                            "state; nothing to corrupt (%s)", err)
                return
            victim = min(self._inputs)
            inp = self._inputs[victim]
            # phantom capacity: the corrupted row looks roomy, so the
            # incremental solve places pods the full solve would buy a
            # node for — a real stale-cache failure mode, deterministic
            self._inputs[victim] = replace(
                inp,
                available=resutil.merge(
                    inp.available, {"cpu": 1024.0, "memory": 2.0**42}
                ),
            )
            log.warning("fault injected: %s (corrupted retained row %s)",
                        err, victim)
            if self._force_audit is None:
                self._force_audit = "fault"

    # -- solve ----------------------------------------------------------------

    def _solve(
        self, pods: Sequence[Pod], pools, micro: bool = False,
    ) -> tuple[Optional[SchedulerResults], str]:
        """One incremental solve: the batched core, then — exactly
        when the full path's admission loop would act — the shared
        priority shed/cutoff loop re-solving the admitted prefix
        through the same core. Returns (results, "") or (None,
        reason) when only the full path's machinery can finish."""
        results, reason = self._solve_core(pods, pools)
        if results is None:
            return None, reason
        if self._priority_overloaded(pods, results):
            if micro:
                # a mixed-priority capacity failure is the shed loop's
                # case; shedding belongs to the full tick (ISSUE 17) —
                # a micro batch must never half-shed the backlog
                return None, "priority"
            return self._enforce_admission(pods, pools, results)
        return results, ""

    def _enforce_admission(
        self, pods, pools, results,
    ) -> tuple[Optional[SchedulerResults], str]:
        """Provisioner._enforce_priority_admission's shed/cutoff loop
        (provisioning/priority.py), in-envelope: the admitted prefix
        re-solves through the incremental core instead of a fresh full
        Scheduler. A re-solve that escapes the envelope mid-loop
        (timeout, topology lowering fallback) hands the WHOLE tick to
        the full path — a half-shed decision must never serve."""
        from karpenter_tpu.provisioning import priority as padm

        # first shed on this cache generation earns a forced audit,
        # like every other newly-widened envelope shape
        if "shed" not in self._envelope_seen:
            self._envelope_seen.add("shed")
            if self._force_audit is None and not self._quarantined:
                self._force_audit = "envelope"

        def solve_fn(keep):
            res, reason = self._solve_core(keep, pools)
            if res is None:
                raise _EnvelopeEscape(reason)
            return res

        try:
            results = padm.enforce_admission(
                list(pods), pools, results, solve_fn,
                plans_over_limits=self._plans_over_limits,
                daemon_overhead=lambda: self._daemon_overhead,
            )
        except _EnvelopeEscape as esc:
            return None, esc.reason
        return results, ""

    def _solve_core(
        self, pods: Sequence[Pod], pools,
    ) -> tuple[Optional[SchedulerResults], str]:
        """The batched fast path against the retained inputs —
        mirroring Scheduler._solve's structure: the simple pods ride
        one batched solve (+ eviction retries + per-pod relaxation),
        topology-spread pods ride the lowered topo_batch solve against
        a Topology built from the retained domain columns, and the
        round's reservation ledger is debited across both phases.
        Returns (results, "") or (None, reason) when only the full
        path's machinery can finish the tick."""
        results = SchedulerResults(new_node_plans=[],
                                   existing_assignments={})
        if not pods:
            return results, ""
        work = dict(self._inputs)   # per-tick view; commits copy-on-write
        open_plans: list = []
        # same wall budget the full Scheduler's _solve enforces; a
        # blown budget hands the WHOLE tick to the full path, which
        # owns the TIMEOUT_ERROR semantics (stamping partial timeouts
        # here would make the audit's fingerprint comparison racy)
        deadline = self.clock() + SOLVE_TIMEOUT_SECONDS
        # reservation budget for THIS round: live usage plus every
        # plan opened during the round (Scheduler's round_in_use)
        round_in_use: dict[str, int] = dict(self._rsv_in_use)

        # split exactly as Scheduler._solve routes: topology-spread
        # pods run the lowered batch; everything else is the fast
        # path. (Pod affinity/anti-affinity, volumes, host ports and
        # DRA made the whole tick ineligible already.)
        simple = [p for p in pods
                  if not p.spec.topology_spread_constraints]
        complex_ = [p for p in pods if p.spec.topology_spread_constraints]

        ok, reason = self._solve_simple(
            simple, pools, work, open_plans, results, round_in_use,
            deadline,
        )
        if not ok:
            return None, reason
        if complex_:
            topology = self._build_topology(pods, pools)
            # fast-path plans' pods enter the topology tracker before
            # the lowered solve, exactly as Scheduler._solve registers
            # its open plans after the fast path drains
            for plan in open_plans:
                for pod in plan.pods:
                    topology.register(
                        pod, plan_domains(plan),
                        source_taints=tuple(
                            plan.pool.spec.template.spec.taints),
                    )
            ok, reason = self._solve_topology(
                complex_, pools, topology, work, open_plans, results,
                round_in_use, deadline,
            )
            if not ok:
                return None, reason

        for plan in open_plans:
            finalize_plan(plan)
            results.new_node_plans.append(plan)
        return results, ""

    def _solve_simple(
        self, place, pools, work, open_plans, results, round_in_use,
        deadline,
    ) -> tuple[bool, str]:
        place = list(place)
        still_failed: list[Pod] = []
        for _ in range(1 + RETRY_ROUNDS):
            if not place:
                break
            if self.clock() > deadline:
                return False, "timeout"
            groups = group_pods(place)
            chosen = self._pruned_keys(groups, work)
            enc = encode(
                groups, pools,
                [work[k] for k in chosen],
                self._daemon_overhead,
                reserved_in_use=round_in_use,
                compat_cache=self.cache,
            )
            if (
                self._dual_stale
                and not self._micro_active
                and _env_on(ENV_MICRO_DUAL, "0")
            ):
                # refresh the micro path's dual certificate from a
                # FULL tick's encode (never a micro batch: its demand
                # axis is a sliver of the backlog) — opt-in, degrades
                # to None and the micro path runs arrival-ordered
                from karpenter_tpu.solver.incremental import (
                    build_dual_floor,
                )

                self._dual = build_dual_floor(enc)
                self._dual_stale = False
            sol = solve_encoded(enc)
            self._commit_existing(sol, chosen, work, results)
            open_plans.extend(sol.new_nodes)
            _debit_reservations(sol.new_nodes, round_in_use)
            evicted_keys = {p.key for p in sol.evicted}
            still_failed.extend(
                p for p in sol.unschedulable if p.key not in evicted_keys
            )
            # k-way-evicted pods are schedulable alone: retry them
            # against the committed state (mirrors Scheduler._solve)
            place = list(sol.evicted)
        still_failed.extend(place)  # retry bound hit

        for pod in still_failed:
            # Scheduler._solve's relaxation block, in-envelope (ISSUE
            # 16): one rung stripped, one solo required-only retry
            # against the committed round state. The incremental path
            # serves only the live provisioner tick, which always
            # honors preferences, so the mutation-then-retry sequence
            # is byte-identical to what the full path would run — the
            # audit restores pre-relax preferences before its shadow
            # solve so the oracle replays the same ladder steps.
            if self.clock() > deadline:
                return False, "timeout"
            retried = False
            relaxed = relax(pod)
            if relaxed:
                self._note_relax(pod, relaxed)
                groups = group_pods([pod], required_only=True)
                chosen = self._pruned_keys(groups, work)
                enc = encode(
                    groups, pools,
                    [work[k] for k in chosen],
                    self._daemon_overhead,
                    reserved_in_use=round_in_use,
                    compat_cache=self.cache,
                )
                retry = solve_encoded(enc)
                if not retry.unschedulable:
                    self._commit_existing(retry, chosen, work, results)
                    open_plans.extend(retry.new_nodes)
                    _debit_reservations(retry.new_nodes, round_in_use)
                    retried = True
                    if self._explaining():
                        from karpenter_tpu import explain

                        explain.note_pod(
                            pod.key, verdict="scheduled-after-relax",
                            relax_unlocked=relaxed,
                        )
            if not retried:
                results.errors[pod.key] = NO_CAPACITY_ERROR
        return True, ""

    def _explaining(self) -> bool:
        """The incremental tick serves only the LIVE provisioning
        solve, so unlike Scheduler._explaining there is no controller
        gate — an open explain record is the whole condition."""
        from karpenter_tpu import explain

        return explain.active() is not None

    def _note_relax(self, pod: Pod, step: str) -> None:
        if self._explaining():
            from karpenter_tpu import explain

            explain.note_relax(pod.key, step)

    def _commit_existing(self, sol, chosen, work, results) -> None:
        for a in sol.existing:
            key = chosen[a.existing_index]
            results.existing_assignments.setdefault(key, []).extend(
                a.pods
            )
            inp = work[key]
            usage = resutil.requests_for_pods(a.pods)
            work[key] = replace(
                inp,
                available=resutil.positive(
                    resutil.subtract(inp.available, usage)
                ),
                pod_count=inp.pod_count + len(a.pods),
            )
            # the committed row is provisional until the pods bind;
            # rebuild it from cluster truth next tick
            self._tracker.mark("Node", key)

    # -- topology phase (ISSUE 15) --------------------------------------------

    def _build_topology(self, pods, pools) -> Topology:
        """The Topology the full Scheduler would build, derived from
        the RETAINED domain columns instead of a per-round walk that
        re-parses every node's labels: pool/type domains (O(catalog),
        both paths pay it), per-node domain + taint provenance from
        `_NodeMeta` (maintained O(dirty)), and pod->domain mappings
        read through the retained labels. Only ticks that actually
        carry topology constraints build one."""
        from karpenter_tpu.scheduling.requirement import IN
        from karpenter_tpu.solver.encode import pool_template_requirements

        domains: dict[str, set] = {}
        domain_taints: dict[str, dict[str, list]] = {}

        def record(key: str, value: str, taints) -> None:
            domains.setdefault(key, set()).add(value)
            domain_taints.setdefault(key, {}).setdefault(value, []).append(
                tuple(taints)
            )

        for pool, types in pools:
            pool_reqs = pool_template_requirements(pool)
            pool_taints = tuple(pool.spec.template.spec.taints)
            for it in types:
                for key in (TOPOLOGY_ZONE_LABEL, CAPACITY_TYPE_LABEL):
                    req = it.requirements.get(key)
                    if req.operator() == IN:
                        gate = pool_reqs.get(key)
                        for v in req.values:
                            if gate.has(v):
                                record(key, v, pool_taints)
        pod_domains: dict[str, dict[str, str]] = {}
        for key in self._order:
            meta = self._meta.get(key)
            if meta is None:
                continue
            for lk, lv in meta.labels.items():
                record(lk, lv, meta.taints)
            if meta.name:
                record(HOSTNAME_LABEL, meta.name, meta.taints)
            mapping = dict(meta.labels)
            mapping[HOSTNAME_LABEL] = meta.name
            for pod_key in meta.node.pod_keys:
                pod_domains[pod_key] = mapping
        scheduled = [p for p in self.kube.pods() if p.spec.node_name]
        return Topology(
            domains=domains,
            cluster_pods=scheduled,
            pending_pods=list(pods),
            pod_domains=pod_domains,
            honor_schedule_anyway=True,
            domain_taints=domain_taints,
        )

    def _solve_topology(
        self, complex_, pools, topology, work, open_plans, results,
        round_in_use, deadline,
    ) -> tuple[bool, str]:
        """Scheduler._solve's lowered-topology block against the
        retained rows. Anything the lowering cannot express (per-pod
        fallback, deferred pods, plan joins with no fitting type)
        hands the whole tick to the full path — the per-pod topology
        loop and its relaxation ladder live only there."""
        if self.clock() > deadline:
            return False, "timeout"
        plan_refs = []
        plan_inputs = []
        for plan in open_plans:
            inp = plan_pseudo_input(plan, self._daemon_overhead)
            if inp is not None:
                plan_refs.append(plan)
                plan_inputs.append(inp)
        row_keys = [k for k in self._order if k in work]
        existing_rows = [work[k] for k in row_keys]
        existing_all = existing_rows + plan_inputs
        tb = topo_batch.prepare(complex_, topology, existing_all, {})
        results.errors.update(tb.errors)
        if tb.fallback:
            return False, "topology"
        if not tb.groups:
            return True, ""
        enc = encode(
            tb.groups, pools, existing_all, self._daemon_overhead,
            reserved_in_use=round_in_use,
            group_cap=tb.group_cap,
            conflict=tb.conflict,
            existing_quota=tb.existing_quota,
            compat_cache=self.cache,
        )
        sol = solve_encoded(enc)
        n_before = len(open_plans)
        open_plans.extend(sol.new_nodes)
        _debit_reservations(sol.new_nodes, round_in_use)
        E = len(existing_rows)
        deferred: list[Pod] = []
        for a in sol.existing:
            if a.existing_index >= E:
                # pods joined an open fast-path plan: narrow its
                # options to types that hold the enlarged pod set and
                # admit the new pods' requirements (the in-flight
                # NodeClaim re-filter, nodeclaim.go:373-447)
                plan = plan_refs[a.existing_index - E]
                used = resutil.merge(
                    self._daemon_overhead.get(plan.pool.metadata.name, {}),
                    resutil.requests_for_pods(plan.pods + a.pods),
                )
                joined_reqs = [Requirements.from_pod(p) for p in a.pods]
                fitting = [
                    it for it in plan.instance_types
                    if resutil.fits(used, it.allocatable)
                    and all(
                        it.requirements.intersects(r) is None
                        for r in joined_reqs
                    )
                ]
                if not fitting:
                    deferred.extend(a.pods)
                    continue
                plan.instance_types = fitting
                plan.offerings = [
                    o for o in plan.offerings
                    if any(it.offerings and o in it.offerings
                           for it in fitting)
                ] or plan.offerings
                plan.pods.extend(a.pods)
                domains = plan_domains(plan)
                for p in a.pods:
                    chosen = dict(domains)
                    chosen.update(tb.assignments.get(p.key, {}))
                    topology.register(p, chosen)
                continue
            key = row_keys[a.existing_index]
            results.existing_assignments.setdefault(key, []).extend(
                a.pods
            )
            inp = work[key]
            usage = resutil.requests_for_pods(a.pods)
            work[key] = replace(
                inp,
                available=resutil.positive(
                    resutil.subtract(inp.available, usage)
                ),
                pod_count=inp.pod_count + len(a.pods),
            )
            self._tracker.mark("Node", key)
            meta = self._meta.get(key)
            labels = dict(meta.labels) if meta is not None else {}
            labels[HOSTNAME_LABEL] = key
            for p in a.pods:
                chosen = dict(labels)
                chosen.update(tb.assignments.get(p.key, {}))
                topology.register(p, chosen)
        for plan in open_plans[n_before:]:
            domains = plan_domains(plan)
            for p in plan.pods:
                chosen = dict(domains)
                chosen.update(tb.assignments.get(p.key, {}))
                topology.register(p, chosen)
        deferred.extend(sol.unschedulable)
        if deferred:
            return False, "topology"
        return True, ""

    # -- micro-batch ordering (ISSUE 17) --------------------------------------

    def micro_order(self, pods: Sequence[Pod]) -> list[Pod]:
        """`_DualFloor` reduced-cost ordering for a debounced micro
        batch: cheapest certified placements first, so a truncated
        batch spends its window on the pods the duals price as easy
        wins. The operator applies this BEFORE handing the batch to
        tick(), so the shadow audit sees the identical pod order and
        the equality claim is untouched. Without a certificate
        (KARPENTER_MICRO_DUAL off, or no full solve yet) arrival
        order stands; ties keep arrival order (stable sort)."""
        pods = list(pods)
        dual = self._dual
        if dual is None or len(pods) < 2:
            return pods
        try:
            price: dict[str, float] = {}
            for g in group_pods(pods):
                sig = (
                    g.requirements.signature(),
                    g.tolerations,
                    tuple(sorted(g.resources.items())),
                )
                lam = dual.lam_by_sig.get(sig, 0.0)
                for p in g.pods:
                    price[p.key] = lam
            return sorted(pods, key=lambda p: price.get(p.key, 0.0))
        except Exception:
            return pods

    # -- priority overload gate (ISSUE 15) ------------------------------------

    def _priority_overloaded(self, pods, results) -> bool:
        """True exactly when the full path's priority admission loop
        would act: mixed priorities AND a capacity-class failure (the
        solve's own no-capacity error, or a plan NodePool limits
        would reject at create). Healthy mixed-priority ticks (the
        common case) pay one scan and serve incrementally."""
        from karpenter_tpu.provisioning.priority import mixed_priorities

        if not mixed_priorities(list(pods)):
            return False
        if any(
            err == NO_CAPACITY_ERROR for err in results.errors.values()
        ):
            return True
        if (
            self._plans_over_limits is not None
            and any(p.pool.spec.limits for p in results.new_node_plans)
        ):
            return bool(self._plans_over_limits(results.new_node_plans))
        return False

    def _pruned_keys(self, groups, work: dict) -> list[str]:
        """Residual prune (exact, from IncrementalPipeline): a node
        below the componentwise MINIMUM request over keys EVERY group
        demands can hold none of them, and nodes only fill during a
        solve — dropping it preserves first-feasible order while
        shrinking the bound axis to nodes with real headroom. Survivors
        keep `self._order` — the Scheduler's existing-node axis order
        (live nodes in cluster order, in-flight fewest-pods-first) —
        so placements stay byte-identical with the full path's."""
        min_req: dict[str, float] = {}
        req_counts: dict[str, int] = {}
        for g in groups:
            for k, v in g.resources.items():
                if v <= 0:
                    continue
                req_counts[k] = req_counts.get(k, 0) + 1
                have = min_req.get(k)
                min_req[k] = v if have is None else min(have, v)
        min_req = {
            k: v for k, v in min_req.items()
            if req_counts[k] == len(groups)
        }
        out = []
        for key in self._order:
            inp = work.get(key)
            if inp is None:
                continue
            # float32-scale margin: the prune runs in float64 host
            # arithmetic while the kernel judges fits in float32 — a
            # boundary-exact fill (4x0.8 on a 4.0 node leaves
            # 0.7999999999999994) reads as "full" here but as exactly
            # 0.8f on device. Prune only nodes the kernel could never
            # accept; a kept-but-infeasible row is a no-op column.
            if any(
                inp.available.get(k, 0.0) < v * (1.0 - 1e-6)
                for k, v in min_req.items()
            ):
                continue
            out.append(key)
        return out

    # -- oracle audit ---------------------------------------------------------

    def _audit_trigger(self, pods) -> Optional[str]:
        if not pods:
            return None   # empty decisions compare trivially equal
        if self._quarantined:
            return "probation"
        if self._force_audit is not None:
            trigger = self._force_audit
            self._force_audit = None
            return trigger
        if self.audit_every > 0 and self._since_audit >= self.audit_every:
            return "cadence"
        return None

    def _audit(
        self, pods, pools_with_types, results: SchedulerResults,
        trigger: str, prefs: Optional[dict] = None,
    ) -> tuple[bool, SchedulerResults]:
        """Shadow full solve + decision fingerprint diff. On
        divergence: quarantine the retained state, record the episode
        for replay, and hand back the shadow decision."""
        from karpenter_tpu.provisioning import priority as padm

        self._since_audit = 0
        # undo the live solve's relaxation mutations: the shadow must
        # climb the same ladder from the same pre-tick base (it then
        # deterministically re-applies the identical rungs, so the
        # pods end the audit in the same state the live solve left)
        if prefs:
            for pod in pods:
                saved = prefs.get(pod.key)
                if saved is not None:
                    pod.spec.affinity = saved[0]
                    pod.spec.topology_spread_constraints = list(saved[1])

        def shadow_solve(keep):
            return self._make_scheduler(
                pools_with_types, "incremental_audit"
            ).solve(list(keep))

        shadow = shadow_solve(pods)
        # the full path wraps its solve in the admission loop; the
        # shadow must too, or an in-envelope shed tick would diff
        # against an unshed oracle. note=False: the live serve already
        # counted the shed metrics/explanations.
        shadow = padm.enforce_admission(
            list(pods), pools_with_types, shadow, shadow_solve,
            plans_over_limits=self._plans_over_limits,
            daemon_overhead=lambda: self._daemon_overhead,
            note=False,
        )
        want = decision_fingerprint(shadow)
        got = decision_fingerprint(results)
        ok = want == got
        self._last_audit = {
            "verdict": "ok" if ok else "divergence",
            "trigger": trigger,
            "tick": self._ticks,
        }
        INCREMENTAL_AUDITS.inc(
            {"verdict": self._last_audit["verdict"], "trigger": trigger}
        )
        if ok:
            return True, shadow
        INCREMENTAL_DIVERGENCE.inc()
        inj = faults.get()
        record = {
            "tick": self._ticks,
            "trigger": trigger,
            "incremental": got,
            "full": want,
            # the fired-fault log up to the divergence: replaying the
            # same spec + seed + workload reproduces this episode
            # byte-identically (FaultInjector.snapshot_log)
            "fault_log": inj.snapshot_log() if inj is not None else [],
        }
        self.divergences.append(record)
        del self.divergences[:-MAX_DIVERGENCE_RECORDS]
        log.error(
            "incremental oracle audit diverged (trigger=%s); "
            "quarantining retained state and serving the full-solve "
            "decision", trigger,
        )
        from karpenter_tpu.solver import resilience

        resilience.note_incremental_poison()
        self._quarantined = True
        self._invalidate(trigger="quarantine")
        # probation (the _quarantined gate) owns the follow-up audits;
        # leaving the force flag set would fire one extra shadow solve
        # AFTER probation clears, with a trigger label outside the
        # metric's documented set
        self._force_audit = None
        self.cache.invalidate()
        return False, shadow

    # -- observability --------------------------------------------------------

    def state_fingerprint(self) -> str:
        """Stable hash of the retained inputs — readyz surfaces it so
        two replicas (or a pre/post-restart pair) can be compared."""
        import hashlib

        rows = sorted(
            (
                key,
                inp.pool_name,
                inp.pod_count,
                tuple(sorted(
                    (k, round(v, 6)) for k, v in inp.available.items()
                )),
            )
            for key, inp in self._inputs.items()
        )
        return hashlib.sha256(repr(rows).encode()).hexdigest()

    def status(self) -> dict:
        return {
            "enabled": incremental_enabled(),
            "quarantined": self._quarantined,
            "retained_nodes": len(self._inputs),
            "fingerprint": self.state_fingerprint(),
            "fingerprint_age_ticks": self._age,
            "last_audit": dict(self._last_audit),
            "divergences": len(self.divergences),
            "ticks": dict(self._counts),
            # per-reason full-path fallback rollup (the
            # karpenter_incremental_tick_total{path="full_backstop",
            # reason} series as a readyz digest)
            "fallbacks": dict(self._fallbacks),
            # event-driven micro-solve rollup (ISSUE 17): served count
            # rides ticks["micro"]; defers are per-reason, mirroring
            # karpenter_incremental_tick_total{path="micro_defer"}
            "micro": {
                "served": self._counts["micro"],
                "deferred": dict(self._micro_defers),
                "dual_certificate": self._dual is not None,
            },
        }


def _node_reservation_id(sn, labels: dict[str, str]) -> str:
    """The reservation a node consumes — its label once launched, or
    the pinned claim requirement before launch (exactly the two reads
    Scheduler.__init__'s ledger loop does)."""
    rid = labels.get(RESERVATION_ID_LABEL, "")
    if not rid and sn.node_claim is not None:
        for spec in sn.node_claim.spec.requirements:
            if spec.key == RESERVATION_ID_LABEL and spec.values:
                rid = spec.values[0]
                break
    return rid


def _debit_reservations(plans, round_in_use: dict[str, int]) -> None:
    """Scheduler._debit_reservations, for the incremental round's
    ledger: each plan opened against a reservation consumes one
    instance for the remainder of the tick."""
    for plan in plans:
        rid = getattr(plan, "reservation_id", "")
        if rid:
            round_in_use[rid] = round_in_use.get(rid, 0) + 1
