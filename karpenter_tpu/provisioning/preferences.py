"""Preference relaxation ladder.

Counterpart of provisioning/scheduling/preferences.go:38-141. When a
pod cannot schedule, its soft constraints are peeled off one rung at a
time (mutating the in-memory pod only):

  1. drop preferred node-affinity terms
  2. drop one required node-affinity term (they are ORed; the scheduler
     only considers the first, so removing it surfaces the next) — the
     FINAL term is never relaxed (preferences.go:70-76)
  3. drop ScheduleAnyway topology-spread constraints
  4. drop preferred pod affinity, then preferred anti-affinity

The reference's terminal rung (tolerate PreferNoSchedule taints,
preferences.go:129-141) has no analogue here because this build's
`tolerates` never blocks on PreferNoSchedule in the first place
(scheduling/taints.py) — same outcome, no relaxation round needed.

Returns the NAME of the rung relaxed (truthy — callers retry), or
None when the ladder is exhausted. The rung name is what the
explainability plane (karpenter_tpu/explain) records per retry, so an
operator can see exactly which preference steps a pod burned before
it scheduled (or didn't).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from karpenter_tpu.kube.objects import Affinity, NodeAffinity, Pod, PodAffinity

_RELAXED_MARK = "karpenter.sh/relaxed"

# ladder rung names, in relaxation order — the structured step codes
# the explain plane records
RELAX_PREFERRED_NODE_AFFINITY = "preferred-node-affinity"
RELAX_REQUIRED_NODE_AFFINITY_TERM = "required-node-affinity-term"
RELAX_SCHEDULE_ANYWAY_SPREAD = "schedule-anyway-spread"
RELAX_PREFERRED_POD_AFFINITY = "preferred-pod-affinity"
RELAX_PREFERRED_POD_ANTI_AFFINITY = "preferred-pod-anti-affinity"


def relax(pod: Pod) -> Optional[str]:
    aff = pod.spec.affinity
    # 1. preferred node affinity
    if aff and aff.node_affinity and aff.node_affinity.preferred:
        pod.spec.affinity = replace(
            aff, node_affinity=replace(aff.node_affinity, preferred=())
        )
        return RELAX_PREFERRED_NODE_AFFINITY
    # 2. required node affinity terms (drop the first OR-term)
    if aff and aff.node_affinity and len(aff.node_affinity.required) > 1:
        pod.spec.affinity = replace(
            aff,
            node_affinity=replace(aff.node_affinity, required=aff.node_affinity.required[1:]),
        )
        return RELAX_REQUIRED_NODE_AFFINITY_TERM
    # 3. ScheduleAnyway spread constraints
    soft_tsc = [
        t for t in pod.spec.topology_spread_constraints
        if t.when_unsatisfiable == "ScheduleAnyway"
    ]
    if soft_tsc:
        pod.spec.topology_spread_constraints = [
            t for t in pod.spec.topology_spread_constraints
            if t.when_unsatisfiable != "ScheduleAnyway"
        ]
        return RELAX_SCHEDULE_ANYWAY_SPREAD
    # 4. preferred pod affinity / anti-affinity
    if aff and aff.pod_affinity and aff.pod_affinity.preferred:
        pod.spec.affinity = replace(
            aff, pod_affinity=replace(aff.pod_affinity, preferred=())
        )
        return RELAX_PREFERRED_POD_AFFINITY
    if aff and aff.pod_anti_affinity and aff.pod_anti_affinity.preferred:
        pod.spec.affinity = replace(
            aff, pod_anti_affinity=replace(aff.pod_anti_affinity, preferred=())
        )
        return RELAX_PREFERRED_POD_ANTI_AFFINITY
    return None


def relaxable(pod: Pod) -> bool:
    """True when relax() would strip something — WITHOUT mutating the
    pod. Retained-state fast paths (the incremental live tick, the
    batched probe solver) use this to decide whether an unscheduled
    pod must route to the full Scheduler's relaxation ladder; calling
    relax() to find out would mutate the pod the full path is about to
    re-solve."""
    aff = pod.spec.affinity
    if aff and aff.node_affinity:
        if aff.node_affinity.preferred:
            return True
        if len(aff.node_affinity.required) > 1:
            return True
    if any(
        t.when_unsatisfiable == "ScheduleAnyway"
        for t in pod.spec.topology_spread_constraints
    ):
        return True
    if aff:
        if aff.pod_affinity and aff.pod_affinity.preferred:
            return True
        if aff.pod_anti_affinity and aff.pod_anti_affinity.preferred:
            return True
    return False
