"""Priority-ordered admission: overload degrades by policy, not luck.

Under overload (demand > NodePool limits or catalog capacity) the
solver's unscheduled set used to be an accident of encode order — FFD
fills whatever fits, so WHICH pods starve depended on shapes, not
importance. "Priority Matters" (PAPERS.md) frames the right contract:
with PriorityClass semantics resolved, the unscheduled set must be
exactly the lowest-priority tail of the admission order, ties broken
by the solver's own deterministic pod order (group_pods' priority-major
FFD sort — the pod order the encode already commits to).

The contract is enforced by `Provisioner._enforce_priority_admission`
(a host-side wrapper around the unchanged solve): when a solve leaves
CAPACITY-class failures among pods that are placeable in principle,
the admission cutoff moves to the highest-priority such failure and
everything at or past the cutoff is shed with `PRIORITY_SHED_ERROR`
while the admitted prefix re-solves clean. Pods that could never
schedule (no compatible launchable config, or too big for any machine)
are OUTSIDE the contract: they keep their own errors and never drag
the tail down with them.

Engages only when the round's pods span MORE THAN ONE priority —
uniform-priority rounds (every pod 0, the default) are byte-identical
to the pre-priority behavior.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.utils import resources as resutil

log = logging.getLogger("karpenter.priority")

# capacity-class failure strings: truncation the admission contract
# covers. Everything else (DRA, timeouts, topology infeasibility,
# minValues policy rejects) is a permanent/transient error in its own
# right — shedding the tail below such a pod would turn one wedged pod
# into a cluster-wide outage. NO_CAPACITY_ERROR is canonical in
# scheduler.py (its producer); LIMITS_ERROR is canonical HERE and
# produced by Provisioner.create_node_claims — both matched by exact
# string equality, so producers and consumers import, never respell.
from karpenter_tpu.provisioning.scheduler import NO_CAPACITY_ERROR  # noqa: E402,F401

LIMITS_ERROR = "nodepool limits exceeded"

PRIORITY_SHED_ERROR = (
    "insufficient capacity; shed by priority admission (lower-priority "
    "tail, will retry next round)"
)

# capacity-class errors preemption may act on for a pending pod
CAPACITY_ERRORS = (NO_CAPACITY_ERROR, LIMITS_ERROR, PRIORITY_SHED_ERROR)


def mixed_priorities(pods: Sequence[Pod]) -> bool:
    """True when the pod set spans more than one resolved priority —
    the only case in which there IS a priority order to honor."""
    seen: Optional[int] = None
    for pod in pods:
        p = pod.spec.priority
        if seen is None:
            seen = p
        elif p != seen:
            return True
    return False


def admission_order(pods: Sequence[Pod]) -> list[Pod]:
    """The admission order the contract is defined over: groups sorted
    priority-major by group_pods (ties broken by the existing
    deterministic FFD order), flattened group-major with pods in
    arrival order within a group — exactly the pod order the encode's
    decode tables commit to."""
    from karpenter_tpu.solver.encode import group_pods

    return [p for g in group_pods(pods) for p in g.pods]


def enforce_admission(
    pods: Sequence[Pod],
    pools,
    results,
    solve_fn,
    plans_over_limits=None,
    daemon_overhead=None,
    note: bool = True,
):
    """The overload degradation contract, shared by every solve path:
    when capacity (catalog or pool limits) truncates the solve, the
    unscheduled set must be exactly the lowest-priority tail of the
    admission order. Iterates cutoff-and-re-solve until the admitted
    prefix is clean; the cutoff strictly decreases, so the loop
    terminates. No-op on uniform-priority rounds.

    - `solve_fn(keep)` re-solves the admitted prefix (the full path
      passes a fresh Scheduler solve; the incremental tick its own
      retained-state core — an escape there aborts the loop by
      raising through this frame).
    - `plans_over_limits(plans)` simulates NodePool limit rejection
      (Provisioner._plans_over_limits); None skips limit folding.
    - `daemon_overhead()` lazily supplies the per-pool overhead the
      placeability check charges (built only on the first failure).
    - `note=False` suppresses metrics/explain/tracing — the oracle
      audit's shadow run must not double-count the live decision."""
    pods = list(pods)
    if not mixed_priorities(pods):
        return results
    # order/placeable are built lazily on the FIRST capacity failure:
    # the healthy mixed-priority round pays only the mixed scan above
    # and the caller's limit simulation
    order: Optional[list] = None
    pos: dict = {}
    placeable: set = set()
    cut = 0
    for _ in range(16):
        raw_failed = [
            key for key, error in results.errors.items()
            if error == NO_CAPACITY_ERROR
        ]
        if plans_over_limits is not None:
            for plan in plans_over_limits(results.new_node_plans):
                raw_failed.extend(p.key for p in plan.pods)
        if order is None:
            if not raw_failed:
                return results
            order = admission_order(pods)
            pos = {p.key: i for i, p in enumerate(order)}
            cut = len(order)
            placeable = placeable_keys(
                pods, pools,
                daemon_overhead() if daemon_overhead is not None else None,
            )
        failed = [
            k for k in raw_failed
            if k in placeable and pos.get(k, cut) < cut
        ]
        if not failed:
            break
        cut = min(pos[k] for k in failed)
        # re-solve the admitted prefix; unplaceable pods rejoin so
        # their permanent errors keep reporting
        keep = order[:cut] + [
            p for p in order[cut:] if p.key not in placeable
        ]
        results = solve_fn(keep)
    else:
        if note:
            log.warning(
                "priority admission did not converge in 16 rounds; "
                "serving the last solve's results"
            )
    if order is None or cut >= len(order):
        return results
    shed = [p for p in order[cut:] if p.key in placeable]
    for pod in shed:
        results.errors[pod.key] = PRIORITY_SHED_ERROR
    if shed and note:
        from karpenter_tpu import explain, tracing
        from karpenter_tpu.metrics.store import PRIORITY_SHED

        tracing.annotate(shed=len(shed),
                         cutoff_priority=order[cut].spec.priority)
        if explain.active() is not None:
            # the admission cutoff is the explanation: the pod was
            # placeable, but everything at or past this priority was
            # shed so the higher-priority prefix stays clean
            cutoff = int(order[cut].spec.priority)
            for pod in shed:
                explain.note_pod(
                    pod.key, verdict="shed", code="priority_shed",
                    cutoff_priority=cutoff,
                    pod_priority=int(pod.spec.priority),
                )
        PRIORITY_SHED.inc(value=float(len(shed)))
        log.warning(
            "priority admission: demand exceeds capacity; shed %d "
            "pod(s) at or below priority %d (cutoff honors the "
            "deterministic admission order)",
            len(shed), order[cut].spec.priority,
        )
    return results


def placeable_keys(
    pods: Sequence[Pod],
    pools_with_types,
    daemon_overhead: Optional[dict[str, dict[str, float]]] = None,
) -> set[str]:
    """Keys of pods that are placeable in principle: compatible with at
    least one launchable config (requirements AND taints) whose
    allocatable holds the pod's requests plus the pool's daemon
    overhead. Only these participate in the tail contract — a pod no
    catalog machine could ever hold is not 'capacity-truncated', it is
    unschedulable, and must not shed the tail below it."""
    from karpenter_tpu.solver.encode import (
        _full_compat,
        group_pods,
        launch_configs,
    )

    groups = group_pods(pods)
    configs = launch_configs(pools_with_types)
    if not configs or not groups:
        return set()
    compat = _full_compat(groups, configs)
    overhead = daemon_overhead or {}
    out: set[str] = set()
    for gi, group in enumerate(groups):
        for ci in np.flatnonzero(compat[gi]):
            cfg = configs[ci]
            need = resutil.merge(
                group.resources,
                overhead.get(cfg.pool.metadata.name, {}),
            )
            if resutil.fits(need, cfg.instance_type.allocatable):
                out.update(p.key for p in group.pods)
                break
    return out
