"""Priority-ordered admission: overload degrades by policy, not luck.

Under overload (demand > NodePool limits or catalog capacity) the
solver's unscheduled set used to be an accident of encode order — FFD
fills whatever fits, so WHICH pods starve depended on shapes, not
importance. "Priority Matters" (PAPERS.md) frames the right contract:
with PriorityClass semantics resolved, the unscheduled set must be
exactly the lowest-priority tail of the admission order, ties broken
by the solver's own deterministic pod order (group_pods' priority-major
FFD sort — the pod order the encode already commits to).

The contract is enforced by `Provisioner._enforce_priority_admission`
(a host-side wrapper around the unchanged solve): when a solve leaves
CAPACITY-class failures among pods that are placeable in principle,
the admission cutoff moves to the highest-priority such failure and
everything at or past the cutoff is shed with `PRIORITY_SHED_ERROR`
while the admitted prefix re-solves clean. Pods that could never
schedule (no compatible launchable config, or too big for any machine)
are OUTSIDE the contract: they keep their own errors and never drag
the tail down with them.

Engages only when the round's pods span MORE THAN ONE priority —
uniform-priority rounds (every pod 0, the default) are byte-identical
to the pre-priority behavior.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.utils import resources as resutil

log = logging.getLogger("karpenter.priority")

# capacity-class failure strings: truncation the admission contract
# covers. Everything else (DRA, timeouts, topology infeasibility,
# minValues policy rejects) is a permanent/transient error in its own
# right — shedding the tail below such a pod would turn one wedged pod
# into a cluster-wide outage. NO_CAPACITY_ERROR is canonical in
# scheduler.py (its producer); LIMITS_ERROR is canonical HERE and
# produced by Provisioner.create_node_claims — both matched by exact
# string equality, so producers and consumers import, never respell.
from karpenter_tpu.provisioning.scheduler import NO_CAPACITY_ERROR  # noqa: E402,F401

LIMITS_ERROR = "nodepool limits exceeded"

PRIORITY_SHED_ERROR = (
    "insufficient capacity; shed by priority admission (lower-priority "
    "tail, will retry next round)"
)

# capacity-class errors preemption may act on for a pending pod
CAPACITY_ERRORS = (NO_CAPACITY_ERROR, LIMITS_ERROR, PRIORITY_SHED_ERROR)


def mixed_priorities(pods: Sequence[Pod]) -> bool:
    """True when the pod set spans more than one resolved priority —
    the only case in which there IS a priority order to honor."""
    seen: Optional[int] = None
    for pod in pods:
        p = pod.spec.priority
        if seen is None:
            seen = p
        elif p != seen:
            return True
    return False


def admission_order(pods: Sequence[Pod]) -> list[Pod]:
    """The admission order the contract is defined over: groups sorted
    priority-major by group_pods (ties broken by the existing
    deterministic FFD order), flattened group-major with pods in
    arrival order within a group — exactly the pod order the encode's
    decode tables commit to."""
    from karpenter_tpu.solver.encode import group_pods

    return [p for g in group_pods(pods) for p in g.pods]


def placeable_keys(
    pods: Sequence[Pod],
    pools_with_types,
    daemon_overhead: Optional[dict[str, dict[str, float]]] = None,
) -> set[str]:
    """Keys of pods that are placeable in principle: compatible with at
    least one launchable config (requirements AND taints) whose
    allocatable holds the pod's requests plus the pool's daemon
    overhead. Only these participate in the tail contract — a pod no
    catalog machine could ever hold is not 'capacity-truncated', it is
    unschedulable, and must not shed the tail below it."""
    from karpenter_tpu.solver.encode import (
        _full_compat,
        group_pods,
        launch_configs,
    )

    groups = group_pods(pods)
    configs = launch_configs(pools_with_types)
    if not configs or not groups:
        return set()
    compat = _full_compat(groups, configs)
    overhead = daemon_overhead or {}
    out: set[str] = set()
    for gi, group in enumerate(groups):
        for ci in np.flatnonzero(compat[gi]):
            cfg = configs[ci]
            need = resutil.merge(
                group.resources,
                overhead.get(cfg.pool.metadata.name, {}),
            )
            if resutil.fits(need, cfg.instance_type.allocatable):
                out.update(p.key for p in group.pods)
                break
    return out
