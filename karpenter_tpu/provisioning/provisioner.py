"""Provisioner: pending pods -> NodeClaims.

Counterpart of pkg/controllers/provisioning/provisioner.go: batch
pending pods (batcher), gate on state sync, snapshot the cluster,
build a Scheduler, solve, then create NodeClaims (parallel in the
reference; sequential here — creation is in-memory) while enforcing
NodePool limits, and nominate target nodes for pods placed on
existing capacity.
"""

from __future__ import annotations

import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from karpenter_tpu.apis.v1.labels import (
    DO_NOT_DISRUPT_ANNOTATION,
    NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION,
    NODEPOOL_LABEL,
    TERMINATION_FINALIZER,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    NodeClaim,
    NodeClaimSpec,
    RequirementSpec,
)
from karpenter_tpu.apis.v1.nodepool import NodePool, nodepool_owner_ref, order_by_weight
from karpenter_tpu.cloudprovider.types import CloudProvider, min_values_coverage
from karpenter_tpu.provisioning import volume_topology
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.metrics.store import NODECLAIMS_CREATED
from karpenter_tpu.kube.objects import ObjectMeta, Pod
from karpenter_tpu.provisioning.scheduler import Scheduler, SchedulerResults
from karpenter_tpu.apis.v1.labels import is_restricted_label
from karpenter_tpu.metrics.store import SCHEDULER_IGNORED_PODS
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.solver.solver import NodePlan
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.utils.resources import ResourceList

log = logging.getLogger("karpenter.provisioner")

_claim_counter = itertools.count(1)

# Runtime default for NodeClaim terminationGracePeriod when the pool
# template leaves it unset — providers set this once at startup
# (nodeclaimtemplate.go:34-37,119). Seconds; None = no default.
DEFAULT_TERMINATION_GRACE_PERIOD: Optional[float] = None

# demand_surge burst pods (solver/faults.py `provision_intake` site):
# the label chaos suites use to find and retire a storm's pods, and the
# priorities the seeded low/high mix resolves to
SURGE_LABEL = "karpenter.sh/demand-surge"
SURGE_HIGH_PRIORITY = 100
SURGE_LOW_PRIORITY = -100


def _specs_from_requirement(req: Requirement, relaxed: bool) -> list[RequirementSpec]:
    """Serialize one algebraic Requirement back into claim spec
    entries via Requirement.spec_entries(). A BestEffort-relaxed plan
    drops a minValues floor ONLY where the surviving value set no
    longer satisfies it (the min-values-relaxed annotation records
    why); only an In value set can fall below its floor (complement
    sets allow unboundedly many values)."""
    specs: list[RequirementSpec] = []
    for op, values, min_values in req.spec_entries():
        if (
            relaxed and min_values is not None and op == IN
            and len(values) < min_values
        ):
            min_values = None
        specs.append(
            RequirementSpec(key=req.key, operator=op, values=values,
                            min_values=min_values)
        )
    return specs


@dataclass
class Batcher:
    """Debounce window for pod arrival (batcher.go:33-92): wait for
    `idle_seconds` of quiet or `max_seconds` total."""

    idle_seconds: float = 1.0
    max_seconds: float = 10.0
    _last_trigger: float = 0.0
    _window_start: float = 0.0
    _pending: bool = False

    def trigger(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        if not self._pending:
            self._window_start = now
            self._pending = True
        self._last_trigger = now

    def ready(self, now: Optional[float] = None) -> bool:
        if not self._pending:
            return False
        now = time.time() if now is None else now
        return (
            now - self._last_trigger >= self.idle_seconds
            or now - self._window_start >= self.max_seconds
        )

    def reset(self) -> None:
        self._pending = False


class Provisioner:
    def __init__(
        self,
        kube: KubeClient,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        options=None,
        clock=None,
        recorder=None,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.options = options
        self.clock = clock if clock is not None else time.monotonic
        self.recorder = recorder
        self.batcher = Batcher()
        # Encoder compat-row/config cache shared across rounds
        # (solver/incremental.EncodedCache): steady-state rounds with
        # repeating pod shapes skip the G x C requirement rebuild. It
        # self-invalidates on catalog fingerprint changes; the
        # NodePool dirty tracker busts it eagerly too (belt and
        # braces for in-place template mutations the fingerprint
        # would only catch through pool.hash()).
        from karpenter_tpu.kube.dirty import DirtyTracker
        from karpenter_tpu.solver.incremental import EncodedCache

        self.encode_cache = EncodedCache()
        self._catalog_dirty = DirtyTracker(kube).watch("NodePool")
        # Incremental live tick (the default reconcile path): retained
        # per-node solver inputs synced O(dirty) from the watch stream,
        # with a shadow full-solve oracle audit and quarantine-on-
        # divergence. Ineligible ticks (topology, volumes, minValues,
        # spot budgets, reservations, churn blow-outs) fall through to
        # the unchanged full Scheduler below. KARPENTER_INCREMENTAL=0
        # disables it entirely.
        from karpenter_tpu.provisioning.incremental_tick import (
            IncrementalTickScheduler,
        )

        self.incremental = IncrementalTickScheduler(
            kube, cluster, self.encode_cache,
            make_scheduler=self._make_scheduler,
            options=options, clock=self.clock,
            # the admission loop's limit simulation: a mixed-priority
            # incremental tick whose plans would blow a pool limit
            # must fall back to the full path (where the shed/cutoff
            # machinery wraps the results)
            plans_over_limits=self._plans_over_limits,
        )

    # -- pod intake (provisioner.go:172-195, utils/node) ----------------------

    def get_pending_pods(self) -> list[Pod]:
        out = []
        ignored = 0
        for pod in self.kube.pods():
            if pod.is_terminal() or pod.is_terminating():
                continue
            if pod.spec.node_name:
                continue
            if pod.owner_kind() == "DaemonSet":
                continue
            if pod.spec.scheduler_name and pod.spec.scheduler_name not in (
                "default-scheduler",
                "karpenter",
            ):
                ignored += 1
                continue
            if pod.spec.volumes:
                # kube-scheduler-rejected PVC states filter at intake
                # (provisioner.go:509 ValidatePersistentVolumeClaims)
                reason = volume_topology.validate_pvcs(pod, self.kube)
                if reason is not None:
                    log.debug(
                        "pod %s not provisionable: %s", pod.key, reason
                    )
                    ignored += 1
                    continue
            out.append(pod)
        SCHEDULER_IGNORED_PODS.set(float(ignored))
        return out

    def reschedulable_pods_from_deleting_nodes(self) -> list[Pod]:
        """Pods on draining nodes are included in the solve so
        replacement capacity exists before eviction
        (provisioner.go:324-333)."""
        out = []
        for node in self.cluster.nodes():
            if not node.deleting():
                continue
            for pod_key in node.pod_keys:
                pod = self.kube.get_pod(*pod_key.split("/", 1))
                if pod is None or pod.is_terminal() or pod.is_terminating():
                    continue
                if pod.owner_kind() == "DaemonSet":
                    continue
                if pod.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION) == "true":
                    continue
                out.append(pod)
        return out

    # -- schedule (provisioner.go:303-400) ------------------------------------

    def ready_pools_with_types(self) -> list[tuple[NodePool, list]]:
        pools = []
        for pool in order_by_weight(self.kube.node_pools()):
            if pool.metadata.deletion_timestamp is not None:
                continue
            if pool.is_static():
                continue
            if pool.status_conditions.is_false("NodeClassReady"):
                continue
            try:
                types = self.cloud_provider.get_instance_types(pool)
            except Exception as err:  # provider hiccups skip the pool
                log.warning("skipping pool %s: %s", pool.metadata.name, err)
                continue
            if types:
                pools.append((pool, types))
        return pools

    def _make_scheduler(self, pools, metrics_controller: str = "provisioner"
                        ) -> Scheduler:
        """The full-path Scheduler construction — one seam shared by
        the live reconcile fallback and the incremental tick's shadow
        oracle audit, so the audit compares against exactly what the
        fallback would have decided."""
        return Scheduler(
            pools_with_types=pools,
            state_nodes=self.cluster.deep_copy_nodes(),
            daemonsets=self.cluster.daemonsets(),
            cluster_pods=self.kube.pods(),
            kube=self.kube,
            allow_reserved=(
                self.options.feature_gates.reserved_capacity
                if self.options is not None else True
            ),
            min_values_policy=(
                self.options.min_values_policy
                if self.options is not None else "Strict"
            ),
            ignore_dra_requests=(
                self.options.ignore_dra_requests
                if self.options is not None else True
            ),
            clock=self.clock,
            compat_cache=self.encode_cache,
            metrics_controller=metrics_controller,
        )

    def schedule(self, extra_pods: Sequence[Pod] = ()) -> SchedulerResults:
        from karpenter_tpu import tracing

        with tracing.span("intake") as sp:
            pods = list(extra_pods) or (
                self.get_pending_pods()
                + self.reschedulable_pods_from_deleting_nodes()
            )
            if not extra_pods:
                # live intake only: a scripted solve must never absorb a
                # chaos burst meant for the reconcile loop
                pods = self._consume_demand_surge(pods)
            # admission-plugin analogue: resolve PriorityClass values
            # onto spec.priority before anything groups the pods
            from karpenter_tpu.scheduling.priority import (
                resolve_pod_priorities,
            )

            resolve_pod_priorities(pods, self.kube)
            sp.annotate(pods=len(pods))
        if self._catalog_dirty.drain("NodePool"):
            self.encode_cache.invalidate()
        pools = self.ready_pools_with_types()
        # the incremental live tick is the default path; it returns
        # None for ticks outside its envelope (explicit extra_pods are
        # a caller-scripted solve, not the live reconcile). A
        # mixed-priority tick that hits a capacity failure — the only
        # case the admission loop below would act on — falls back to
        # the full path inside the tick (reason "priority"), so an
        # incremental serve never needs the shed/cutoff machinery.
        # The route span carries the decision + reason — the
        # incremental tick annotates it from its gates.
        if not extra_pods:
            with tracing.span("route"):
                results = self.incremental.tick(pods, pools)
                if results is not None:
                    self.cluster.mark_pod_scheduling_decisions(pods)
                    return results
        results = self._make_scheduler(pools).solve(pods)
        with tracing.span("admission"):
            results = self._enforce_priority_admission(pods, pools, results)
        self.cluster.mark_pod_scheduling_decisions(pods)
        return results

    # -- priority admission (ISSUE 8) -----------------------------------------

    def _consume_demand_surge(self, pods: list[Pod]) -> list[Pod]:
        """The `provision_intake` fault site: a firing `demand_surge`
        rule is consumed here as a deterministic burst of pending pods
        — created in the store (a workload controller scaled out
        mid-tick) and joined to this round's solve."""
        from karpenter_tpu.solver import faults as _faults

        try:
            _faults.fire("provision_intake")
        except _faults.DemandSurgeError as err:
            burst = self._synthesize_surge(err)
            log.warning(
                "fault injected: %s (%d surge pods join this round)",
                err, len(burst),
            )
            pods = pods + burst
        except _faults.FaultError as err:
            # a mis-kinded chaos spec aimed at this site must not take
            # the reconcile loop down — consume and warn, exactly as
            # the providers do at cloud_interrupt
            log.warning(
                "ignoring non-surge fault at provision_intake: %s", err
            )
        return pods

    def _synthesize_surge(self, err) -> list[Pod]:
        """Deterministic burst pods for one DemandSurgeError: names
        `surge-<seq>-<i>`, priority low (-100) or high (100) decided by
        the seeded hash — a pure function of (seed, seq), so the same
        schedule injects byte-identical demand across runs. Bare pods
        (no owner): an evicted or shed surge pod never rebirths, so the
        storm is occurrence-bounded by construction."""
        from karpenter_tpu.kube.objects import Container, PodSpec
        from karpenter_tpu.solver.faults import _hash01

        out: list[Pod] = []
        for i in range(err.count):
            name = f"surge-{err.seq}-{i}"
            existing = self.kube.get_pod("default", name)
            if existing is not None:
                out.append(existing)
                continue
            high = _hash01(err.seed, f"surge-{err.seq}", i + 1) < 0.5
            pod = Pod(
                metadata=ObjectMeta(
                    name=name,
                    labels={SURGE_LABEL: str(err.seq)},
                ),
                spec=PodSpec(
                    containers=[Container(
                        requests={"cpu": 0.5, "memory": float(2**30)}
                    )],
                    priority=SURGE_HIGH_PRIORITY if high
                    else SURGE_LOW_PRIORITY,
                ),
            )
            self.kube.create(pod)
            out.append(pod)
        return out

    def _plans_over_limits(self, plans: Sequence[NodePlan]) -> list[NodePlan]:
        """Plans `create_node_claims` would reject for NodePool limits,
        simulated WITHOUT mutation against the same usage snapshot and
        in the same order the real create walks — so the admission loop
        can fold limit truncation into the priority cutoff before any
        claim exists."""
        usage_by_pool = self.cluster.nodepool_resources()
        over: list[NodePlan] = []
        for plan in plans:
            pool = plan.pool
            if not pool.spec.limits:
                continue
            usage = usage_by_pool.get(pool.metadata.name, {})
            fitting = [
                it for it in plan.instance_types
                if all(
                    usage.get(key, 0.0) + it.capacity.get(key, 0.0) <= limit
                    for key, limit in pool.spec.limits.items()
                )
            ]
            if not fitting:
                over.append(plan)
                continue
            # create also rejects when the surviving types leave the
            # plan's OFFERING set empty (a spot-budget pin can strip
            # every offering of the limit-fitting types) — a plan this
            # sim passes but create would kill breaks the tail contract
            if plan.offerings and not any(
                o in it.offerings for it in fitting for o in plan.offerings
            ):
                over.append(plan)
                continue
            usage_by_pool[pool.metadata.name] = resutil.merge(
                usage, fitting[0].capacity
            )
        return over

    def _enforce_priority_admission(
        self, pods: Sequence[Pod], pools, results: SchedulerResults,
    ) -> SchedulerResults:
        """The overload degradation contract (provisioning/priority.py):
        when capacity (catalog or pool limits) truncates the solve, the
        unscheduled set must be exactly the lowest-priority tail of the
        admission order. Iterates cutoff-and-re-solve until the
        admitted prefix is clean; the cutoff strictly decreases, so the
        loop terminates. No-op on uniform-priority rounds."""
        from karpenter_tpu.provisioning import priority as padm
        from karpenter_tpu.provisioning.scheduler import NodeInputBuilder

        return padm.enforce_admission(
            list(pods), pools, results,
            solve_fn=lambda keep: self._make_scheduler(pools).solve(keep),
            plans_over_limits=self._plans_over_limits,
            daemon_overhead=lambda: NodeInputBuilder(
                pools, self.cluster.daemonsets()
            ).daemon_overhead(),
        )

    # -- create (provisioner.go:407-459) --------------------------------------

    def create_node_claims(self, results: SchedulerResults,
                           now: Optional[float] = None) -> list[NodeClaim]:
        from karpenter_tpu import tracing

        with tracing.span("create") as sp:
            created = self._create_node_claims(results, now)
            sp.annotate(claims=len(created),
                        limit_rejected=len(results.new_node_plans)
                        - len(created))
        return created

    def _create_node_claims(self, results: SchedulerResults,
                            now: Optional[float] = None) -> list[NodeClaim]:
        from karpenter_tpu import tracing

        # decision provenance: the launched claim carries the trace id
        # of the tick that produced it, so any node on the fleet
        # resolves back to the exact span tree (and fault window) via
        # /debug/traces?trace_id=<annotation>
        provenance = tracing.current_trace_id()
        created = []
        # one usage snapshot per round (an O(nodes) scan under the
        # cluster lock — not per plan), advanced in-loop with each
        # created claim's expected capacity so the plans of one call
        # cannot jointly blow a pool limit
        usage_by_pool = self.cluster.nodepool_resources()
        for plan in results.new_node_plans:
            claim = self._claim_from_plan(plan, usage_by_pool)
            if claim is None:
                from karpenter_tpu import explain
                from karpenter_tpu.provisioning.priority import (
                    LIMITS_ERROR,
                )

                for pod in plan.pods:
                    results.errors[pod.key] = LIMITS_ERROR
                    if explain.active() is not None:
                        explain.note_pod(
                            pod.key, verdict="unschedulable",
                            error=LIMITS_ERROR, code="limits",
                            pool=plan.pool.metadata.name,
                        )
                continue
            if claim.status.capacity:
                pool_name = plan.pool.metadata.name
                usage_by_pool[pool_name] = resutil.merge(
                    usage_by_pool.get(pool_name, {}), claim.status.capacity
                )
            if now is not None:
                # stamp the driving clock: liveness deadlines compare
                # claim age against the same `now` the controllers run
                # on, so a simulated-future round must not create
                # claims that look 15 minutes old already
                claim.metadata.creation_timestamp = now
            if provenance:
                claim.metadata.annotations[
                    tracing.PROVENANCE_ANNOTATION
                ] = provenance
            self.kube.create(claim)
            plan.claim_name = claim.metadata.name
            # sync-write into state so back-to-back solves see it
            # (provisioner.go:448-453)
            self.cluster.update_node_claim(claim)
            created.append(claim)
            # capacity type from the plan's resolved (cheapest) offering
            # — the launch target; the claim's own label lands only at
            # registration
            NODECLAIMS_CREATED.inc({
                "nodepool": plan.pool.metadata.name,
                "capacity_type": (
                    plan.offerings[0].capacity_type if plan.offerings else ""
                ),
            })
        # nominate existing nodes receiving pods (provisioner.go:399);
        # node_for_key also resolves claim-name keys so in-flight
        # nodes that just received assignments get their nomination
        # window too (disruption must not treat them as empty)
        for node_name in results.existing_assignments:
            state = self.cluster.node_for_key(node_name)
            if state is not None:
                state.nominate()
        return created

    def _claim_from_plan(
        self, plan: NodePlan,
        usage_by_pool: Optional[dict[str, ResourceList]] = None,
    ) -> Optional[NodeClaim]:
        pool = plan.pool
        # limits check (reference checks at create: nodepool.go Limits).
        # The claim keeps instance-type flexibility, so the LAUNCH may
        # resolve onto any admitted type: drop the types that would
        # breach the remaining limit headroom — then whichever type the
        # provider picks, the pool stays within its limits.
        if pool.spec.limits:
            if usage_by_pool is not None:
                usage = usage_by_pool.get(pool.metadata.name, {})
            else:
                usage = self.cluster.nodepool_resources().get(
                    pool.metadata.name, {}
                )
            fitting = [
                it for it in plan.instance_types
                if all(
                    usage.get(key, 0.0) + it.capacity.get(key, 0.0) <= limit
                    for key, limit in pool.spec.limits.items()
                )
            ]
            if not fitting:
                return None
            plan.instance_types = fitting
            plan.offerings = [
                o for o in plan.offerings
                if any(o in it.offerings for it in fitting)
            ]
            if not plan.offerings:
                return None

        requirements = [
            RequirementSpec(key=spec.key, operator=spec.operator,
                            values=tuple(spec.values), min_values=spec.min_values)
            for spec in pool.spec.template.spec.requirements
        ]
        for key, value in pool.spec.template.labels.items():
            requirements.append(RequirementSpec(key=key, operator=IN, values=(value,)))
        # tighten to the solved instance-type set
        type_names = tuple(it.name for it in plan.instance_types)
        requirements.append(
            RequirementSpec(key="node.kubernetes.io/instance-type", operator=IN,
                            values=type_names)
        )
        zones = tuple(sorted({o.zone for o in plan.offerings}))
        if zones:
            requirements.append(
                RequirementSpec(key="topology.kubernetes.io/zone", operator=IN,
                                values=zones)
            )
        captypes = tuple(sorted({o.capacity_type for o in plan.offerings}))
        if captypes:
            requirements.append(
                RequirementSpec(key="karpenter.sh/capacity-type", operator=IN,
                                values=captypes)
            )
        # a reservation-pinned plan carries its reservation id so the
        # provider launches into the reserved capacity
        # (FinalizeScheduling, scheduling/nodeclaim.go:252)
        rids = tuple(sorted({
            o.reservation_id for o in plan.offerings if o.reservation_id
        }))
        if rids:
            requirements.append(
                RequirementSpec(key="karpenter.sh/reservation-id", operator=IN,
                                values=rids)
            )

        # tighten with the scheduled pods' own requirements: the
        # reference's in-flight NodeClaim accumulates every added
        # pod's requirement set (nodeclaim.go:114-167 Add), so a claim
        # serving tier=gold pods pins the tier label even when the
        # template admits several values
        combined = Requirements(
            Requirement(r.key, r.operator, list(r.values), r.min_values)
            for r in requirements
        )
        for pod in plan.pods:
            combined.add(
                *(
                    r
                    for r in Requirements.from_pod(pod, required_only=True)
                    # keys the claim may not carry as requirements
                    # (karpenter.sh/nodepool rides the label; fully
                    # restricted domains are admission-rejected)
                    if r.key != NODEPOOL_LABEL
                    and is_restricted_label(r.key) is None
                )
            )
        if plan.min_values_relaxed:
            # BestEffort relaxation lowers an unsatisfiable floor to
            # the count of values the launchable instance types still
            # cover — the reference writes the satisfiable count back
            # onto the requirement (nodeclaim.go:147-150) rather than
            # dropping the floor outright
            coverage = min_values_coverage(plan.instance_types, combined)
            for req in combined:
                if (
                    req.min_values is not None
                    and coverage.get(req.key, 0) < req.min_values
                ):
                    req.min_values = coverage[req.key] or None
        requirements = []
        for req in combined:
            requirements.extend(
                _specs_from_requirement(req, plan.min_values_relaxed)
            )

        name = f"{pool.metadata.name}-{next(_claim_counter):05d}"
        claim = NodeClaim(
            metadata=ObjectMeta(
                name=name,
                namespace="",
                labels={NODEPOOL_LABEL: pool.metadata.name,
                        **pool.spec.template.labels},
                annotations=dict(pool.spec.template.annotations),
                finalizers=[TERMINATION_FINALIZER],
                owner_references=[nodepool_owner_ref(pool)],
            ),
            spec=NodeClaimSpec(
                requirements=requirements,
                resources=resutil.requests_for_pods(plan.pods),
                taints=list(pool.spec.template.spec.taints),
                startup_taints=list(pool.spec.template.spec.startup_taints),
                node_class_ref=pool.spec.template.spec.node_class_ref,
                expire_after=pool.spec.template.spec.expire_after,
                termination_grace_period=(
                    pool.spec.template.spec.termination_grace_period
                    if pool.spec.template.spec.termination_grace_period is not None
                    else DEFAULT_TERMINATION_GRACE_PERIOD
                ),
            ),
        )
        # expected capacity from the plan's primary (cheapest) type: an
        # unlaunched claim must still count against pool limits in
        # cluster state (StateNode.capacity falls back to this; the
        # provider's ACTUAL launch overwrites it, launch.go analogue) —
        # otherwise back-to-back rounds before a lifecycle tick see
        # zero committed capacity and jointly blow the limit
        if plan.instance_types:
            claim.status.capacity = dict(plan.instance_types[0].capacity)
        claim.metadata.annotations["karpenter.sh/nodepool-hash"] = pool.hash()
        if plan.min_values_relaxed:
            claim.metadata.annotations[
                NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION
            ] = "true"
        claim.metadata.annotations["karpenter.sh/nodepool-hash-version"] = "v3"
        return claim

    # -- reconcile loop (provisioner.go:119-145) ------------------------------

    def reconcile(self, now: Optional[float] = None) -> SchedulerResults:
        if not self.cluster.synced():
            return SchedulerResults(new_node_plans=[], existing_assignments={})
        # advance the provider's time-varying spot price curve before
        # the catalog is read: launch decisions see current spot market
        # prices, and a moved curve busts the encoder cache through the
        # catalog fingerprint exactly like an overlay price change
        reprice = getattr(self.cloud_provider, "reprice", None)
        if reprice is not None and now is not None:
            reprice(now)
        results = self.schedule()
        # crash window: the solver decided but nothing is written yet —
        # a restart must re-solve to the same decision from the API
        from karpenter_tpu.solver import faults as _faults

        _faults.fire("crash_claims")
        self.create_node_claims(results, now=now)
        self._record_events(results, now=now)
        self.batcher.reset()
        return results

    def micro_solve(
        self, pods: Sequence[Pod], now: Optional[float] = None,
    ) -> Optional[SchedulerResults]:
        """Event-driven micro provisioning round (ISSUE 17): a
        debounced arrival batch rides the incremental tick's O(dirty)
        path against retained inputs. Intake is the batch the reactive
        plane resolved — never a store walk. Returns None when the
        incremental envelope DEFERRED the batch to the next full tick
        (ineligible shape, cold cache, churn, quarantine, priority
        shedding); the operator re-arms the batcher in that case."""
        from karpenter_tpu import tracing

        if not pods or not self.cluster.synced():
            return None
        reprice = getattr(self.cloud_provider, "reprice", None)
        if reprice is not None and now is not None:
            reprice(now)
        pods = list(pods)
        from karpenter_tpu.scheduling.priority import (
            resolve_pod_priorities,
        )

        resolve_pod_priorities(pods, self.kube)
        if self._catalog_dirty.drain("NodePool"):
            self.encode_cache.invalidate()
        pools = self.ready_pools_with_types()
        # reduced-cost ordering from the retained dual certificate —
        # applied BEFORE tick() so the shadow audit sees the same order
        pods = self.incremental.micro_order(pods)
        with tracing.span("route"):
            results = self.incremental.tick(pods, pools, micro=True)
        if results is None:
            return None
        # same crash window as reconcile(): decided, nothing written —
        # the chaos suite kills the operator mid-micro-solve here
        from karpenter_tpu.solver import faults as _faults

        _faults.fire("crash_claims")
        self.create_node_claims(results, now=now)
        self._record_events(results, now=now)
        self.cluster.mark_pod_scheduling_decisions(pods)
        return results

    def _record_events(self, results: SchedulerResults,
                       now: Optional[float] = None) -> None:
        """Pod-facing scheduling events (scheduling/events.go:46-68:
        Nominated on placement, FailedScheduling with the reason on
        the unschedulable remainder)."""
        if self.recorder is None:
            return
        from karpenter_tpu.events.recorder import Event

        for target, pods in results.existing_assignments.items():
            # the assignment key is a node name OR an in-flight claim
            # name (scheduler._state_node_key) — say which, so kubectl
            # readers don't grep for a Node that doesn't exist yet
            noun = "node" if self.kube.get_node(target) else "nodeclaim"
            for pod in pods:
                self.recorder.publish(Event(
                    kind="Pod", name=pod.metadata.name,
                    namespace=pod.metadata.namespace, type="Normal",
                    reason="Nominated",
                    message=f"Pod should schedule on {noun} {target}",
                ), now=now)
        for plan in results.new_node_plans:
            if not plan.claim_name:
                continue  # limits rejected the claim; errors carry it
            for pod in plan.pods:
                self.recorder.publish(Event(
                    kind="Pod", name=pod.metadata.name,
                    namespace=pod.metadata.namespace, type="Normal",
                    reason="Nominated",
                    message="Pod should schedule on nodeclaim "
                            f"{plan.claim_name}",
                ), now=now)
        if results.errors:
            from karpenter_tpu import explain
            from karpenter_tpu.explain import funnel as funnel_mod
            from karpenter_tpu.metrics.store import POD_UNSCHEDULABLE_TICKS
            from karpenter_tpu.provisioning.scheduler import reason_code

            for key, reason in results.errors.items():
                pod = self.kube.get_pod(*key.split("/", 1))
                if pod is None:
                    continue
                # persistence stays visible through the counter even
                # while the (sticky-deduped) Event below never reposts
                POD_UNSCHEDULABLE_TICKS.inc({"reason": reason_code(reason)})
                message = f"Failed to schedule pod: {reason}"
                exclusions = funnel_mod.top_exclusions(explain.find_pod(key))
                if exclusions:
                    message += " (" + "; ".join(exclusions) + ")"
                # sticky: an identical message republished tick after
                # tick refreshes the recorder's frozen-key dedupe
                # window instead of reposting every DEDUPE_TTL
                self.recorder.publish(Event(
                    kind="Pod", name=pod.metadata.name,
                    namespace=pod.metadata.namespace, type="Warning",
                    reason="FailedScheduling",
                    message=message,
                ), now=now, sticky=True)
