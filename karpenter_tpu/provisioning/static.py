"""Static-capacity pools: replica-count maintenance.

Counterpart of pkg/controllers/static/{provisioning,deprovisioning}
(753 + 911 LoC) and the StaticDrift method (staticdrift.go:50-116):
NodePools with spec.replicas set hold exactly that many nodes built
from the template, independent of pod demand. Scale-up launches claims
from the template; scale-down picks the lowest-disruption-cost nodes;
drifted static nodes are rolled one at a time, replacement first.
"""

from __future__ import annotations

import itertools
import logging
import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    NODEPOOL_HASH_ANNOTATION,
    NODEPOOL_HASH_VERSION,
    NODEPOOL_HASH_VERSION_ANNOTATION,
    NODEPOOL_LABEL,
    TERMINATION_FINALIZER,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_DRIFTED,
    COND_INITIALIZED,
    NodeClaim,
    NodeClaimSpec,
    RequirementSpec,
)
from karpenter_tpu.apis.v1.nodepool import NodePool, nodepool_owner_ref
from karpenter_tpu.disruption.engine import pod_disruption_cost
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.kube.objects import ObjectMeta
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling.requirement import IN
from karpenter_tpu.state.cluster import Cluster

log = logging.getLogger("karpenter.static")

_counter = itertools.count(1)


class StaticCapacityController:
    def __init__(self, kube: KubeClient, cluster: Cluster,
                 options: Optional[Options] = None):
        self.kube = kube
        self.cluster = cluster
        self.options = options or Options()

    def reconcile_all(self, now: Optional[float] = None) -> None:
        if not self.options.feature_gates.static_capacity:
            return
        now = time.time() if now is None else now
        for pool in self.kube.node_pools():
            if not pool.is_static() or pool.metadata.deletion_timestamp is not None:
                continue
            self._reconcile_pool(pool, now)

    def _pool_claims(self, pool: NodePool) -> list[NodeClaim]:
        return [
            c for c in self.kube.node_claims()
            if c.metadata.labels.get(NODEPOOL_LABEL) == pool.metadata.name
        ]

    def _reconcile_pool(self, pool: NodePool, now: float) -> None:
        claims = self._pool_claims(pool)
        active = [c for c in claims if c.metadata.deletion_timestamp is None]
        target = pool.spec.replicas or 0
        if len(active) < target:
            # reserve before launching (statenodepool.go
            # ReserveNodeCount): under informer lag a second reconcile
            # sees stale counts; the reservation is what prevents it
            # from overshooting the replica target
            granted = self.cluster.reserve_node_count(
                pool.metadata.name, target - len(active), target
            )
            launched = 0
            try:
                for _ in range(granted):
                    self._launch(pool)
                    launched += 1
            except Exception:
                # every unlaunched slot goes back, not just the one
                # that failed — leaked reservations would wedge the
                # pool below its replica target forever
                self.cluster.release_node_reservation(
                    pool.metadata.name, granted - launched
                )
                raise
        elif len(active) > target:
            self._scale_down(pool, active, len(active) - target, now)
        else:
            self._roll_drifted(pool, active, now)

    def _next_claim_name(self, pool: NodePool) -> str:
        """Collision-proof claim name: the module counter restarts on
        checkpoint resume (KubeClient.load), so skip names the durable
        store already holds."""
        while True:
            name = f"{pool.metadata.name}-static-{next(_counter):05d}"
            if self.kube.get_node_claim(name) is None:
                return name

    def _launch(self, pool: NodePool) -> NodeClaim:
        requirements = [
            RequirementSpec(key=r.key, operator=r.operator, values=tuple(r.values),
                            min_values=r.min_values)
            for r in pool.spec.template.spec.requirements
        ]
        for key, value in pool.spec.template.labels.items():
            requirements.append(RequirementSpec(key=key, operator=IN, values=(value,)))
        claim = NodeClaim(
            metadata=ObjectMeta(
                name=self._next_claim_name(pool),
                namespace="",
                labels={NODEPOOL_LABEL: pool.metadata.name,
                        **pool.spec.template.labels},
                annotations={
                    NODEPOOL_HASH_ANNOTATION: pool.hash(),
                    NODEPOOL_HASH_VERSION_ANNOTATION: NODEPOOL_HASH_VERSION,
                },
                finalizers=[TERMINATION_FINALIZER],
                owner_references=[nodepool_owner_ref(pool)],
            ),
            spec=NodeClaimSpec(
                requirements=requirements,
                taints=list(pool.spec.template.spec.taints),
                startup_taints=list(pool.spec.template.spec.startup_taints),
                node_class_ref=pool.spec.template.spec.node_class_ref,
                expire_after=pool.spec.template.spec.expire_after,
                termination_grace_period=pool.spec.template.spec.termination_grace_period,
            ),
        )
        self.kube.create(claim)
        log.info("static pool %s: launched %s", pool.metadata.name, claim.metadata.name)
        return claim

    def _scale_down(self, pool: NodePool, active: list[NodeClaim], count: int,
                    now: float) -> None:
        """Deprovision the cheapest-to-disrupt nodes, drifted claims
        first (static/deprovisioning/controller.go:75-200). When the
        surplus exists because a drift roll is in flight, wait for the
        replacement to initialize before removing anything."""
        if any(
            not c.status_conditions.is_true(COND_INITIALIZED) for c in active
        ) and any(c.status_conditions.is_true(COND_DRIFTED) for c in active):
            return
        def cost(claim: NodeClaim) -> tuple:
            state = None
            for node in self.cluster.nodes():
                if node.node_claim is claim or (
                    node.node_claim is not None
                    and node.node_claim.metadata.name == claim.metadata.name
                ):
                    state = node
                    break
            drifted = claim.status_conditions.is_true(COND_DRIFTED)
            if state is None:
                return (not drifted, 0.0)
            total = 0.0
            for pod_key in state.pod_keys:
                pod = self.kube.get_pod(*pod_key.split("/", 1))
                if pod is not None and pod.owner_kind() != "DaemonSet":
                    total += pod_disruption_cost(pod)
            return (not drifted, total)

        for claim in sorted(active, key=cost)[:count]:
            self.kube.delete(claim, now=now)
            log.info("static pool %s: scaled down %s", pool.metadata.name,
                     claim.metadata.name)

    def _roll_drifted(self, pool: NodePool, active: list[NodeClaim], now: float) -> None:
        """StaticDrift: replace drifted nodes one at a time, replacement
        first (staticdrift.go:50-116)."""
        drifted = [c for c in active if c.status_conditions.is_true(COND_DRIFTED)]
        if not drifted:
            return
        # budget check: one roll at a time within allowed disruptions
        allowed = pool.must_get_allowed_disruptions(
            now, len(active), "Drifted"
        )
        if allowed <= 0:
            return
        # a pending replacement (uninitialized fresh claim) means a roll
        # is already in flight; wait for it
        initializing = [
            c for c in active
            if not c.status_conditions.is_true(COND_INITIALIZED)
        ]
        if initializing:
            return
        # replacement-first: launch the surplus claim now; once it
        # initializes, _scale_down removes the drifted claim (drifted
        # claims sort first) — staticdrift.go:50-116 ordering
        replacement = self._launch(pool)
        log.info("static pool %s: rolling drifted %s -> %s", pool.metadata.name,
                 drifted[0].metadata.name, replacement.metadata.name)
