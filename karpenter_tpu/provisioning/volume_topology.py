"""PVC-driven zonal requirements + CSI volume-limit context.

Counterpart of provisioning/scheduling/volumetopology.go:51-160: a pod
referencing a BOUND PVC must schedule into the persistent volume's
zone; a pod with an unbound PVC whose StorageClass restricts
allowedTopologies must schedule into one of those zones. The derived
requirement is stored on `pod.spec.injected_requirements` (transient,
re-derived every round) where `Requirements.from_pod` picks it up for
both the batched solver encoding and the per-pod path.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from karpenter_tpu.apis.v1.labels import TOPOLOGY_ZONE_LABEL
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.scheduling.requirement import IN, Requirement

if TYPE_CHECKING:  # pragma: no cover
    from karpenter_tpu.kube.client import KubeClient


# CSI provisioners the installed providers cannot serve; providers
# populate this (scheduling.UnsupportedProvisioners in the reference,
# empty by default)
UNSUPPORTED_PROVISIONERS: set[str] = set()


def _pvc_name_for(pod: Pod, vol) -> "str | None":
    """The claim a pod volume references, or None for claimless kinds
    (emptyDir/hostPath/NFS). Generic ephemeral volumes resolve to
    their '<pod>-<volume>' claim — the single naming contract shared
    by intake validation and zone injection."""
    if vol.ephemeral:
        return f"{pod.metadata.name}-{vol.name}"
    return vol.pvc_name or None


def _owned_by(pvc, pod: Pod) -> bool:
    # kind+name+UID, as kube-scheduler's ephemeral.VolumeIsForPod
    # checks: a stale claim left by a deleted same-name pod must not
    # pass as the recreated pod's own
    return any(
        ref.kind == "Pod"
        and ref.name == pod.metadata.name
        and ref.uid == pod.metadata.uid
        for ref in pvc.metadata.owner_references
    )


def validate_pvcs(pod: Pod, kube: "KubeClient") -> "str | None":
    """Why this pod cannot be provisioned w.r.t. its PVCs, or None.

    Mirrors ValidatePersistentVolumeClaims
    (volumetopology.go:160-215): the cases kube-scheduler itself
    rejects — deleting or Lost claims, bound claims whose volume is
    gone, unbound claims with no / unknown / Immediate-mode /
    unsupported-provisioner storage class. Such pods are filtered at
    intake rather than churning the scheduler every round.
    """
    for vol in pod.spec.volumes:
        pvc_name = _pvc_name_for(pod, vol)
        if pvc_name is None:
            continue  # emptyDir/hostPath/NFS-style volumes: no claim
        pvc = kube.get_pvc(pod.metadata.namespace, pvc_name)
        if pvc is None:
            if vol.ephemeral:
                continue  # created after scheduling; nothing to check
            return f"persistentvolumeclaim {pvc_name} not found"
        if vol.ephemeral and not _owned_by(pvc, pod):
            # an existing claim under the ephemeral name that the pod
            # does not own is rejected by kube-scheduler forever
            # (volumeutil.GetPersistentVolumeClaim ownership check)
            return (
                f"persistentvolumeclaim {pvc_name} exists but is not "
                "owned by the pod"
            )
        if pvc.metadata.deletion_timestamp is not None:
            return f"persistentvolumeclaim {pvc_name} is being deleted"
        if pvc.phase == "Lost":
            return (
                f"persistentvolumeclaim {pvc_name} bound to "
                "non-existent persistentvolume"
            )
        if pvc.spec.volume_name:
            if kube.get_pv(pvc.spec.volume_name) is None:
                return (
                    f"persistentvolume {pvc.spec.volume_name} not found"
                )
            continue
        sc_name = pvc.spec.storage_class_name
        if not sc_name:
            return f"unbound persistentvolumeclaim {pvc_name} must define a storage class"
        sc = kube.get_storage_class(sc_name)
        if sc is None:
            return f"storage class {sc_name} not found"
        if sc.volume_binding_mode == "Immediate":
            return (
                f"persistentvolumeclaim {pvc_name} with immediate "
                "volume binding mode must be bound"
            )
        if sc.provisioner in UNSUPPORTED_PROVISIONERS:
            return f"provisioner {sc.provisioner} is not supported"
    return None


def inject(pod: Pod, kube: "KubeClient") -> None:
    """Re-derive the pod's PVC zonal requirements for this round."""
    reqs: list[Requirement] = []
    for vol in pod.spec.volumes:
        pvc_name = _pvc_name_for(pod, vol)
        if pvc_name is None:
            continue
        pvc = kube.get_pvc(pod.metadata.namespace, pvc_name)
        if pvc is None:
            continue
        zones = None
        if pvc.spec.volume_name:
            pv = kube.get_pv(pvc.spec.volume_name)
            if pv is not None and pv.zones:
                zones = pv.zones
        elif pvc.spec.storage_class_name:
            sc = kube.get_storage_class(pvc.spec.storage_class_name)
            if sc is not None and sc.zones:
                zones = sc.zones
        if zones:
            reqs.append(Requirement(TOPOLOGY_ZONE_LABEL, IN, list(zones)))
    pod.spec.injected_requirements = reqs
