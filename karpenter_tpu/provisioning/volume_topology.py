"""PVC-driven zonal requirements + CSI volume-limit context.

Counterpart of provisioning/scheduling/volumetopology.go:51-160: a pod
referencing a BOUND PVC must schedule into the persistent volume's
zone; a pod with an unbound PVC whose StorageClass restricts
allowedTopologies must schedule into one of those zones. The derived
requirement is stored on `pod.spec.injected_requirements` (transient,
re-derived every round) where `Requirements.from_pod` picks it up for
both the batched solver encoding and the per-pod path.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from karpenter_tpu.apis.v1.labels import TOPOLOGY_ZONE_LABEL
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.scheduling.requirement import IN, Requirement

if TYPE_CHECKING:  # pragma: no cover
    from karpenter_tpu.kube.client import KubeClient


def inject(pod: Pod, kube: "KubeClient") -> None:
    """Re-derive the pod's PVC zonal requirements for this round."""
    reqs: list[Requirement] = []
    for vol in pod.spec.volumes:
        pvc_name = vol.pvc_name
        if vol.ephemeral:
            pvc_name = f"{pod.metadata.name}-{vol.name}"
        if not pvc_name:
            continue
        pvc = kube.get_pvc(pod.metadata.namespace, pvc_name)
        if pvc is None:
            continue
        zones = None
        if pvc.spec.volume_name:
            pv = kube.get_pv(pvc.spec.volume_name)
            if pv is not None and pv.zones:
                zones = pv.zones
        elif pvc.spec.storage_class_name:
            sc = kube.get_storage_class(pvc.spec.storage_class_name)
            if sc is not None and sc.zones:
                zones = sc.zones
        if zones:
            reqs.append(Requirement(TOPOLOGY_ZONE_LABEL, IN, list(zones)))
    pod.spec.injected_requirements = reqs
