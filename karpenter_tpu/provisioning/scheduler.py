"""Scheduling orchestration: pods + cluster snapshot -> node plans.

Counterpart of provisioning/scheduling/scheduler.go. The flow
(NewScheduler provisioner.go:235-301 + Solve scheduler.go:377):

1. ready NodePools ordered by weight; instance types per pool
2. existing + in-flight nodes from the state snapshot (existing first,
   in-flight sorted fewest-pods-first — scheduler.go:552 comment)
3. daemonset overhead per pool template (scheduler.go:772-803)
4. fast path: pods free of topology constraints go through the batched
   TPU solver in one shot (solver.solve)
5. slow path: topology-constrained pods run per-pod against the same
   dense encoding with Topology domain filtering, with the preference
   relaxation ladder (preferences.go:38-141) applied on failure
6. results: NodeClaimPlans (pool + price-ordered instance types,
   truncated to MAX_INSTANCE_TYPES honoring minValues), existing-node
   assignments, per-pod errors
"""

from __future__ import annotations

import logging
import math
import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    CAPACITY_TYPE_SPOT,
    HOSTNAME_LABEL,
    NODEPOOL_LABEL,
    RESERVATION_ID_LABEL,
    SPOT_MAX_FRACTION_ANNOTATION,
    SPOT_MIN_ON_DEMAND_ANNOTATION,
    TOPOLOGY_ZONE_LABEL,
    WELL_KNOWN_LABELS,
)
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.types import (
    InstanceType,
    order_by_price,
    satisfies_min_values,
    truncate,
)
from karpenter_tpu.kube.objects import Pod
from karpenter_tpu.metrics.store import (
    SCHEDULER_QUEUE_DEPTH,
    SCHEDULER_SCHEDULING_DURATION,
    SCHEDULER_UNFINISHED_WORK,
    SCHEDULER_UNSCHEDULABLE_PODS,
)
from karpenter_tpu.scheduling.hostports import HostPortUsage, pod_host_ports
from karpenter_tpu.scheduling.volumeusage import VolumeUsage, pod_volume_drivers
from karpenter_tpu.provisioning import volume_topology
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.scheduling.taints import tolerates_pod
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import topo_batch
from karpenter_tpu.solver.encode import (
    ExistingNodeInput,
    PodGroup,
    encode,
    group_pods,
)
from karpenter_tpu.solver.solver import NodePlan, Solution, solve_encoded
from karpenter_tpu.state.cluster import StateNode
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.provisioning.preferences import relax

log = logging.getLogger("karpenter.scheduler")

# scheduler knob (nodeclaimtemplate.go:41)
MAX_INSTANCE_TYPES = 600

# Solve wall-clock bound (provisioner.go:365-368): one minute, after
# which the round returns best-effort partial results and unplaced pods
# report a timeout error
SOLVE_TIMEOUT_SECONDS = 60.0

TIMEOUT_ERROR = "scheduling timed out; will retry next round"

# the batched fast path's capacity failure — the CANONICAL string:
# priority admission, the disruption priority veto, preemption, and
# the incremental tick's audit all match on it exactly, so every
# producer and consumer must import THIS constant
NO_CAPACITY_ERROR = "no compatible instance types or nodes"

# DRA pods are rejected permanently (no relaxation retry) while the
# ignore-dra-requests flag is on — scheduler.go:489-491, 448-452
DRA_ERROR = (
    "pod has Dynamic Resource Allocation requirements that are not yet "
    "supported"
)

# the relaxation ladder's terminal failure on the per-pod topology
# path — single-sourced like NO_CAPACITY_ERROR/LIMITS_ERROR (ISSUE 14
# satellite): the exact string is the contract; consumers that need a
# machine-readable class go through reason_code() below
TOPOLOGY_INCOMPATIBLE_ERROR = (
    "incompatible with topology constraints or no capacity"
)

# minValues rejects are parametric ("minValues requirement not met:
# <detail>"); this prefix is their stable, matchable head
MIN_VALUES_ERROR_PREFIX = "minValues requirement not met"


def reason_code(error: str) -> str:
    """Structured reason code for one scheduler error string — the
    label the unschedulable-ticks counter and the explain plane carry
    so dashboards never regex free-form prose. Exact-string consumers
    (priority shedding, preemption, the disruption veto) keep matching
    the canonical constants; this is the classification layer on top."""
    if error == NO_CAPACITY_ERROR:
        return "no_capacity"
    if error == TOPOLOGY_INCOMPATIBLE_ERROR:
        return "topology_or_capacity"
    if error == TIMEOUT_ERROR:
        return "timeout"
    if error == DRA_ERROR:
        return "dra_unsupported"
    if error.startswith(MIN_VALUES_ERROR_PREFIX):
        return "min_values"
    # late imports: provisioning.priority imports THIS module for the
    # canonical capacity string, so its constants resolve lazily here
    from karpenter_tpu.provisioning.priority import (
        LIMITS_ERROR,
        PRIORITY_SHED_ERROR,
    )

    if error == LIMITS_ERROR:
        return "limits"
    if error == PRIORITY_SHED_ERROR:
        return "priority_shed"
    return "other"


@dataclass
class SchedulerResults:
    new_node_plans: list[NodePlan]
    existing_assignments: dict[str, list[Pod]]      # state-node name -> pods
    errors: dict[str, str] = field(default_factory=dict)  # pod key -> reason
    # resilience ladder rungs (other than the primary) that served any
    # kernel call of this solve — empty on a healthy tick
    degraded_rungs: list[str] = field(default_factory=list)

    @property
    def scheduled_count(self) -> int:
        return sum(len(n.pods) for n in self.new_node_plans) + sum(
            len(p) for p in self.existing_assignments.values()
        )


def _state_node_key(node: StateNode) -> str:
    """Stable key for an existing-node assignment: the node name, or
    the claim name while the node has not materialized (an in-flight
    claim has no Node object yet; an empty key would collide every
    in-flight assignment onto one entry)."""
    if node.name:
        return node.name
    if node.node_claim is not None:
        return node.node_claim.metadata.name
    return ""


def _pool_requirements(pool: NodePool) -> Requirements:
    """The pool template's requirement set, minValues included."""
    from karpenter_tpu.solver.encode import pool_template_requirements

    return pool_template_requirements(pool, with_labels=False)


def _strip_offerings(it: InstanceType, drop) -> InstanceType:
    """Instance type without the offerings `drop` matches (unchanged
    instance returned when nothing matched)."""
    kept = [o for o in it.offerings if not drop(o)]
    if len(kept) == len(it.offerings):
        return it
    from karpenter_tpu.cloudprovider.types import Offerings

    return InstanceType(
        name=it.name,
        requirements=it.requirements,
        offerings=Offerings(kept),
        capacity=it.capacity,
        overhead=it.overhead,
    )


def _strip_reserved(it: InstanceType) -> InstanceType:
    """Instance type without its reserved-capacity offerings."""
    return _strip_offerings(it, lambda o: o.is_reserved())


def _strip_spot(it: InstanceType) -> InstanceType:
    """Instance type without its spot offerings (a pool whose spot
    budget is zero never encodes a spot column at all)."""
    return _strip_offerings(it, lambda o: o.is_spot())


# -- spot availability targets ------------------------------------------------
#
# Spot capacity is interruptible; a pool that lets EVERY node resolve
# to spot trades its whole availability on the interruption regime.
# Two per-pool knobs bound the exposure (KubePACS availability targets,
# PAPERS.md): a max fraction of the pool's nodes that may be spot, and
# an absolute floor of on-demand nodes. Fleet-wide env defaults; pool
# annotations override.

SPOT_MAX_FRACTION_ENV = "KARPENTER_SPOT_MAX_FRACTION"
SPOT_MIN_ON_DEMAND_ENV = "KARPENTER_SPOT_MIN_ON_DEMAND"


def pool_spot_budget(pool: NodePool) -> tuple[float, int]:
    """(max spot fraction in [0, 1], min non-spot node floor >= 0)
    for one pool — annotation over env default over (1.0, 0). The
    floor counts every non-interruptible node (on-demand AND
    reserved): it bounds exposure to the interruption regime, not the
    billing model."""

    def _knob(ann_key, env_key, default, cast, lo):
        # a malformed annotation falls back to the FLEET default (the
        # env knob), not straight to unbounded — a typo'd per-pool
        # override must not widen the pool's exposure past what the
        # operator configured fleet-wide
        for source, raw in (
            (ann_key, pool.metadata.annotations.get(ann_key)),
            (env_key, os.environ.get(env_key, "")),
        ):
            if not raw:
                continue
            try:
                return max(lo, cast(raw))
            except (TypeError, ValueError):
                log.warning("bad spot budget knob %s=%r; ignoring",
                            source, raw)
        return default

    frac = _knob(SPOT_MAX_FRACTION_ANNOTATION, SPOT_MAX_FRACTION_ENV,
                 1.0, float, 0.0)
    floor = _knob(SPOT_MIN_ON_DEMAND_ANNOTATION, SPOT_MIN_ON_DEMAND_ENV,
                  0, int, 0)
    return (min(frac, 1.0), floor)


def note_unschedulable_explanations(
    pods: Sequence[Pod],
    results: "SchedulerResults",
    pools_with_types,
    existing_inputs: Sequence[ExistingNodeInput],
    daemon_overhead: Optional[dict] = None,
    reserved_in_use: Optional[dict[str, int]] = None,
) -> None:
    """Record a verdict for every unschedulable pod — the error, its
    structured reason code, and (for capacity-class failures) the
    elimination funnel. Module-level: the full Scheduler and the
    incremental live tick explain through the same function, so the
    two paths' accounts cannot drift. Runs AFTER the solve, only over
    the failed set, with the funnel memoized per scheduling signature
    so a thousand identical starved pods pay one catalog walk."""
    from karpenter_tpu import explain
    from karpenter_tpu.explain import funnel as funnel_mod

    if explain.active() is None or not results.errors:
        return
    by_key = {p.key: p for p in pods}
    funnel_cache: dict[tuple, dict] = {}
    for key, error in sorted(results.errors.items()):
        code = reason_code(error)
        explain.note_pod(key, verdict="unschedulable", error=error,
                         code=code)
        if code not in ("no_capacity", "topology_or_capacity"):
            continue
        pod = by_key.get(key)
        if pod is None:
            continue
        sig = (
            Requirements.from_pod(pod).signature(),
            tuple(sorted(pod.spec.tolerations, key=repr)),
            tuple(sorted(resutil.pod_requests(pod).items())),
        )
        funnel = funnel_cache.get(sig)
        if funnel is None:
            funnel = funnel_mod.compute(
                pod, pools_with_types, existing_inputs,
                daemon_overhead, reserved_in_use,
            )
            funnel_cache[sig] = funnel
        explain.note_funnel(key, funnel)


class NodeInputBuilder:
    """Shared builder of solver inputs from cluster state: existing/
    in-flight node inputs and daemonset overhead/reservations.

    Extracted from Scheduler so the provisioner's incremental live
    tick (provisioning/incremental_tick.py) derives its RETAINED
    per-node inputs through the exact same code path the full
    Scheduler uses per round — the two paths cannot drift, which is
    what makes the incremental-vs-full oracle audit a meaningful
    equality check instead of a tolerance band."""

    def __init__(
        self,
        pools_with_types: Sequence[tuple[NodePool, Sequence[InstanceType]]],
        daemonsets: Sequence = (),
        ignore_dra_requests: bool = True,
    ):
        self.pools_with_types = list(pools_with_types)
        self.daemonsets = list(daemonsets)
        self.ignore_dra_requests = ignore_dra_requests
        # per-node daemon reservation, memoized: invariant within a
        # scheduling round, but existing_input re-runs per committed
        # pod on the slow path. The live tick invalidates per key when
        # a node's watch events mark it dirty.
        self._daemon_reserve_cache: dict[str, dict[str, float]] = {}

    def invalidate(self, key: str) -> None:
        """Drop one node's memoized daemon reservation (the node's
        taints/labels/daemon pods changed)."""
        self._daemon_reserve_cache.pop(key, None)

    def existing_input(self, node: StateNode) -> ExistingNodeInput:
        reqs = Requirements.from_labels(node.labels())
        if node.node_claim is not None and not node.registered():
            for spec in node.node_claim.spec.requirements:
                reqs.add(Requirement(spec.key, spec.operator, spec.values,
                                     spec.min_values))
        available = resutil.positive(node.available())
        claim = node.node_claim
        if (
            node.node is None
            and claim is not None
            and not claim.status.allocatable
        ):
            # no REAL allocatable yet = the provider hasn't launched
            # (creation stamps only the plan's expected capacity); a
            # launched-but-full node has allocatable set and correctly
            # reports empty `available` above.
            # A claim created but not yet LAUNCHED has no
            # status.capacity: model it from its admissible instance
            # types like the reference's in-flight NodeClaim scheduling
            # nodes (scheduler.go builds them from instanceTypeOptions)
            # — otherwise pods freed by a disruption command can't land
            # on the command's own replacement and the provisioner buys
            # duplicate capacity (suite_test.go:454). The MINIMUM
            # allocatable across admissible types is conservative:
            # whatever type the launch resolves can hold what we place.
            # (Gated on the claim being truly unlaunched — a launched,
            # full node legitimately has empty `available`.)
            available = resutil.positive(
                resutil.subtract(
                    self._min_admissible_allocatable(node, reqs), node.used()
                )
            )
        reserve = self.daemon_reserve(node)
        if reserve:
            available = resutil.positive(
                resutil.subtract(available, reserve)
            )
        return ExistingNodeInput(
            name=_state_node_key(node),
            requirements=reqs,
            taints=tuple(node.taints()),
            available=available,
            pool_name=node.nodepool_name(),
            pod_count=len(node.pod_keys),
        )

    def _min_admissible_allocatable(
        self, node: StateNode, reqs: Requirements
    ) -> ResourceList:
        """Component-wise minimum allocatable over the pool's instance
        types compatible with `reqs` (the caller's labels+claim
        requirements) — the floor of what the launch can
        materialize."""
        floor: ResourceList = {}
        for pool, types in self.pools_with_types:
            if pool.metadata.name != node.nodepool_name():
                continue
            for it in types:
                if it.requirements.intersects(reqs) is not None:
                    continue
                alloc = it.allocatable
                if not floor:
                    floor = dict(alloc)
                else:
                    floor = {
                        k: min(v, alloc.get(k, 0.0))
                        for k, v in floor.items()
                    }
        return floor

    def _daemon_expected(
        self, node_reqs: Requirements, taints: list
    ) -> dict[str, float]:
        """Total requests of daemonsets whose pods can land on a node
        with these taints/labels (isDaemonPodCompatibleWithNode,
        scheduler.go:708-717) — the one filter shared by new-node
        overhead budgeting and existing-node reservation."""
        from karpenter_tpu.utils.pod import has_dra_requirements

        expected: dict[str, float] = {}
        for ds in self.daemonsets:
            pod = Pod(spec=ds.spec.template.spec)
            pod.metadata.labels = dict(ds.spec.template.metadata.labels)
            # a DRA daemon pod can never be scheduled by us, so its
            # requests must not inflate any budget
            # (shouldSkipDaemonPod, scheduler.go:702-705)
            if self.ignore_dra_requests and has_dra_requirements(pod):
                continue
            if tolerates_pod(taints, pod) is not None:
                continue
            if not self._daemon_compatible(node_reqs, pod):
                continue
            expected = resutil.merge(expected, resutil.pod_requests(pod))
        return expected

    def _daemon_compatible(self, node_reqs: Requirements, pod: Pod) -> bool:
        """Daemon-pod schedulability against a node/template: required
        node-affinity terms are ORed — ANY matching term admits the
        pod (the kube-scheduler semantic the reference's per-term check
        follows) — and hostname affinity is dropped first: a daemonset
        pinned to an EXISTING node's hostname says nothing about new
        capacity (suite_test.go "remove daemonset node hostname
        affinity when considering daemonset schedulability")."""
        base = Requirements.from_labels(dict(pod.spec.node_selector))
        if pod.spec.injected_requirements:
            base.add(*pod.spec.injected_requirements)
        aff = pod.spec.affinity
        terms = ()
        if aff is not None and aff.node_affinity is not None:
            terms = aff.node_affinity.required or ()
        if not terms:
            return node_reqs.is_compatible(
                base, allow_undefined=WELL_KNOWN_LABELS
            )
        for term in terms:
            reqs = Requirements(r.copy() for r in base)
            reqs.add(*(
                r
                for r in Requirements.from_node_selector_requirements(
                    term.match_expressions
                ).values()
                if r.key != HOSTNAME_LABEL
            ))
            if node_reqs.is_compatible(
                reqs, allow_undefined=WELL_KNOWN_LABELS
            ):
                return True
        return False

    def daemon_reserve(self, node: StateNode) -> dict[str, float]:
        """Capacity still owed to daemonsets on this node: the
        requests of every daemonset whose pods CAN land here, minus
        daemon pods already bound, floored at zero (unexpected daemon
        pods must not push the reservation negative) —
        existingnode.go:41-52, scheduler.go isDaemonPodCompatibleWithNode.
        """
        if not self.daemonsets or not node.managed():
            return {}
        cache_key = _state_node_key(node)
        cached = self._daemon_reserve_cache.get(cache_key)
        if cached is not None:
            return cached
        expected = self._daemon_expected(
            Requirements.from_labels(node.labels()), list(node.taints())
        )
        # net of daemon pods already bound to the node — cluster state
        # tracks these (terminal pods excluded) so the reservation is
        # not re-derived from the raw pod list
        reserve = (
            resutil.positive(resutil.subtract(expected, node.daemon_usage))
            if expected
            else {}
        )
        self._daemon_reserve_cache[cache_key] = reserve
        return reserve

    def daemon_overhead(self) -> dict[str, dict[str, float]]:
        """Per-pool daemonset resource overhead (scheduler.go:772-803):
        sum requests of daemon pods whose scheduling terms admit the
        pool template. Uses the same full-compatibility filter
        (undefined-key rules included) as the existing-node
        reservation, via _daemon_expected."""
        from karpenter_tpu.solver.encode import pool_template_requirements

        out: dict[str, dict[str, float]] = {}
        for pool, types in self.pools_with_types:
            total = self._daemon_expected(
                pool_template_requirements(pool, with_pool_pin=True),
                list(pool.spec.template.spec.taints),
            )
            if total:
                out[pool.metadata.name] = total
        return out


def plan_domains(plan: NodePlan) -> dict[str, str]:
    """Representative domains for a planned node. Module-level: the
    full Scheduler and the incremental live tick derive a planned
    node's topology contribution through the same function."""
    out: dict[str, str] = {}
    if plan.offerings:
        out[TOPOLOGY_ZONE_LABEL] = plan.offerings[0].zone
        out[CAPACITY_TYPE_LABEL] = plan.offerings[0].capacity_type
    out[HOSTNAME_LABEL] = f"planned-{id(plan)}"
    out[NODEPOOL_LABEL] = plan.pool.metadata.name
    return out


def plan_pseudo_input(
    plan: NodePlan, daemon_overhead: dict
) -> Optional[ExistingNodeInput]:
    """An open plan as a pseudo-existing node for the lowered topology
    solve — the in-flight NodeClaim model (scheduling/nodeclaim.go:
    114-167): remaining capacity is the cheapest instance-type option
    that still holds the plan's current pods. Module-level so the
    incremental live tick's topology phase builds the exact same
    pseudo rows the full Scheduler does."""
    used = resutil.merge(
        daemon_overhead.get(plan.pool.metadata.name, {}),
        resutil.requests_for_pods(plan.pods),
    )
    for it in plan.instance_types:  # price-ordered
        if resutil.fits(used, it.allocatable):
            avail = resutil.positive(resutil.subtract(it.allocatable, used))
            break
    else:
        return None
    labels = plan_domains(plan)
    reqs = Requirements.from_labels(labels)
    for key, value in plan.pool.spec.template.labels.items():
        reqs.add(Requirement(key, IN, [value]))
    # permanent taints only: startupTaints clear before pods run, so
    # they never gate placement onto the planned node (same rule as
    # build_configs / statenode.go:322-326)
    taints = tuple(plan.pool.spec.template.spec.taints)
    return ExistingNodeInput(
        name=f"planned-{id(plan)}",
        requirements=reqs,
        taints=taints,
        available=avail,
        pool_name=plan.pool.metadata.name,
        pod_count=len(plan.pods),
    )


def finalize_plan(plan: NodePlan) -> None:
    """Price-order and truncate instance types, honoring the pool's
    minValues floors (results.TruncateInstanceTypes,
    provisioner.go:374; types.go:322-334). Module-level: the full
    Scheduler and the incremental live tick finalize through the same
    function."""
    pool_reqs = _pool_requirements(plan.pool)
    try:
        plan.instance_types = truncate(
            plan.instance_types, pool_reqs, MAX_INSTANCE_TYPES
        )
    except ValueError:
        # truncation cannot keep the minValues floor —
        # _enforce_min_values decides reject (Strict) vs relax
        plan.instance_types = truncate(
            plan.instance_types, Requirements(), MAX_INSTANCE_TYPES
        )


class Scheduler:
    def __init__(
        self,
        pools_with_types: Sequence[tuple[NodePool, Sequence[InstanceType]]],
        state_nodes: Sequence[StateNode] = (),
        daemonsets: Sequence = (),
        cluster_pods: Sequence[Pod] = (),
        honor_preferences: bool = True,
        allow_reserved: bool = True,
        min_values_policy: str = "Strict",
        kube=None,
        clock=None,
        solve_timeout: float = SOLVE_TIMEOUT_SECONDS,
        ignore_dra_requests: bool = True,
        metrics_controller: str = "provisioner",
        objective: str = "ffd",
        compat_cache=None,
        existing_input_cache: Optional[dict[str, ExistingNodeInput]] = None,
    ):
        # "cost" engages the LP planner on the batched fast path (the
        # global-repack consolidation re-solve); topology/per-pod paths
        # always pack FFD — their constraints aren't in the LP
        self.objective = objective
        # incremental.EncodedCache shared across rounds by the owning
        # provisioner: steady-state rounds re-encode only the group
        # signatures that actually changed (dirty rows)
        self.compat_cache = compat_cache
        self.min_values_policy = min_values_policy
        self.ignore_dra_requests = ignore_dra_requests
        self.metrics_controller = metrics_controller
        self._solve_start = 0.0
        self._last_progress_publish = 0.0
        self.kube = kube
        import time as _time

        self.clock = clock if clock is not None else _time.monotonic
        self.solve_timeout = solve_timeout
        self._deadline: Optional[float] = None
        if not allow_reserved:
            # ReservedCapacity gate off: reserved offerings never enter
            # the solve (options.go feature gates)
            pools_with_types = [
                (pool, [_strip_reserved(it) for it in types])
                for pool, types in pools_with_types
            ]
        # a zero spot budget is enforced INSIDE the encoded offering
        # matrices: the pool's spot offerings never become config
        # columns, so neither pack_split nor the per-pod path can pick
        # one (fractional budgets pin plans post-solve instead — the
        # node count a fraction applies to is unknown until decode)
        pools_with_types = [
            (
                pool,
                [_strip_spot(it) for it in types]
                if pool_spot_budget(pool)[0] <= 0.0 else types,
            )
            for pool, types in pools_with_types
        ]
        # weight order (provisioner.go:241-262)
        self.pools_with_types = sorted(
            pools_with_types, key=lambda pt: (-pt[0].spec.weight, pt[0].metadata.name)
        )
        if self.min_values_policy != "BestEffort":
            # Strict: a pool whose full catalog cannot satisfy its own
            # minValues can never launch a valid claim — drop it up
            # front so pods fall through to the next weighted pool
            # (upstream filters minValues-incompatible options per
            # nodepool during scheduling, types.go:284-318)
            kept = []
            for pool, types in self.pools_with_types:
                pool_reqs = _pool_requirements(pool)
                if pool_reqs.has_min_values():
                    # count only types the pool's own requirements admit
                    # — raw-catalog counting would let an unsatisfiable
                    # pool survive on incompatible types
                    compatible = [
                        it for it in types
                        if pool_reqs.intersects(it.requirements) is None
                    ]
                    _, err = satisfies_min_values(compatible, pool_reqs)
                    if err is not None:
                        continue
                kept.append((pool, types))
            self.pools_with_types = kept
        self.honor_preferences = honor_preferences
        self.daemonsets = list(daemonsets)
        self.cluster_pods = list(cluster_pods)

        # existing-node input + daemon machinery shared with the
        # incremental live tick (see NodeInputBuilder)
        self.input_builder = NodeInputBuilder(
            self.pools_with_types, self.daemonsets, self.ignore_dra_requests
        )

        # existing first, then in-flight fewest-pods-first (scheduler.go:552)
        live = [n for n in state_nodes if not n.deleting() and n.initialized()]
        inflight = [n for n in state_nodes if not n.deleting() and not n.initialized()]
        inflight.sort(key=lambda n: (len(n.pod_keys), n.name))
        self.state_nodes = live + inflight
        # `existing_input_cache` (state/retained.RetainedFleetSeam):
        # retained, dirty-tracked ExistingNodeInput rows keyed by
        # _state_node_key — a cached row is exactly what
        # _existing_input would build (the seam only retains rows for
        # stable launched nodes and rebuilds on watch dirt), so a hit
        # skips the per-node label/reserve derivation. Commits during
        # the solve refresh the LOCAL list only; the shared cache dict
        # is never mutated here.
        if existing_input_cache:
            self.existing_inputs = [
                existing_input_cache.get(_state_node_key(n))
                or self._existing_input(n)
                for n in self.state_nodes
            ]
        else:
            self.existing_inputs = [
                self._existing_input(n) for n in self.state_nodes
            ]

        # live reservation usage: nodes (incl. deleting — the instance
        # is held until gone) already launched against a reservation id
        # reduce how many more the solver may open
        # (scheduling/reservationmanager.go:28-110)
        self.reserved_in_use: dict[str, int] = {}
        for node in state_nodes:
            rid = node.labels().get(RESERVATION_ID_LABEL, "")
            if not rid and node.node_claim is not None:
                # a pinned claim that hasn't launched yet carries the
                # reservation only in its spec requirements — it must
                # still consume budget or back-to-back solves
                # overcommit the reservation
                for spec in node.node_claim.spec.requirements:
                    if spec.key == RESERVATION_ID_LABEL and spec.values:
                        rid = spec.values[0]
                        break
            if rid:
                self.reserved_in_use[rid] = self.reserved_in_use.get(rid, 0) + 1

        # total instances per reservation id (for per-pod path budget
        # checks; the batched path enforces the same budget in-kernel)
        self._rsv_capacity: dict[str, int] = {}
        for _, types in self.pools_with_types:
            for it in types:
                for o in it.offerings:
                    if o.is_reserved():
                        self._rsv_capacity[o.reservation_id] = max(
                            self._rsv_capacity.get(o.reservation_id, 0),
                            o.reservation_capacity,
                        )

        self.daemon_overhead = self.input_builder.daemon_overhead()
        self.topology = self._build_topology()

        # per-node host-port reservations from live pods
        # (hostportusage.go; consumed by the per-pod path)
        self._host_ports: dict[str, HostPortUsage] = {}
        for pod in self.cluster_pods:
            if pod.spec.node_name and pod_host_ports(pod):
                self._host_ports.setdefault(
                    pod.spec.node_name, HostPortUsage()
                ).add(pod)

        # per-node CSI volume-limit accounting (volumeusage.go;
        # existingnode.go:29-140): limits come from CSINode objects,
        # usage is seeded from live pods' PVC volumes
        self._volume_usage: dict[str, VolumeUsage] = {}
        if self.kube is not None:
            for csi in self.kube.csi_nodes():
                if csi.volume_limits:
                    self._volume_usage[csi.metadata.name] = VolumeUsage(
                        limits=csi.volume_limits
                    )
            if self._volume_usage:
                for pod in self.cluster_pods:
                    usage = self._volume_usage.get(pod.spec.node_name)
                    if usage is not None and pod.spec.volumes:
                        usage.add(pod, self.kube)

    # -- construction helpers -------------------------------------------------

    def _existing_input(self, node: StateNode) -> ExistingNodeInput:
        return self.input_builder.existing_input(node)

    def _note_gap(self, solution: Solution) -> None:
        """Feed the SLO engine's optimality SLI (metrics/slo.py) from
        the PROVISIONING fleet solve only: disruption simulations'
        candidate-subset solves carry gaps vs their own restricted LP
        estimates (routinely large on tiny sub-problems) that say
        nothing about fleet optimality, so they must not note."""
        if self.metrics_controller != "provisioner":
            return
        lp = solution.lp
        est = lp.get("estimate") if lp else None
        if est:
            from karpenter_tpu.metrics import slo

            slo.note("gap_vs_lp", solution.total_price / est - 1.0)

    # -- decision explainability (karpenter_tpu/explain) ----------------------

    def _explaining(self) -> bool:
        """True only for the LIVE provisioning solve with an explain
        record open: disruption simulations solve restricted
        sub-problems whose 'errors' are probe verdicts, not scheduling
        verdicts — they must not pollute pod explanations (the same
        controller gate the SLO optimality feed uses)."""
        if self.metrics_controller != "provisioner":
            return False
        from karpenter_tpu import explain

        return explain.active() is not None

    def _note_relax(self, pod: Pod, step: str) -> None:
        if self._explaining():
            from karpenter_tpu import explain

            explain.note_relax(pod.key, step)

    def _note_explanations(
        self, pods: Sequence[Pod], results: SchedulerResults
    ) -> None:
        if not results.errors or not self._explaining():
            return
        note_unschedulable_explanations(
            pods, results, self.pools_with_types, self.existing_inputs,
            self.daemon_overhead, self.reserved_in_use,
        )

    def _accept_solution(
        self, solution: Solution, open_plans: list, results: SchedulerResults,
        round_in_use: dict[str, int],
    ) -> None:
        """Fold a batched Solution into the round's results: accept
        new plans and commit existing-node assignments (keyed via
        _state_node_key so in-flight nodes key by claim name)."""
        self._accept_plans(
            solution.new_nodes, open_plans, results, round_in_use
        )
        for a in solution.existing:
            node = self.state_nodes[a.existing_index]
            results.existing_assignments.setdefault(
                _state_node_key(node), []
            ).extend(a.pods)
            for p in a.pods:
                self._commit_existing(a.existing_index, p)

    def _build_topology(self) -> Topology:
        # Domain discovery honors the POOL's own requirements
        # (topology.go:105-146): a pool restricted to two zones
        # contributes only those two as spread domains — otherwise the
        # skew floor counts zones no node could ever open in and
        # DoNotSchedule wedges.
        from karpenter_tpu.solver.encode import pool_template_requirements

        domains: dict[str, set[str]] = {}
        # per-domain taint provenance: one taint tuple per SOURCE
        # (pool template or live node) contributing the domain —
        # consumed by nodeTaintsPolicy=Honor spread constraints
        domain_taints: dict[str, dict[str, list]] = {}

        def record(key: str, value: str, taints) -> None:
            domains.setdefault(key, set()).add(value)
            domain_taints.setdefault(key, {}).setdefault(value, []).append(
                tuple(taints)
            )

        for pool, types in self.pools_with_types:
            pool_reqs = pool_template_requirements(pool)
            pool_taints = tuple(pool.spec.template.spec.taints)
            for it in types:
                for key in (TOPOLOGY_ZONE_LABEL, CAPACITY_TYPE_LABEL):
                    req = it.requirements.get(key)
                    if req.operator() == IN:
                        gate = pool_reqs.get(key)
                        for v in req.values:
                            if gate.has(v):
                                record(key, v, pool_taints)
        pod_domains: dict[str, dict[str, str]] = {}
        for node in self.state_nodes:
            labels = node.labels()
            node_taints = tuple(node.taints())
            for key, value in labels.items():
                record(key, value, node_taints)
            if node.name:
                record(HOSTNAME_LABEL, node.name, node_taints)
            for pod_key in node.pod_keys:
                mapping = {k: v for k, v in labels.items()}
                mapping[HOSTNAME_LABEL] = node.name
                pod_domains[pod_key] = mapping
        scheduled = [p for p in self.cluster_pods if p.spec.node_name]
        return Topology(domains=domains, cluster_pods=scheduled, pending_pods=[],
                        pod_domains=pod_domains,
                        honor_schedule_anyway=self.honor_preferences,
                        domain_taints=domain_taints)

    # -- solve ----------------------------------------------------------------

    def _timed_out(self) -> bool:
        if self._deadline is None:
            return False
        now = self.clock()
        # progress gauge for the in-flight solve (unfinished_work_
        # seconds), published at most once a second — this predicate
        # runs once per pod on the slow path and must stay a cheap
        # comparison
        if now - self._last_progress_publish >= 1.0:
            self._last_progress_publish = now
            self._publish_progress(now=now)
        return now > self._deadline

    def solve(self, pods: Sequence[Pod]) -> SchedulerResults:
        # scheduler-subsystem metrics wrap the whole solve, labeled by
        # controller so disruption SIMULATIONS never stomp the
        # provisioner's series (provisioning/scheduling/metrics.go:33-95
        # uses the same ControllerLabel disambiguation)
        labels = {"controller": self.metrics_controller}
        self._solve_start = self.clock()
        self._last_progress_publish = self._solve_start
        SCHEDULER_UNFINISHED_WORK.set(0.0, labels)
        results: Optional[SchedulerResults] = None
        from karpenter_tpu import tracing
        from karpenter_tpu.solver import resilience

        resilience.pop_degraded()  # scope the report to THIS solve
        try:
            with tracing.span(
                "scheduler.solve",
                controller=self.metrics_controller, pods=len(pods),
            ) as tsp:
                results = self._solve(pods)
                tsp.annotate(errors=len(results.errors))
            self._note_explanations(pods, results)
            return results
        finally:
            degraded = resilience.pop_degraded()
            if degraded:
                # the tick still decided — but through fallback rungs;
                # say so once per solve, not once per kernel call
                log.warning(
                    "%s solve served degraded via rung(s) %s "
                    "(see karpenter_solver_ladder_total)",
                    self.metrics_controller, sorted(set(degraded)),
                )
                if results is not None:
                    results.degraded_rungs = sorted(set(degraded))
            SCHEDULER_QUEUE_DEPTH.set(0.0, labels)
            SCHEDULER_UNFINISHED_WORK.set(0.0, labels)
            SCHEDULER_SCHEDULING_DURATION.observe(
                self.clock() - self._solve_start, labels
            )
            if results is not None:
                SCHEDULER_UNSCHEDULABLE_PODS.set(
                    float(len(results.errors)), labels
                )
            else:
                # the solve died: drop the series rather than leave a
                # count from a different run next to a fresh duration
                SCHEDULER_UNSCHEDULABLE_PODS.delete(labels)

    def _solve(self, pods: Sequence[Pod]) -> SchedulerResults:
        # best-effort wall-clock bound for the whole round
        # (provisioner.go:365-368); work completed before the deadline
        # is kept, pods not yet placed report TIMEOUT_ERROR
        self._deadline = self.clock() + self.solve_timeout
        if self.kube is not None:
            # PriorityClass resolution at every solve entry (the
            # volume-topology pattern): provisioning and disruption
            # simulations group pods by the same resolved priorities
            # no matter which caller stamped the pods last
            from karpenter_tpu.scheduling.priority import (
                resolve_pod_priorities,
            )

            resolve_pod_priorities(list(pods), self.kube)
        dra_rejected: list[Pod] = []
        if self.ignore_dra_requests:
            # DRA gate (scheduler.go:489-491): device allocation can't
            # be simulated, so these pods get a permanent error up
            # front — they never enter the solve and never relax
            from karpenter_tpu.utils.pod import has_dra_requirements

            kept = []
            for pod in pods:
                (dra_rejected if has_dra_requirements(pod) else kept).append(pod)
            pods = kept
        if self.kube is not None:
            # PVC zonal requirements re-derived HERE, at every solve
            # entry (provisioning and disruption simulation alike), so
            # results never depend on which caller stamped the shared
            # pod object last (volumetopology.go:51-160)
            for pod in pods:
                if pod.spec.volumes or pod.spec.injected_requirements:
                    volume_topology.inject(pod, self.kube)
        topology_full = Topology(
            domains=self.topology.domains,
            cluster_pods=[p for p in self.cluster_pods if p.spec.node_name],
            pending_pods=list(pods),
            pod_domains=self._pod_domains(),
            honor_schedule_anyway=self.honor_preferences,
            domain_taints=self.topology.domain_taints,
        )
        simple: list[Pod] = []
        complex_: list[Pod] = []
        volume_limited: list[Pod] = []
        limited_drivers = {
            d for usage in self._volume_usage.values() for d in usage.limits
        }
        for pod in pods:
            # CSI attach limits are per unique volume per node — only
            # the per-pod path tracks them (the reference enforces
            # them on existing nodes only, existingnode.go:29-140);
            # route per-pod only when the pod's drivers are actually
            # limited somewhere
            if (
                limited_drivers
                and pod.spec.volumes
                and limited_drivers & pod_volume_drivers(pod, self.kube).keys()
            ):
                volume_limited.append(pod)
            elif topology_full.has_constraints(pod) or pod_host_ports(pod):
                complex_.append(pod)
            else:
                simple.append(pod)

        results = SchedulerResults(new_node_plans=[], existing_assignments={})
        for pod in dra_rejected:
            results.errors[pod.key] = DRA_ERROR
        # queue depth counts pods actually entering the solve (gated
        # pods never wait); drained at phase boundaries
        self._publish_progress(
            len(simple) + len(complex_) + len(volume_limited)
        )

        # reservation budget for THIS round: live usage plus every plan
        # opened during the round, batched or per-pod, so later
        # placements (retries, complex pods) never re-grant budget a
        # sibling plan already consumed (reservationmanager.go debits
        # across all in-flight nodeclaims of one scheduling run)
        round_in_use: dict[str, int] = dict(self.reserved_in_use)

        # fast path: one batched solve on device
        open_plans: list[NodePlan] = []
        if simple:
            solution = self._batched_solve(simple, reserved_in_use=round_in_use)
            self._note_gap(solution)
            self._accept_solution(solution, open_plans, results, round_in_use)

            # k-way-evicted pods are schedulable alone: re-solve them
            # in BATCHES (same-group pods stay co-placed) until none
            # remain — every pass admits at least its first group, so
            # the loop shrinks; kernel-infeasible stragglers fall
            # through to the relaxation ladder below
            evicted_keys = {p.key for p in solution.evicted}
            evicted = list(solution.evicted)
            still_failed: list[Pod] = []
            rounds = 0
            while evicted and rounds < 16 and not self._timed_out():
                retry = self._batched_solve(
                    evicted, reserved_in_use=round_in_use
                )
                self._accept_solution(
                    retry, open_plans, results, round_in_use
                )
                re_evicted = {p.key for p in retry.evicted}
                still_failed.extend(
                    p for p in retry.unschedulable
                    if p.key not in re_evicted
                )
                evicted = list(retry.evicted)
                rounds += 1
            still_failed.extend(evicted)  # bound hit / timed out

            pending = [
                p for p in solution.unschedulable
                if p.key not in evicted_keys
            ] + still_failed
            # the fast path drained: what's left is the retry backlog
            # plus the slower paths
            self._publish_progress(
                len(pending) + len(complex_) + len(volume_limited)
            )
            for pod in pending:
                retried = False
                if self._timed_out():
                    results.errors[pod.key] = TIMEOUT_ERROR
                    continue
                if self.honor_preferences:
                    relaxed = relax(pod)
                    if relaxed:
                        self._note_relax(pod, relaxed)
                        retry = self._batched_solve(
                            [pod], required_only=True,
                            reserved_in_use=round_in_use,
                        )
                        if not retry.unschedulable:
                            self._accept_solution(
                                retry, open_plans, results, round_in_use
                            )
                            retried = True
                            if self._explaining():
                                from karpenter_tpu import explain

                                explain.note_pod(
                                    pod.key, verdict="scheduled-after-relax",
                                    relax_unlocked=relaxed,
                                )
                if not retried:
                    results.errors[pod.key] = NO_CAPACITY_ERROR
            for plan in open_plans:
                for pod in plan.pods:
                    topology_full.register(
                        pod, self._plan_domains(plan),
                        source_taints=tuple(plan.pool.spec.template.spec.taints),
                    )

        # topology path: lower spread/affinity/ports to solver-native
        # form (domain pins + per-node caps + group conflicts) and run
        # ONE batched device solve; only what the lowering cannot
        # express falls back to the per-pod loop (solver/topo_batch.py)
        deferred: list[Pod] = []
        if complex_ and self._timed_out():
            for pod in complex_:
                results.errors[pod.key] = TIMEOUT_ERROR
            complex_ = []
        if complex_:
            # open fast-path plans join the solve as pseudo-existing
            # nodes (in-flight NodeClaim model) so constrained pods can
            # share them instead of opening fresh capacity
            plan_refs: list[NodePlan] = []
            plan_inputs: list[ExistingNodeInput] = []
            for plan in open_plans:
                inp = self._plan_input(plan)
                if inp is not None:
                    plan_refs.append(plan)
                    plan_inputs.append(inp)
            existing_all = list(self.existing_inputs) + plan_inputs
            tb = topo_batch.prepare(
                complex_, topology_full, existing_all, self._host_ports
            )
            results.errors.update(tb.errors)
            deferred = list(tb.fallback)
            if tb.groups:
                enc = encode(
                    tb.groups,
                    self.pools_with_types,
                    existing_all,
                    self.daemon_overhead,
                    reserved_in_use=round_in_use,
                    group_cap=tb.group_cap,
                    conflict=tb.conflict,
                    existing_quota=tb.existing_quota,
                    compat_cache=self.compat_cache,
                )
                solution = solve_encoded(enc)
                n_before = len(open_plans)
                self._accept_plans(
                    solution.new_nodes, open_plans, results, round_in_use
                )
                E = len(self.existing_inputs)
                for a in solution.existing:
                    inp = existing_all[a.existing_index]
                    if a.existing_index >= E:
                        # pods joined an open fast-path plan: narrow its
                        # options to types that hold the enlarged pod
                        # set and admit the new pods' requirements (the
                        # in-flight NodeClaim re-filter,
                        # nodeclaim.go:373-447)
                        plan = plan_refs[a.existing_index - E]
                        used = resutil.merge(
                            self.daemon_overhead.get(plan.pool.metadata.name, {}),
                            resutil.requests_for_pods(plan.pods + a.pods),
                        )
                        joined_reqs = [Requirements.from_pod(p) for p in a.pods]
                        fitting = [
                            it for it in plan.instance_types
                            if resutil.fits(used, it.allocatable)
                            and all(
                                it.requirements.intersects(r) is None
                                for r in joined_reqs
                            )
                        ]
                        if not fitting:
                            deferred.extend(a.pods)
                            continue
                        plan.instance_types = fitting
                        plan.offerings = [
                            o for o in plan.offerings
                            if any(it.offerings and o in it.offerings for it in fitting)
                        ] or plan.offerings
                        plan.pods.extend(a.pods)
                        domains = self._plan_domains(plan)
                        for p in a.pods:
                            self._register_topo_pod(
                                p, domains, inp.name, tb, topology_full
                            )
                        continue
                    node = self.state_nodes[a.existing_index]
                    results.existing_assignments.setdefault(
                        inp.name, []
                    ).extend(a.pods)
                    labels = dict(node.labels())
                    labels[HOSTNAME_LABEL] = inp.name
                    for p in a.pods:
                        self._commit_existing(a.existing_index, p)
                        self._register_topo_pod(p, labels, inp.name, tb, topology_full)
                for plan in open_plans[n_before:]:
                    domains = self._plan_domains(plan)
                    for p in plan.pods:
                        self._register_topo_pod(
                            p, domains, f"planned-{id(plan)}", tb, topology_full
                        )
                deferred.extend(solution.unschedulable)

        # slow path: per-pod with topology + volume-limit filtering
        deferred.extend(volume_limited)
        self._publish_progress(len(deferred))
        if deferred:
            self._solve_complex(
                deferred, open_plans, topology_full, results, round_in_use
            )

        for plan in open_plans:
            self._finalize_plan(plan)
            if not self._enforce_min_values(plan, results):
                continue
            results.new_node_plans.append(plan)
        self._enforce_spot_budget(results.new_node_plans)
        return results

    def _enforce_spot_budget(self, plans: list[NodePlan]) -> None:
        """Per-pool spot availability targets over the WHOLE round's
        plans plus the live fleet: with a max-spot-fraction cap or a
        min-on-demand floor configured, plans that would resolve to a
        spot launch (their cheapest surviving offering is spot) are
        pinned off spot — spot offerings dropped, so the claim's
        capacity-type requirement and the provider's launch resolve to
        the cheapest surviving non-spot offering (on-demand, or
        reserved where one applies) — until the targets hold. Plans whose pods REQUIRE spot (no
        on-demand offering survived the solve) can never be pinned;
        they consume the budget first and any residual violation is
        logged. Later-opened plans pin first (deterministic, and the
        earlier plans carry the round's first-placed pods)."""
        from karpenter_tpu.metrics.store import SPOT_BUDGET_PINNED

        by_pool: dict[str, list[NodePlan]] = {}
        for plan in plans:
            by_pool.setdefault(plan.pool.metadata.name, []).append(plan)
        for pool_name, pool_plans in by_pool.items():
            frac, od_floor = pool_spot_budget(pool_plans[0].pool)
            if frac >= 1.0 and od_floor <= 0:
                continue
            existing_spot = existing_other = 0
            for node in self.state_nodes:
                if node.nodepool_name() != pool_name or node.deleting():
                    continue
                ct = node.labels().get(CAPACITY_TYPE_LABEL, "")
                if ct == CAPACITY_TYPE_SPOT:
                    existing_spot += 1
                elif ct:
                    existing_other += 1

            def _resolves_spot(plan: NodePlan) -> bool:
                if not plan.offerings:
                    return False
                cheapest = min(plan.offerings, key=lambda o: o.price)
                return cheapest.capacity_type == CAPACITY_TYPE_SPOT

            spot_plans = [p for p in pool_plans if _resolves_spot(p)]
            total = existing_spot + existing_other + len(pool_plans)
            n_spot = existing_spot + len(spot_plans)
            n_od = total - n_spot
            need, cause = 0, ""
            # epsilon before truncating: 0.7 * 10 is 6.999999999999999
            # in binary floats, and a bare int() would pin one plan
            # that is legitimately within budget
            spot_cap = int(frac * total + 1e-9)
            if frac < 1.0 and n_spot > spot_cap:
                need, cause = n_spot - spot_cap, "max-spot-fraction"
            if od_floor > 0 and n_od < min(od_floor, total):
                if min(od_floor, total) - n_od > need:
                    need, cause = (
                        min(od_floor, total) - n_od, "min-on-demand-floor"
                    )
            if need <= 0:
                continue
            for plan in reversed(spot_plans):
                if need <= 0:
                    break
                od = [o for o in plan.offerings if not o.is_spot()]
                if not od:
                    continue  # pods demand spot; budget can't touch it
                plan.offerings = od
                kept_types = [
                    it for it in plan.instance_types
                    if any(o in it.offerings for o in od)
                ]
                if kept_types:
                    plan.instance_types = kept_types
                plan.price = min(o.price for o in plan.offerings)
                need -= 1
                SPOT_BUDGET_PINNED.inc(
                    {"nodepool": pool_name, "cause": cause}
                )
            if need > 0:
                if spot_plans:
                    log.warning(
                        "spot budget for pool %s unsatisfiable: %d "
                        "planned spot nodes have no on-demand offering "
                        "(pods pin capacity-type=spot)", pool_name, need,
                    )
                else:
                    # nothing in this round to pin: the EXISTING fleet
                    # already exceeds the budget (e.g. the knob was
                    # tightened); attrition/consolidation retires the
                    # excess, provisioning cannot
                    log.warning(
                        "spot budget for pool %s: existing fleet is %d "
                        "node(s) over budget; new plans already comply",
                        pool_name, need,
                    )

    def _enforce_min_values(self, plan: NodePlan, results: SchedulerResults) -> bool:
        """minValues flexibility floor per planned node
        (types.go:284-318; relaxation annotation scheduler.go:649-658).
        The floors are checked against the TIGHTENED requirement set —
        pool requirements intersected with the scheduled pods' own —
        exactly as the reference filters with nodeClaimRequirements
        (nodeclaim.go:146,425-433): a pod selector can shrink a pool's
        In set below its floor even when the raw pool requirements
        remain satisfiable.
        Strict: such a plan is rejected and its pods report the reason.
        BestEffort: the plan survives, marked relaxed so serialization
        lowers the floors to the satisfiable count and the claim gets
        the min-values-relaxed annotation."""
        pool_reqs = _pool_requirements(plan.pool)
        if not pool_reqs.has_min_values():
            return True
        tightened = Requirements(r.copy() for r in pool_reqs)
        for pod in plan.pods:
            tightened.add(*Requirements.from_pod(pod, required_only=True))
        _, err = satisfies_min_values(plan.instance_types, tightened)
        if err is None:
            return True
        if self.min_values_policy == "BestEffort":
            plan.min_values_relaxed = True
            return True
        for pod in plan.pods:
            results.errors[pod.key] = f"{MIN_VALUES_ERROR_PREFIX}: {err}"
        return False

    def _pod_domains(self) -> dict[str, dict[str, str]]:
        out: dict[str, dict[str, str]] = {}
        for node in self.state_nodes:
            labels = node.labels()
            for pod_key in node.pod_keys:
                mapping = dict(labels)
                mapping[HOSTNAME_LABEL] = node.name
                out[pod_key] = mapping
        return out

    def _publish_progress(
        self, queue_depth: Optional[int] = None,
        now: Optional[float] = None,
    ) -> None:
        """Publish the in-flight solve's progress gauges. Called at
        phase boundaries (device solves are single blocking calls, so
        their interior cannot be sampled without a watcher thread —
        the gauges reflect the last boundary). `now` lets callers that
        already read the clock avoid a second read (stepping fake
        clocks would otherwise advance per publish)."""
        labels = {"controller": self.metrics_controller}
        SCHEDULER_UNFINISHED_WORK.set(
            (self.clock() if now is None else now) - self._solve_start,
            labels,
        )
        if queue_depth is not None:
            SCHEDULER_QUEUE_DEPTH.set(float(queue_depth), labels)

    def _batched_solve(
        self,
        pods: Sequence[Pod],
        required_only: bool = False,
        reserved_in_use: Optional[dict[str, int]] = None,
    ) -> Solution:
        self._publish_progress()
        groups = group_pods(pods, required_only=required_only)
        enc = encode(
            groups,
            self.pools_with_types,
            self.existing_inputs,
            self.daemon_overhead,
            reserved_in_use=(
                reserved_in_use if reserved_in_use is not None
                else self.reserved_in_use
            ),
            compat_cache=self.compat_cache,
        )
        return solve_encoded(enc, objective=self.objective)

    def _rsv_remaining(self, rid: str, round_in_use: dict[str, int]) -> int:
        """Instances left on a reservation after live nodes AND plans
        opened earlier in this scheduling round (reservationmanager.go
        debits across all in-flight nodeclaims of a run)."""
        return self._rsv_capacity.get(rid, 0) - round_in_use.get(rid, 0)

    @staticmethod
    def _debit_reservations(plans: Sequence[NodePlan], round_in_use: dict[str, int]) -> None:
        for plan in plans:
            if plan.reservation_id:
                round_in_use[plan.reservation_id] = (
                    round_in_use.get(plan.reservation_id, 0) + 1
                )

    def _accept_plans(
        self,
        new_nodes: Sequence[NodePlan],
        open_plans: list[NodePlan],
        results: SchedulerResults,
        round_in_use: dict[str, int],
    ) -> None:
        """Admit a batched solution's planned nodes into the round:
        Strict minValues rejects a plan BEFORE its pods enter the
        topology tracker (phantom pods would skew spread/anti-affinity
        for the rest of the round), and survivors debit the round's
        reservation budget exactly once."""
        kept = [
            plan for plan in new_nodes if self._enforce_min_values(plan, results)
        ]
        self._debit_reservations(kept, round_in_use)
        open_plans.extend(kept)

    def _commit_existing(self, idx: int, pod: Pod) -> None:
        node = self.state_nodes[idx]
        usage = resutil.pod_requests(pod)
        node.pod_usage = resutil.merge(node.pod_usage, usage)
        node.pod_keys.add(pod.key)
        # refresh solver input for subsequent passes
        self.existing_inputs[idx] = self._existing_input(node)

    def _register_topo_pod(
        self, pod: Pod, base_domains: dict[str, str], host_port_key: str,
        tb, topology: Topology,
    ) -> None:
        """Commit one lowered-solve placement into the round's topology
        tracker and host-port ledger (assignment domains override the
        node's representative ones)."""
        chosen = dict(base_domains)
        chosen.update(tb.assignments.get(pod.key, {}))
        topology.register(pod, chosen)
        if pod_host_ports(pod):
            self._host_ports.setdefault(host_port_key, HostPortUsage()).add(pod)

    def _plan_input(self, plan: NodePlan) -> Optional[ExistingNodeInput]:
        return plan_pseudo_input(plan, self.daemon_overhead)

    def _plan_domains(self, plan: NodePlan) -> dict[str, str]:
        return plan_domains(plan)

    # -- slow path ------------------------------------------------------------

    def _solve_complex(
        self,
        pods: Sequence[Pod],
        open_plans: list[NodePlan],
        topology: Topology,
        results: SchedulerResults,
        round_in_use: dict[str, int],
    ) -> None:
        """Per-pod scheduling with topology domain filtering.

        Pods in FFD order; each pod tries existing nodes, open plans,
        then a new node, honoring the Topology's allowed domains. On
        failure the preference ladder relaxes the pod and retries
        (scheduler.go:456 + preferences.go).
        """
        ordered = sorted(
            pods,
            key=lambda p: -(
                resutil.pod_requests(p).get("cpu", 0.0)
                + resutil.pod_requests(p).get("memory", 0.0) / 2**32
            ),
        )
        for pod in ordered:
            if self._timed_out():
                results.errors[pod.key] = TIMEOUT_ERROR
                continue
            last_step: Optional[str] = None
            for _ in range(8):  # relaxation ladder bound
                if self._try_place(pod, open_plans, topology, results, round_in_use):
                    if last_step is not None and self._explaining():
                        # the ladder unlocked this placement: say
                        # which rung did it
                        from karpenter_tpu import explain

                        explain.note_pod(
                            pod.key, verdict="scheduled-after-relax",
                            relax_unlocked=last_step,
                        )
                    break
                topology.invalidate(pod.key)  # relax() mutates the pod
                step = relax(pod) if self.honor_preferences else None
                if not step:
                    results.errors[pod.key] = TOPOLOGY_INCOMPATIBLE_ERROR
                    break
                last_step = step
                self._note_relax(pod, step)

    def _try_place(
        self,
        pod: Pod,
        open_plans: list[NodePlan],
        topology: Topology,
        results: SchedulerResults,
        round_in_use: dict[str, int],
    ) -> bool:
        pod_reqs = Requirements.from_pod(pod)
        requests = resutil.pod_requests(pod)

        # 1) existing nodes
        for idx, node in enumerate(self.state_nodes):
            inp = self.existing_inputs[idx]
            if node.deleting():
                continue
            if tolerates_pod(list(inp.taints), pod) is not None:
                continue
            if not inp.requirements.is_compatible(
                pod_reqs, allow_undefined=WELL_KNOWN_LABELS
            ):
                continue
            if not resutil.fits(requests, inp.available):
                continue
            if pod_host_ports(pod):
                # keyed by inp.name: an in-flight node has no Node yet,
                # so node.name is "" and unnamed nodes would share (and
                # falsely conflict in) one bucket
                usage = self._host_ports.setdefault(inp.name, HostPortUsage())
                if usage.conflict(pod) is not None:
                    continue
            if pod.spec.volumes:
                # CSI attach limits on the existing node
                # (existingnode.go:29-140, volumeusage.go)
                vusage = self._volume_usage.get(inp.name)
                if vusage is not None and vusage.exceeds_limits(pod, self.kube):
                    continue
            labels = node.labels()
            candidate = {k: {v} for k, v in labels.items()}
            candidate[HOSTNAME_LABEL] = {inp.name}
            allowed = topology.allowed_domains_for_pod(pod, candidate)
            if allowed is None:
                continue
            self._commit_existing(idx, pod)
            if pod_host_ports(pod):
                self._host_ports[inp.name].add(pod)
            if pod.spec.volumes and inp.name in self._volume_usage:
                self._volume_usage[inp.name].add(pod, self.kube)
            results.existing_assignments.setdefault(inp.name, []).append(pod)
            topology.register(pod, {k: next(iter(v)) for k, v in allowed.items() if v})
            return True

        # 2) open planned nodes
        for plan in open_plans:
            if pod_host_ports(pod):
                # port check first: _plan_can_add narrows the plan's
                # type options as a side effect of admission
                usage = self._host_ports.setdefault(
                    f"planned-{id(plan)}", HostPortUsage()
                )
                if usage.conflict(pod) is not None:
                    continue
            if not self._plan_can_add(plan, pod, pod_reqs, requests, topology):
                continue
            if pod_host_ports(pod):
                self._host_ports[f"planned-{id(plan)}"].add(pod)
            plan.pods.append(pod)
            topology.register(
                pod, self._plan_domains(plan),
                source_taints=tuple(plan.pool.spec.template.spec.taints),
            )
            return True

        # 3) new node — permanent template taints only; startupTaints
        # clear before pods run (same rule as build_configs)
        for pool, types in self.pools_with_types:
            taints = tuple(pool.spec.template.spec.taints)
            if tolerates_pod(list(taints), pod) is not None:
                continue
            # the pool's OWN template requirements (labels included)
            # filter which types and offerings may launch under it —
            # exactly as build_configs does for the batched path;
            # without it this path can plan a node in a zone the pool
            # forbids
            from karpenter_tpu.solver.encode import pool_template_requirements

            pool_reqs = pool_template_requirements(pool)
            fitting = []
            for it in types:
                if it.requirements.intersects(pod_reqs) is not None:
                    continue
                if pool_reqs.intersects(it.requirements) is not None:
                    continue
                overhead = self.daemon_overhead.get(pool.metadata.name, {})
                need = resutil.merge(requests, overhead)
                if not resutil.fits(need, it.allocatable):
                    continue
                offerings = [
                    o
                    for o in it.offerings.available().compatible(pod_reqs)
                    if pool_reqs.intersects(o.requirements) is None
                ]
                if not offerings:
                    continue
                fitting.append((it, offerings))
            if not fitting:
                continue
            zones = {o.zone for _, offs in fitting for o in offs}
            candidate = {
                TOPOLOGY_ZONE_LABEL: zones,
                CAPACITY_TYPE_LABEL: {
                    o.capacity_type for _, offs in fitting for o in offs
                },
                HOSTNAME_LABEL: {f"planned-new-{id(pod)}"},
                NODEPOOL_LABEL: {pool.metadata.name},
            }
            for key, value in pool.spec.template.labels.items():
                candidate.setdefault(key, {value})
            allowed = topology.allowed_domains_for_pod(pod, candidate)
            if allowed is None:
                continue
            allowed_zones = allowed.get(TOPOLOGY_ZONE_LABEL, zones)
            allowed_cts = allowed.get(
                CAPACITY_TYPE_LABEL, candidate[CAPACITY_TYPE_LABEL]
            )
            chosen_types = []
            chosen_offerings = []
            for it, offs in fitting:
                offs2 = [
                    o for o in offs
                    if o.zone in allowed_zones and o.capacity_type in allowed_cts
                    # a reserved offering only stays on the menu while
                    # its reservation has budget left this round —
                    # otherwise N per-pod plans could each pin the
                    # near-free reservation past its instance count
                    and (
                        not o.is_reserved()
                        or self._rsv_remaining(o.reservation_id, round_in_use) > 0
                    )
                ]
                if offs2:
                    chosen_types.append(it)
                    chosen_offerings.extend(offs2)
            if not chosen_types:
                continue
            if self.min_values_policy != "BestEffort":
                # Strict minValues checked at creation: a failing plan
                # would otherwise be rejected after its pod already
                # registered into the topology tracker
                pool_reqs = _pool_requirements(pool)
                if pool_reqs.has_min_values():
                    _, mv_err = satisfies_min_values(chosen_types, pool_reqs)
                    if mv_err is not None:
                        continue
            chosen_offerings.sort(key=lambda o: o.price)
            plan = NodePlan(
                pool=pool,
                instance_types=order_by_price(chosen_types, pod_reqs),
                offerings=chosen_offerings,
                pods=[pod],
                price=chosen_offerings[0].price,
            )
            if chosen_offerings[0].is_reserved():
                plan.reservation_id = chosen_offerings[0].reservation_id
                self._debit_reservations([plan], round_in_use)
            open_plans.append(plan)
            if pod_host_ports(pod):
                usage = HostPortUsage()
                usage.add(pod)
                self._host_ports[f"planned-{id(plan)}"] = usage
            topology.register(
                pod, self._plan_domains(plan),
                source_taints=tuple(plan.pool.spec.template.spec.taints),
            )
            return True
        return False

    def _plan_can_add(self, plan: NodePlan, pod: Pod, pod_reqs: Requirements,
                      requests, topology: Topology) -> bool:
        # permanent template taints only (startupTaints never gate
        # placement; see build_configs)
        taints = tuple(plan.pool.spec.template.spec.taints)
        if tolerates_pod(list(taints), pod) is not None:
            return False
        overhead = self.daemon_overhead.get(plan.pool.metadata.name, {})
        used = resutil.merge(
            overhead, resutil.requests_for_pods(plan.pods), requests
        )
        remaining_types = [
            it
            for it in plan.instance_types
            if it.requirements.intersects(pod_reqs) is None
            and resutil.fits(used, it.allocatable)
        ]
        if not remaining_types:
            return False
        candidate = {k: {v} for k, v in self._plan_domains(plan).items()}
        allowed = topology.allowed_domains_for_pod(pod, candidate)
        if allowed is None:
            return False
        plan.instance_types = remaining_types
        names = {it.name for it in remaining_types}
        plan.offerings = [
            o for o in plan.offerings if any(
                it.offerings and o in it.offerings for it in remaining_types
            )
        ] or plan.offerings
        return True

    # -- finalize -------------------------------------------------------------

    def _finalize_plan(self, plan: NodePlan) -> None:
        finalize_plan(plan)
