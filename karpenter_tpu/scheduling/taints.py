"""Taint toleration checking and merging.

Counterpart of pkg/scheduling/taints.go: `tolerates` returns the first
untolerated taint (None = all tolerated); `merge` unions by
(key, effect) match; `KNOWN_EPHEMERAL_TAINTS` are ignored on
uninitialized managed nodes.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

from karpenter_tpu.apis.v1.labels import UNREGISTERED_NO_EXECUTE_TAINT
from karpenter_tpu.kube.objects import Pod, Taint, Toleration

# Taints expected on a node while it's initializing; ignored for
# scheduling against uninitialized managed nodes (taints.go:36-43).
KNOWN_EPHEMERAL_TAINTS: tuple[Taint, ...] = (
    Taint(key="node.kubernetes.io/not-ready", effect="NoSchedule"),
    Taint(key="node.kubernetes.io/not-ready", effect="NoExecute"),
    Taint(key="node.kubernetes.io/unreachable", effect="NoSchedule"),
    Taint(key="node.cloudprovider.kubernetes.io/uninitialized", value="true", effect="NoSchedule"),
    UNREGISTERED_NO_EXECUTE_TAINT,
)


def tolerates(taints: Sequence[Taint], tolerations: Sequence[Toleration]) -> Optional[str]:
    """None if every taint is tolerated, else a message naming the first offender.

    PreferNoSchedule taints never block scheduling (k8s semantics; the
    preference ladder separately *tries* to avoid them).
    """
    for taint in taints:
        if taint.effect == "PreferNoSchedule":
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return f"did not tolerate taint {taint.key}={taint.value}:{taint.effect}"
    return None


def tolerates_pod(taints: Sequence[Taint], pod: Pod) -> Optional[str]:
    return tolerates(taints, pod.spec.tolerations)


def merge(taints: Sequence[Taint], with_taints: Iterable[Taint]) -> list[Taint]:
    """Union keeping the receiver's taints on (key, effect) conflicts."""
    out = list(taints)
    for taint in with_taints:
        if not any(t.key == taint.key and t.effect == taint.effect for t in out):
            out.append(taint)
    return out


def is_ephemeral(taint: Taint) -> bool:
    return any(
        taint.key == known.key and taint.effect == known.effect
        for known in KNOWN_EPHEMERAL_TAINTS
    )


def filter_ephemeral(taints: Sequence[Taint]) -> list[Taint]:
    return [t for t in taints if not is_ephemeral(t)]
