"""Pod priority resolution from PriorityClass objects.

On a real cluster the priority admission plugin stamps
`pod.spec.priority` from `priorityClassName` at create time
(kube-apiserver, plugin/pkg/admission/priority). This substrate has no
admission chain, so the provisioner resolves priorities at intake —
and the Scheduler re-resolves at every solve entry (the
volume-topology pattern) so disruption simulations and scripted solves
see the same numbers no matter which caller stamped last.

Rules, mirroring the admission plugin:

- an already-stamped nonzero `spec.priority` wins (the pod was
  admitted with it; re-resolution must not flip it);
- `priorityClassName` resolves to that class's value; a dangling name
  is logged and left at 0 (admission would have rejected the pod —
  here it must not take the tick down);
- with no class name, the cluster's global-default class applies
  (highest value wins if several are marked default — k8s admission
  forbids that state, this substrate just needs a deterministic pick);
- otherwise 0.

The two built-in system classes are known without cluster objects.
"""

from __future__ import annotations

import logging
from typing import Iterable, Optional, Sequence

from karpenter_tpu.kube.objects import Pod, PriorityClass

log = logging.getLogger("karpenter.priority")

# built-in classes every cluster has (k8s bootstraps them)
SYSTEM_CLASSES = {
    "system-cluster-critical": 2_000_000_000,
    "system-node-critical": 2_000_001_000,
}

PREEMPT_LOWER_PRIORITY = "PreemptLowerPriority"
PREEMPT_NEVER = "Never"


def class_map(classes: Iterable[PriorityClass]) -> dict[str, PriorityClass]:
    return {c.metadata.name: c for c in classes}


def default_class(
    classes: Iterable[PriorityClass],
) -> Optional[PriorityClass]:
    """The cluster's global-default class; ties (an invalid state a
    real apiserver rejects) break on (value, name) for determinism."""
    defaults = [c for c in classes if c.global_default]
    if not defaults:
        return None
    return max(defaults, key=lambda c: (c.value, c.metadata.name))


def resolve_priority(
    pod: Pod, classes: dict[str, PriorityClass],
    default: Optional[PriorityClass] = None,
) -> int:
    """The priority this pod schedules at (does not mutate the pod)."""
    if pod.spec.priority:
        return pod.spec.priority
    name = pod.spec.priority_class_name
    if name:
        if name in SYSTEM_CLASSES:
            return SYSTEM_CLASSES[name]
        cls = classes.get(name)
        if cls is None:
            log.warning(
                "pod %s references unknown PriorityClass %r; "
                "scheduling at priority 0", pod.key, name,
            )
            return 0
        return cls.value
    return default.value if default is not None else 0


def resolve_pod_priorities(pods: Sequence[Pod], kube) -> None:
    """Stamp `spec.priority` in place for every pod whose class name
    (or the cluster default) resolves — the admission-plugin analogue,
    run at provisioner intake and at every Scheduler solve entry. The
    stamp is idempotent: a nonzero priority is never overwritten."""
    if kube is None or not pods:
        return
    classes = class_map(kube.list("PriorityClass"))
    if not classes and not any(
        p.spec.priority_class_name for p in pods
    ):
        return
    default = default_class(classes.values())
    for pod in pods:
        if pod.spec.priority:
            continue
        value = resolve_priority(pod, classes, default)
        if value:
            pod.spec.priority = value


def preemption_allowed(
    pod: Pod, classes: dict[str, PriorityClass]
) -> bool:
    """Whether this pod's class permits nominating victims
    (preemptionPolicy: Never pods queue above lower priorities but
    never evict them)."""
    name = pod.spec.priority_class_name
    if not name or name in SYSTEM_CLASSES:
        return True
    cls = classes.get(name)
    return cls is None or cls.preemption_policy != PREEMPT_NEVER
