"""Per-node CSI volume-limit accounting.

Counterpart of pkg/scheduling/volumeusage.go: each node supports a
bounded number of attached volumes per CSI driver; pods referencing
PVCs consume slots keyed by the storage class' provisioner. Volume
counting is by unique volume (a PVC shared by two pods counts once).
"""

from __future__ import annotations

from typing import Optional, TYPE_CHECKING

from karpenter_tpu.kube.objects import Pod

if TYPE_CHECKING:  # pragma: no cover
    from karpenter_tpu.kube.client import KubeClient


def pod_volume_drivers(pod: Pod, kube: "Optional[KubeClient]") -> dict[str, set[str]]:
    """driver -> unique volume ids consumed by this pod."""
    out: dict[str, set[str]] = {}
    if kube is None:
        return out
    for vol in pod.spec.volumes:
        pvc_name = vol.pvc_name
        if vol.ephemeral:
            pvc_name = f"{pod.metadata.name}-{vol.name}"
        if not pvc_name:
            continue
        pvc = kube.get_pvc(pod.metadata.namespace, pvc_name)
        if pvc is None:
            continue
        sc_name = pvc.spec.storage_class_name
        driver = "kubernetes.io/no-provisioner"
        if sc_name:
            sc = kube.get_storage_class(sc_name)
            if sc is not None:
                driver = sc.provisioner
        volume_id = pvc.spec.volume_name or f"pvc:{pvc.key}"
        out.setdefault(driver, set()).add(volume_id)
    return out


class VolumeUsage:
    """Tracks attached volumes per driver on one node."""

    def __init__(self, limits: Optional[dict[str, int]] = None):
        self._volumes: dict[str, set[str]] = {}
        self.limits = dict(limits or {})

    def exceeds_limits(self, pod: Pod, kube: "Optional[KubeClient]") -> Optional[str]:
        for driver, vols in pod_volume_drivers(pod, kube).items():
            limit = self.limits.get(driver)
            if limit is None:
                continue
            combined = self._volumes.get(driver, set()) | vols
            if len(combined) > limit:
                return f"would exceed volume limit for CSI driver {driver} ({limit})"
        return None

    def add(self, pod: Pod, kube: "Optional[KubeClient]") -> None:
        for driver, vols in pod_volume_drivers(pod, kube).items():
            self._volumes.setdefault(driver, set()).update(vols)

    def copy(self) -> "VolumeUsage":
        out = VolumeUsage(self.limits)
        out._volumes = {k: set(v) for k, v in self._volumes.items()}
        return out
