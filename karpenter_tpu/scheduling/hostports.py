"""Per-node host-port conflict tracking.

Counterpart of pkg/scheduling/hostportusage.go: pods requesting host
ports conflict when (hostIP, port, protocol) overlap on one node
(0.0.0.0 conflicts with everything).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.kube.objects import Pod


@dataclass(frozen=True)
class HostPort:
    ip: str
    port: int

    def conflicts(self, other: "HostPort") -> bool:
        if self.port != other.port:
            return False
        return self.ip == other.ip or self.ip == "0.0.0.0" or other.ip == "0.0.0.0"


def pod_host_ports(pod: Pod) -> list[HostPort]:
    out = []
    for container in list(pod.spec.containers) + list(pod.spec.init_containers):
        for port in container.ports:
            out.append(HostPort(ip=container.host_ip or "0.0.0.0", port=port))
    return out


class HostPortUsage:
    """Tracks host ports reserved on one (planned or real) node."""

    def __init__(self) -> None:
        self._reserved: dict[str, list[HostPort]] = {}  # pod key -> ports

    def conflict(self, pod: Pod) -> Optional[str]:
        wanted = pod_host_ports(pod)
        for ports in self._reserved.values():
            for existing in ports:
                for want in wanted:
                    if want.conflicts(existing):
                        return f"host port {want.port} conflicts with existing pod"
        return None

    def add(self, pod: Pod) -> None:
        self._reserved[pod.key] = pod_host_ports(pod)

    def remove(self, pod_key: str) -> None:
        self._reserved.pop(pod_key, None)

    def copy(self) -> "HostPortUsage":
        out = HostPortUsage()
        out._reserved = {k: list(v) for k, v in self._reserved.items()}
        return out
