"""Requirements: a keyed set of Requirement with intersection algebra.

Behavioral counterpart of pkg/scheduling/requirements.go: Add tightens
by intersection, Compatible enforces the custom-label "must be defined"
rule (well-known labels exempt), Intersects applies the
NotIn/DoesNotExist leniency. Pod conversion mirrors NewPodRequirements
(heaviest preferred term treated as required; first required term
selected — the relaxation ladder peels these off on failure).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional

from karpenter_tpu.apis.v1.labels import WELL_KNOWN_LABELS, is_restricted_node_label
from karpenter_tpu.kube.objects import NodeSelectorRequirement, Pod
from karpenter_tpu.scheduling.requirement import (
    DOES_NOT_EXIST,
    EXISTS,
    IN,
    NOT_IN,
    Requirement,
)


class IncompatibleError(Exception):
    """Raised/returned when two requirement sets cannot be satisfied."""


class Requirements:
    """Map key -> Requirement with set algebra. Mutable; Add intersects."""

    __slots__ = ("_reqs",)

    def __init__(self, requirements: Iterable[Requirement] = ()):
        self._reqs: dict[str, Requirement] = {}
        self.add(*requirements)

    # -- constructors ---------------------------------------------------------

    @classmethod
    def from_node_selector_requirements(
        cls, reqs: Iterable[NodeSelectorRequirement]
    ) -> "Requirements":
        return cls(
            Requirement(r.key, r.operator, r.values) for r in reqs
        )

    @classmethod
    def from_labels(cls, labels: dict[str, str]) -> "Requirements":
        return cls(Requirement(k, IN, [v]) for k, v in labels.items())

    @classmethod
    def from_pod(cls, pod: Pod, required_only: bool = False) -> "Requirements":
        """Pod -> requirements (reference newPodRequirements).

        Preferred node-affinity terms: the single heaviest is treated as
        required (the scheduler's relaxation ladder removes it if
        unsatisfiable). Required terms are ORed in k8s; only the first
        is taken, relaxation removes terms one at a time.
        """
        reqs = cls.from_labels(dict(pod.spec.node_selector))
        if pod.spec.injected_requirements:
            # PVC-derived zonal requirements (volumetopology.go:51-160)
            reqs.add(*pod.spec.injected_requirements)
        affinity = pod.spec.affinity
        if affinity is None or affinity.node_affinity is None:
            return reqs
        node_affinity = affinity.node_affinity
        if not required_only and node_affinity.preferred:
            heaviest = max(node_affinity.preferred, key=lambda t: t.weight)
            reqs.add(
                *cls.from_node_selector_requirements(
                    heaviest.preference.match_expressions
                ).values()
            )
        if node_affinity.required:
            reqs.add(
                *cls.from_node_selector_requirements(
                    node_affinity.required[0].match_expressions
                ).values()
            )
        return reqs

    # -- container protocol ---------------------------------------------------

    def add(self, *requirements: Requirement) -> None:
        for req in requirements:
            existing = self._reqs.get(req.key)
            if existing is not None:
                req = req.intersection(existing)
            self._reqs[req.key] = req

    def get(self, key: str) -> Requirement:
        """Undefined keys behave as Exists (allow anything)."""
        req = self._reqs.get(key)
        if req is None:
            return Requirement(key, EXISTS)
        return req

    def has(self, key: str) -> bool:
        return key in self._reqs

    def keys(self) -> set[str]:
        return set(self._reqs)

    def values(self) -> list[Requirement]:
        return list(self._reqs.values())

    def __iter__(self) -> Iterator[Requirement]:
        return iter(self._reqs.values())

    def __len__(self) -> int:
        return len(self._reqs)

    def __contains__(self, key: str) -> bool:
        return key in self._reqs

    def copy(self) -> "Requirements":
        out = Requirements()
        out._reqs = dict(self._reqs)
        return out

    # -- algebra --------------------------------------------------------------

    def compatible(
        self, incoming: "Requirements", allow_undefined: frozenset[str] = frozenset()
    ) -> Optional[str]:
        """None if `incoming` can loosely be met, else an error string.

        Custom labels must be *defined* on the receiver to match
        (undefined -> reject unless operator is NotIn/DoesNotExist);
        labels in `allow_undefined` (typically WellKnownLabels) are
        allowed to be undefined.
        """
        for key in incoming.keys():
            if key in allow_undefined:
                continue
            op = incoming.get(key).operator()
            if self.has(key) or op in (NOT_IN, DOES_NOT_EXIST):
                continue
            return f'label "{key}" does not have known values'
        return self.intersects(incoming)

    def is_compatible(
        self, incoming: "Requirements", allow_undefined: frozenset[str] = frozenset()
    ) -> bool:
        return self.compatible(incoming, allow_undefined) is None

    def intersects(self, incoming: "Requirements") -> Optional[str]:
        """None if all shared keys have overlapping values.

        When both sides are NotIn/DoesNotExist the empty intersection is
        forgiven (reference requirements.go:248-268).
        """
        small, large = (self, incoming) if len(self) <= len(incoming) else (incoming, self)
        for key in small.keys():
            if key not in large:
                continue
            existing = self.get(key)
            inc = incoming.get(key)
            if not existing.has_intersection(inc):
                if inc.operator() in (NOT_IN, DOES_NOT_EXIST) and existing.operator() in (
                    NOT_IN,
                    DOES_NOT_EXIST,
                ):
                    continue
                return f"key {key}, {inc!r} not in {existing!r}"
        return None

    def intersection(self, incoming: "Requirements") -> "Requirements":
        out = self.copy()
        out.add(*incoming.values())
        return out

    # -- projections ----------------------------------------------------------

    def labels(self) -> dict[str, str]:
        """Representative labels for a node satisfying these requirements."""
        out: dict[str, str] = {}
        for key, req in self._reqs.items():
            if is_restricted_node_label(key):
                continue
            value = req.any_value()
            if value:
                out[key] = value
        return out

    def has_min_values(self) -> bool:
        return any(r.min_values is not None for r in self._reqs.values())

    def __repr__(self) -> str:
        return ", ".join(sorted(repr(r) for r in self._reqs.values()))

    def signature(self) -> tuple:
        """Lossless grouping key (repr truncates long value lists)."""
        return tuple(sorted(r.signature() for r in self._reqs.values()))


ALLOW_UNDEFINED_WELL_KNOWN = WELL_KNOWN_LABELS


def has_preferred_node_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return bool(aff and aff.node_affinity and aff.node_affinity.preferred)
