"""Topology tracking: spread constraints, pod (anti-)affinity.

Counterpart of pkg/controllers/provisioning/scheduling/topology.go +
topologygroup.go: TopologyGroups own domain-count maps; placement asks
each matching group which domains remain legal, and registration
increments the chosen domain. Includes the inverse anti-affinity scan
(topology.go:280-327): existing pods' required anti-affinity terms
block incoming pods that match their selectors.

Domains per topology key are discovered from NodePool requirements,
live nodes and planned nodes (topology.go:105-146). Hostname domains
are synthesized per (planned) node.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Iterable, Optional

from karpenter_tpu.apis.v1.labels import HOSTNAME_LABEL
from karpenter_tpu.kube.objects import (
    LabelSelector,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
)

TYPE_SPREAD = "spread"
TYPE_AFFINITY = "affinity"
TYPE_ANTI_AFFINITY = "anti-affinity"


@dataclass
class TopologyGroup:
    """One constraint shared by all pods carrying it
    (topologygroup.go:56-128)."""

    type: str
    key: str                       # topology key (zone, hostname, ...)
    selector: LabelSelector
    namespaces: frozenset[str]
    max_skew: int = 1
    min_domains: Optional[int] = None
    # node-inclusion policies (topologynodefilter.go): affinity Honor
    # (default) computes skew only over domains the pod can reach;
    # Ignore counts every domain. Taints Ignore (default) counts all;
    # Honor counts only domains reachable via tolerated taints.
    node_affinity_policy: str = "Honor"
    node_taints_policy: str = "Ignore"
    # owner tolerations backing the taints=Honor filter
    owner_tolerations: tuple = ()
    owners: set[str] = field(default_factory=set)   # pod keys owning it
    counts: dict[str, int] = field(default_factory=dict)  # domain -> matching pods
    # anti-affinity only: domains where an *owner* pod landed — future
    # selector-matching pods are excluded from these (inverse scan)
    owner_counts: dict[str, int] = field(default_factory=dict)

    def matches(self, namespace: str, labels: dict[str, str]) -> bool:
        return namespace in self.namespaces and self.selector.matches(labels)

    def register_domain(self, domain: str) -> None:
        self.counts.setdefault(domain, 0)

    def record(self, domain: str, delta: int = 1) -> None:
        self.counts[domain] = self.counts.get(domain, 0) + delta

    # -- legality -------------------------------------------------------------

    def allowed_domains(
        self,
        candidate_domains: Iterable[str],
        eligible: Optional[set[str]] = None,
        taint_eligible: Optional[set[str]] = None,
    ) -> set[str]:
        """Domains where one more matching pod keeps the constraint
        satisfied (nextDomainTopologySpread topologygroup.go:226-311).

        `eligible`: the domains the POD itself may reach (its node
        selector / required affinity) — per NodeAffinityPolicy=Honor the
        skew minimum is computed over these, never over domains the pod
        could not land in."""
        candidates = set(candidate_domains)
        if self.type == TYPE_SPREAD:
            if self.node_affinity_policy == "Ignore":
                # skew is computed over EVERY domain, including ones
                # the pod's own selector excludes (the caller still
                # restricts actual placement via the candidate set)
                eligible = None
            if taint_eligible is not None:
                # nodeTaintsPolicy=Honor: domains only reachable via
                # taints the owner does not tolerate neither count in
                # the skew minimum nor accept placement
                # (topologynodefilter.go Matches)
                eligible = (
                    taint_eligible if eligible is None
                    else eligible & taint_eligible
                )
            if eligible is not None:
                # a domain the pod's own required terms exclude is never
                # a legal placement, and never part of the skew minimum
                candidates &= eligible
                if not candidates:
                    return set()
            live = {d: c for d, c in self.counts.items()}
            for d in candidates:
                live.setdefault(d, 0)
            if eligible is not None:
                live = {d: c for d, c in live.items() if d in eligible}
            if not live:
                return candidates
            global_min = min(live.values())
            # min_domains: while fewer domains than minDomains have
            # pods and an empty domain exists anywhere, the next pod
            # must open one (nextDomainTopologySpread's minDomains
            # handling) — candidates without an empty domain are
            # rejected. Only when NO domain is empty anywhere does the
            # k8s fallback apply: global minimum treated as 0 for the
            # skew check.
            if self.min_domains is not None:
                nonzero = sum(1 for c in live.values() if c > 0)
                if nonzero < self.min_domains:
                    if any(c == 0 for c in live.values()):
                        return {d for d in candidates if live.get(d, 0) == 0}
                    return {
                        d for d in candidates
                        if live.get(d, 0) + 1 <= self.max_skew
                    }
            return {
                d for d in candidates if live.get(d, 0) + 1 - global_min <= self.max_skew
            }
        if self.type == TYPE_AFFINITY:
            occupied = {d for d, c in self.counts.items() if c > 0}
            if not occupied:
                # first matching pod anywhere is legal only if an owner
                # self-selects (topologygroup.go anyCompatiblePod logic
                # approximated: handled by caller via `self_selecting`)
                return set(candidates)
            return candidates & occupied
        # anti-affinity: only empty domains
        return {d for d in candidates if self.counts.get(d, 0) == 0}

    def has_occupied(self) -> bool:
        return any(c > 0 for c in self.counts.values())


def _spread_signature(pod: Pod, tsc: TopologySpreadConstraint) -> tuple:
    sig = (
        TYPE_SPREAD,
        tsc.topology_key,
        tsc.max_skew,
        tsc.min_domains,
        tsc.when_unsatisfiable,
        tsc.label_selector,
        pod.metadata.namespace,
        tsc.node_affinity_policy,
        tsc.node_taints_policy,
    )
    if tsc.node_taints_policy == "Honor":
        # the taint filter is built from the OWNER pod's tolerations
        # (MakeTopologyNodeFilter, topologynodefilter.go:38-65), so
        # pods with different toleration sets cannot share a group
        sig = sig + (tuple(pod.spec.tolerations),)
    return sig


def _term_signature(kind: str, pod: Pod, term: PodAffinityTerm) -> tuple:
    namespaces = term.namespaces or (pod.metadata.namespace,)
    return (kind, term.topology_key, term.label_selector, tuple(sorted(namespaces)))


class Topology:
    """Global tracker for one scheduling run (topology.go:47)."""

    def __init__(
        self,
        domains: dict[str, set[str]],
        cluster_pods: Iterable[Pod] = (),
        pending_pods: Iterable[Pod] = (),
        pod_domains: Optional[dict[str, dict[str, str]]] = None,
        honor_schedule_anyway: bool = True,
        domain_taints: Optional[dict[str, dict[str, list]]] = None,
    ):
        """
        domains: topology key -> known domain values.
        cluster_pods: already-scheduled pods (seed counts + inverse
          anti-affinity).
        pod_domains: pod key -> {topology key: domain} for scheduled
          pods (derived from their node's labels).
        honor_schedule_anyway: treat ScheduleAnyway spread constraints
          as required (relaxed later by the preference ladder).
        domain_taints: topology key -> domain -> list of taint tuples,
          one per SOURCE (pool template or live node) contributing the
          domain; consumed by nodeTaintsPolicy=Honor constraints. A
          domain absent from the map counts as reachable untainted.
        """
        self.domains = {k: set(v) for k, v in domains.items()}
        # dedupe provenance: scheduler.record() appends one entry per
        # (type, value) source; identical taint tuples collapse
        self.domain_taints = {
            key: {d: list(dict.fromkeys(srcs)) for d, srcs in per.items()}
            for key, per in (domain_taints or {}).items()
        }
        # taint-eligibility caching (hot per-candidate-node loop)
        self._domain_generation = 0
        self._taint_elig_cache: dict[int, tuple[int, set]] = {}
        self.honor_schedule_anyway = honor_schedule_anyway
        self._groups: dict[tuple, TopologyGroup] = {}
        # required-only requirement sets, parsed once per pod per round
        # (allowed_domains_for_pod runs once per candidate node in the
        # scheduler loop — reparsing there would be quadratic)
        self._pod_reqs_cache: dict[str, "Requirements"] = {}
        pod_domains = pod_domains or {}

        for pod in pending_pods:
            for group in self._groups_for_pod(pod, create=True):
                group.owners.add(pod.key)

        # Inverse anti-affinity (topology.go:280-327): scheduled pods
        # with required anti-affinity block future matching pods.
        for pod in cluster_pods:
            aff = pod.spec.affinity
            if aff and aff.pod_anti_affinity:
                for term in aff.pod_anti_affinity.required:
                    sig = _term_signature(TYPE_ANTI_AFFINITY, pod, term)
                    group = self._ensure(sig, TYPE_ANTI_AFFINITY, term.topology_key,
                                         term.label_selector,
                                         term.namespaces or (pod.metadata.namespace,))
                    domain = pod_domains.get(pod.key, {}).get(term.topology_key)
                    if domain is not None:
                        group.owner_counts[domain] = group.owner_counts.get(domain, 0) + 1

        # Seed counts from scheduled pods for every group.
        for pod in cluster_pods:
            domains_for_pod = pod_domains.get(pod.key, {})
            for group in self._groups.values():
                if group.matches(pod.metadata.namespace, pod.metadata.labels):
                    domain = domains_for_pod.get(group.key)
                    if domain is not None:
                        group.record(domain)

    # -- group construction ---------------------------------------------------

    def _ensure(self, sig: tuple, type_: str, key: str, selector: LabelSelector,
                namespaces: Iterable[str], max_skew: int = 1,
                min_domains: Optional[int] = None,
                node_affinity_policy: str = "Honor",
                node_taints_policy: str = "Ignore",
                owner_tolerations: tuple = ()) -> TopologyGroup:
        group = self._groups.get(sig)
        if group is None:
            group = TopologyGroup(
                type=type_,
                key=key,
                selector=selector,
                namespaces=frozenset(namespaces),
                max_skew=max_skew,
                min_domains=min_domains,
                node_affinity_policy=node_affinity_policy,
                node_taints_policy=node_taints_policy,
                owner_tolerations=owner_tolerations,
            )
            for domain in self.domains.get(key, ()):  # known domains
                group.register_domain(domain)
            self._groups[sig] = group
        return group

    def _taint_eligible_domains(self, group: TopologyGroup) -> set[str]:
        """Domains reachable through at least one source (pool or live
        node) whose taints the group's owner tolerates. A domain with
        no recorded taint provenance counts as reachable untainted.
        Approximation vs the reference's per-NODE filter: counts from
        pods already running behind intolerable taints still
        contribute to domain totals (we track counts per domain, not
        per node)."""
        from karpenter_tpu.scheduling.taints import tolerates

        cached = self._taint_elig_cache.get(id(group))
        if cached is not None and cached[0] == self._domain_generation:
            return cached[1]
        provenance = self.domain_taints.get(group.key, {})
        out = set()
        for domain in self.domains.get(group.key, ()):
            sources = provenance.get(domain)
            if not sources:
                out.add(domain)
                continue
            if any(
                tolerates(list(src), list(group.owner_tolerations)) is None
                for src in sources
            ):
                out.add(domain)
        self._taint_elig_cache[id(group)] = (self._domain_generation, out)
        return out

    def _groups_for_pod(self, pod: Pod, create: bool = False) -> list[TopologyGroup]:
        out = []
        for tsc in pod.spec.topology_spread_constraints:
            if tsc.when_unsatisfiable == "ScheduleAnyway" and not self.honor_schedule_anyway:
                continue
            sig = _spread_signature(pod, tsc)
            if create:
                out.append(
                    self._ensure(
                        sig, TYPE_SPREAD, tsc.topology_key,
                        tsc.label_selector, (pod.metadata.namespace,),
                        tsc.max_skew, tsc.min_domains,
                        node_affinity_policy=tsc.node_affinity_policy,
                        node_taints_policy=tsc.node_taints_policy,
                        owner_tolerations=tuple(pod.spec.tolerations),
                    )
                )
            elif sig in self._groups:
                out.append(self._groups[sig])
        aff = pod.spec.affinity
        if aff:
            if aff.pod_affinity:
                for term in aff.pod_affinity.required:
                    sig = _term_signature(TYPE_AFFINITY, pod, term)
                    if create:
                        out.append(self._ensure(sig, TYPE_AFFINITY, term.topology_key,
                                                term.label_selector,
                                                term.namespaces or (pod.metadata.namespace,)))
                    elif sig in self._groups:
                        out.append(self._groups[sig])
            if aff.pod_anti_affinity:
                for term in aff.pod_anti_affinity.required:
                    sig = _term_signature(TYPE_ANTI_AFFINITY, pod, term)
                    if create:
                        out.append(self._ensure(sig, TYPE_ANTI_AFFINITY, term.topology_key,
                                                term.label_selector,
                                                term.namespaces or (pod.metadata.namespace,)))
                    elif sig in self._groups:
                        out.append(self._groups[sig])
        return out

    def has_constraints(self, pod: Pod) -> bool:
        """True if this pod carries topology constraints or is blocked
        by any anti-affinity group."""
        if pod.spec.topology_spread_constraints:
            return True
        aff = pod.spec.affinity
        if aff and (aff.pod_affinity or aff.pod_anti_affinity):
            return True
        for group in self._groups.values():
            if group.type == TYPE_ANTI_AFFINITY and group.matches(
                pod.metadata.namespace, pod.metadata.labels
            ):
                return True
        return False

    def invalidate(self, pod_key: str) -> None:
        """Drop the cached requirement parse for a pod whose spec was
        mutated (the preference-relaxation ladder edits pods in place)."""
        self._pod_reqs_cache.pop(pod_key, None)

    def register_domain(self, key: str, domain: str) -> None:
        self.domains.setdefault(key, set()).add(domain)
        for group in self._groups.values():
            if group.key == key:
                group.register_domain(domain)

    # -- placement API --------------------------------------------------------

    def allowed_domains_for_pod(
        self, pod: Pod, candidate: dict[str, set[str]]
    ) -> Optional[dict[str, set[str]]]:
        """Intersect candidate domains per topology key with every
        constraint this pod participates in. None => no legal placement.

        `candidate`: topology key -> domains the target node could take.
        """
        result = {k: set(v) for k, v in candidate.items()}
        pod_reqs = self._pod_reqs_cache.get(pod.key)
        if pod_reqs is None:
            from karpenter_tpu.scheduling.requirements import Requirements

            pod_reqs = Requirements.from_pod(pod, required_only=True)
            self._pod_reqs_cache[pod.key] = pod_reqs
        # Constraints the pod owns
        for group in self._groups_for_pod(pod):
            domains = result.get(group.key)
            if domains is None:
                # node has no value for this key -> illegal for spread
                # constraints that require the label
                return None
            gate = pod_reqs.get(group.key)
            eligible = {
                d for d in self.domains.get(group.key, ()) if gate.has(d)
            } or None
            taint_eligible = None
            if group.node_taints_policy == "Honor":
                taint_eligible = self._taint_eligible_domains(group)
            allowed = group.allowed_domains(
                domains, eligible=eligible, taint_eligible=taint_eligible
            )
            if group.type == TYPE_AFFINITY and not group.has_occupied():
                # first pod: legal only if the pod self-selects (it
                # will satisfy its own affinity) — else any domain is
                # dead (reference: anyCompatiblePod check)
                if not group.matches(pod.metadata.namespace, pod.metadata.labels):
                    return None
            if not allowed:
                return None
            result[group.key] = allowed
        # Inverse anti-affinity: this pod matches some group's selector,
        # so it must avoid domains where that group's owners landed.
        for group in self._groups.values():
            if group.type != TYPE_ANTI_AFFINITY:
                continue
            if not group.matches(pod.metadata.namespace, pod.metadata.labels):
                continue
            domains = result.get(group.key)
            if domains is None:
                continue
            allowed = {d for d in domains if group.owner_counts.get(d, 0) == 0}
            if not allowed:
                return None
            result[group.key] = allowed
        return result

    def register(
        self, pod: Pod, chosen: dict[str, str], source_taints: tuple = ()
    ) -> None:
        """Commit a placement: update counts on all matching groups.
        `source_taints`: the placed node's taints, recorded as the new
        domains' provenance so nodeTaintsPolicy=Honor constraints see
        planned tainted nodes correctly."""
        self._domain_generation += 1
        for key, domain in chosen.items():
            if domain not in self.domains.get(key, ()):
                self.domains.setdefault(key, set()).add(domain)
            srcs = self.domain_taints.setdefault(key, {}).setdefault(
                domain, []
            )
            if tuple(source_taints) not in srcs:
                srcs.append(tuple(source_taints))
        for group in self._groups.values():
            domain = chosen.get(group.key)
            if domain is None:
                continue
            if group.matches(pod.metadata.namespace, pod.metadata.labels):
                group.record(domain)
            if group.type == TYPE_ANTI_AFFINITY and pod.key in group.owners:
                group.owner_counts[domain] = group.owner_counts.get(domain, 0) + 1
            group.register_domain(domain)
