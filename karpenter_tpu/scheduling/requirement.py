"""Single-key requirement as a value set with operator semantics.

Behavioral counterpart of the reference's pkg/scheduling/requirement.go
(Requirement: complement representation, Gt/Lt bounds, minValues,
Intersection/HasIntersection/Has). This representation is also what the
TPU solver encodes into dense masks (see karpenter_tpu.solver.encode):
a Requirement over a finite vocabulary is exactly a boolean row.
"""

from __future__ import annotations

import random
from typing import Iterable, Optional

from karpenter_tpu.apis.v1.labels import NORMALIZED_LABELS

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

_MAXLEN = 2**63 - 1


class Requirement:
    """One label-key constraint.

    Internally either an allowlist (complement=False: value must be in
    `values`) or a denylist (complement=True: value must not be in
    `values`), with optional integer bounds greater_than/less_than and
    an optional minValues flexibility floor.
    """

    __slots__ = ("key", "complement", "values", "greater_than", "less_than", "min_values")

    def __init__(
        self,
        key: str,
        operator: str,
        values: Iterable[str] = (),
        min_values: Optional[int] = None,
    ):
        self.key = NORMALIZED_LABELS.get(key, key)
        self.min_values = min_values
        self.greater_than: Optional[int] = None
        self.less_than: Optional[int] = None
        values = list(values)
        if operator == IN:
            self.complement = False
            self.values = frozenset(values)
        elif operator == NOT_IN:
            self.complement = True
            self.values = frozenset(values)
        elif operator == EXISTS:
            self.complement = True
            self.values = frozenset()
        elif operator == DOES_NOT_EXIST:
            self.complement = False
            self.values = frozenset()
        elif operator == GT:
            self.complement = True
            self.values = frozenset()
            self.greater_than = int(values[0])
        elif operator == LT:
            self.complement = True
            self.values = frozenset()
            self.less_than = int(values[0])
        else:
            raise ValueError(f"unknown operator {operator!r}")

    # -- construction helpers -------------------------------------------------

    @classmethod
    def _raw(
        cls,
        key: str,
        complement: bool,
        values: frozenset[str],
        greater_than: Optional[int],
        less_than: Optional[int],
        min_values: Optional[int],
    ) -> "Requirement":
        r = cls.__new__(cls)
        r.key = key
        r.complement = complement
        r.values = values
        r.greater_than = greater_than
        r.less_than = less_than
        r.min_values = min_values
        return r

    # -- predicates -----------------------------------------------------------

    def operator(self) -> str:
        if self.complement:
            return NOT_IN if self.values else EXISTS
        return IN if self.values else DOES_NOT_EXIST

    def __len__(self) -> int:
        if self.complement:
            return _MAXLEN - len(self.values)
        return len(self.values)

    def has(self, value: str) -> bool:
        """True if the requirement allows `value`."""
        in_set = value in self.values
        ok = not in_set if self.complement else in_set
        return ok and _within(value, self.greater_than, self.less_than)

    def value_list(self) -> list[str]:
        return sorted(self.values)

    def spec_entries(self) -> list[tuple[str, tuple[str, ...], Optional[int]]]:
        """Serialize to (operator, values, minValues) claim-spec
        entries whose conjunction denotes exactly this requirement.
        Gt/Lt bounds live outside the value set (complement
        representation), so they emit as their own entries — a
        flattening to operator()/value_list() alone would collapse a
        bare bound into Exists and lose it (the claim-tightening path
        in nodeclaim.go keeps Gt/Lt as separate NodeSelectorRequirement
        entries for the same reason)."""
        entries: list[tuple[str, tuple[str, ...], Optional[int]]] = []
        if self.greater_than is not None:
            entries.append((GT, (str(self.greater_than),), None))
        if self.less_than is not None:
            entries.append((LT, (str(self.less_than),), None))
        op = self.operator()
        if entries and op == EXISTS and not self.values:
            # the bounds already imply existence; a minValues floor
            # must still ride one of the surviving entries
            if self.min_values is not None:
                last_op, last_values, _ = entries[-1]
                entries[-1] = (last_op, last_values, self.min_values)
            return entries
        entries.append((op, tuple(self.value_list()), self.min_values))
        return entries

    def any_value(self) -> str:
        """A representative allowed value (used to label nodes)."""
        if self.operator() == IN:
            return min(self.values)
        if self.operator() in (NOT_IN, EXISTS):
            lo = (self.greater_than + 1) if self.greater_than is not None else 0
            hi = self.less_than if self.less_than is not None else 2**31
            for _ in range(16):
                candidate = str(random.randrange(lo, hi))
                if candidate not in self.values:
                    return candidate
        return ""

    # -- set algebra ----------------------------------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        """The requirement allowing exactly values allowed by both."""
        complement = self.complement and other.complement
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        min_values = _max_opt(self.min_values, other.min_values)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return Requirement(self.key, DOES_NOT_EXIST, min_values=min_values)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement:
            values = other.values - self.values
        elif other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = frozenset(v for v in values if _within(v, greater_than, less_than))
        if not complement:
            greater_than, less_than = None, None
        return Requirement._raw(self.key, complement, values, greater_than, less_than, min_values)

    def has_intersection(self, other: "Requirement") -> bool:
        """Allocation-free check that `intersection` would be non-empty."""
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return False
        if self.complement and other.complement:
            return True
        if self.complement:
            return any(
                v not in self.values and _within(v, greater_than, less_than)
                for v in other.values
            )
        if other.complement:
            return any(
                v not in other.values and _within(v, greater_than, less_than)
                for v in self.values
            )
        return any(
            v in other.values and _within(v, greater_than, less_than) for v in self.values
        )

    # -- misc -----------------------------------------------------------------

    def copy(self) -> "Requirement":
        return Requirement._raw(
            self.key, self.complement, self.values, self.greater_than, self.less_than, self.min_values
        )

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Requirement):
            return NotImplemented
        return (
            self.key == other.key
            and self.complement == other.complement
            and self.values == other.values
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
            and self.min_values == other.min_values
        )

    def __hash__(self) -> int:
        return hash((self.key, self.complement, self.values, self.greater_than, self.less_than))

    def signature(self) -> tuple:
        """Lossless, hashable identity — unlike __repr__, which
        truncates long value lists for display and must never be used
        as a grouping key."""
        # None -> -1 so signatures stay totally ordered (sort keys);
        # legal Gt/Lt/minValues operands are non-negative
        return (
            self.key,
            self.complement,
            tuple(sorted(self.values)),
            -1 if self.greater_than is None else self.greater_than,
            -1 if self.less_than is None else self.less_than,
            -1 if self.min_values is None else self.min_values,
        )

    def __repr__(self) -> str:
        op = self.operator()
        if op in (EXISTS, DOES_NOT_EXIST):
            s = f"{self.key} {op}"
        else:
            vals = self.value_list()
            if len(vals) > 5:
                vals = vals[:5] + [f"and {len(vals) - 5} others"]
            s = f"{self.key} {op} {vals}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        if self.min_values is not None:
            s += f" minValues {self.min_values}"
        return s


def _within(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    if greater_than is None and less_than is None:
        return True
    try:
        num = int(value)
    except ValueError:
        return False
    if greater_than is not None and greater_than >= num:
        return False
    if less_than is not None and less_than <= num:
        return False
    return True


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)
