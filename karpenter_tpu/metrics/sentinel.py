"""In-process regression sentinel (ISSUE 13 tentpole).

"Did the last change make things slower?" is answered today by a human
re-running bench. The sentinel answers it continuously: rolling
baselines over the per-phase solver durations and the tick wall, with
anomalies flagged the moment a signal departs its own recent history —
as span events on the open trace and as
`karpenter_sentinel_anomaly_total{signal}`, never by blocking a tick.

Baseline model: EWMA of the signal plus an EWMA of the absolute
deviation (a MAD estimate) — both sample-count-driven, with no
wall-clock dependence anywhere, so the baselines replay identically
for an identical sample sequence. A sample is anomalous when its
deviation from the EWMA exceeds max(K x MAD, floor) after the warmup
count; the floor keeps microsecond-scale phases (steady-state encode)
from paging on scheduler jitter. Anomalous samples still update the
baselines (a real regression becomes the new normal within ~1/alpha
samples — the counter records the transition, which is the signal).

The span events the sentinel emits are timing-coupled by definition,
so `tracing.structure()` strips them (the `sentinel_anomaly` event
name is nonstructural) — byte-identical fault replays stay
byte-identical even when machine load trips the sentinel in only one
of the two runs.

Knobs (read per observation — cheap, and chaos suites flip them live):

| env | default | effect |
| --- | --- | --- |
| KARPENTER_SENTINEL | 1 | 0 disables observation entirely |
| KARPENTER_SENTINEL_WARMUP | 16 | samples before a signal can flag |
| KARPENTER_SENTINEL_K | 8.0 | anomaly threshold, in MAD multiples |
| KARPENTER_SENTINEL_ALPHA | 0.05 | EWMA smoothing factor |
| KARPENTER_SENTINEL_FLOOR_MS | 5.0 | absolute deviation floor |
"""

from __future__ import annotations

import os
import threading
from typing import Optional


def enabled() -> bool:
    return os.environ.get("KARPENTER_SENTINEL", "1").strip().lower() not in (
        "0", "off", "false", "no",
    )


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


class _Baseline:
    __slots__ = ("n", "ewma", "mad", "anomalies", "last_value",
                 "last_deviation")

    def __init__(self) -> None:
        self.n = 0
        self.ewma = 0.0
        self.mad = 0.0
        self.anomalies = 0
        self.last_value = 0.0
        self.last_deviation = 0.0


class Sentinel:
    """Rolling EWMA+MAD baselines keyed by signal name. observe() is
    O(1), lock-bounded, and exception-free — the telemetry plane must
    never take the hot path down."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._baselines: dict[str, _Baseline] = {}

    def observe(self, signal: str, value: float) -> bool:
        """Feed one sample; returns True when it was flagged anomalous
        (after warmup). Baselines update on every sample either way."""
        if not enabled():
            return False
        try:
            value = float(value)
            if value != value or value in (float("inf"), float("-inf")):
                # a non-finite sample must neither poison the EWMA nor
                # land NaN on the baseline gauges (a NaN gauge breaks
                # any consumer doing integer formatting)
                return False
            return self._observe(signal, value)
        except Exception:  # pragma: no cover - defensive by contract
            return False

    def _observe(self, signal: str, value: float) -> bool:
        warmup = _env_int("KARPENTER_SENTINEL_WARMUP", 16)
        k = _env_float("KARPENTER_SENTINEL_K", 8.0)
        alpha = _env_float("KARPENTER_SENTINEL_ALPHA", 0.05)
        floor = _env_float("KARPENTER_SENTINEL_FLOOR_MS", 5.0) / 1000.0
        with self._lock:
            base = self._baselines.get(signal)
            if base is None:
                base = self._baselines[signal] = _Baseline()
            if base.n == 0:
                deviation = 0.0
                anomaly = False
                base.ewma = value
            else:
                deviation = abs(value - base.ewma)
                anomaly = (
                    base.n >= warmup
                    and deviation > max(k * base.mad, floor)
                )
                base.ewma += alpha * (value - base.ewma)
            base.mad += alpha * (deviation - base.mad)
            base.n += 1
            base.last_value = value
            base.last_deviation = deviation
            if anomaly:
                base.anomalies += 1
            ewma, mad = base.ewma, base.mad
        from karpenter_tpu.metrics.store import (
            SENTINEL_ANOMALIES,
            SENTINEL_BASELINE,
        )

        SENTINEL_BASELINE.set(round(ewma, 9),
                              {"signal": signal, "stat": "ewma"})
        SENTINEL_BASELINE.set(round(mad, 9),
                              {"signal": signal, "stat": "mad"})
        if anomaly:
            SENTINEL_ANOMALIES.inc({"signal": signal})
            from karpenter_tpu import tracing

            # nonstructural by name (tracing._NONSTRUCTURAL_EVENTS):
            # the payload is timing-coupled, so replays may disagree
            tracing.add_event(
                "sentinel_anomaly",
                signal=signal,
                value_ms=round(value * 1000.0, 3),
                baseline_ms=round(ewma * 1000.0, 3),
                mad_ms=round(mad * 1000.0, 3),
            )
        return anomaly

    def summary(self) -> dict:
        """Per-signal baseline digest (bench's sentinel_summary)."""
        with self._lock:
            return {
                name: {
                    "samples": b.n,
                    "ewma_ms": round(b.ewma * 1000.0, 3),
                    "mad_ms": round(b.mad * 1000.0, 3),
                    "last_ms": round(b.last_value * 1000.0, 3),
                    "anomalies": b.anomalies,
                }
                for name, b in sorted(self._baselines.items())
            }

    def anomaly_total(self) -> int:
        with self._lock:
            return sum(b.anomalies for b in self._baselines.values())

    def snapshot(self) -> dict:
        """Checkpoint view of every baseline (ISSUE 18 satellite):
        summary() plus per-signal warmup state and the anomaly total —
        the block readyz()[\"sentinel\"] mirrors, and what
        reset_baselines() hands back as the phase checkpoint."""
        warmup = _env_int("KARPENTER_SENTINEL_WARMUP", 16)
        with self._lock:
            return {
                "signals": {
                    name: {
                        "samples": b.n,
                        "ewma_ms": round(b.ewma * 1000.0, 3),
                        "mad_ms": round(b.mad * 1000.0, 3),
                        "last_ms": round(b.last_value * 1000.0, 3),
                        "anomalies": b.anomalies,
                        "warmed": b.n >= warmup,
                    }
                    for name, b in sorted(self._baselines.items())
                },
                "anomaly_total": sum(
                    b.anomalies for b in self._baselines.values()
                ),
            }

    def reset_baselines(self, signals=None) -> dict:
        """Drop baselines so the named signals (all, when None)
        re-enter warmup deterministically — the soak harness's
        phase-boundary seam: a regime change (diurnal wave -> surge
        storm) is a NEW normal, and carrying the old baseline across
        it would page on the phase transition itself. Returns the
        pre-reset snapshot() (the phase checkpoint); the in-object
        anomaly counts reset with their baselines, while
        karpenter_sentinel_anomaly_total keeps the whole-process
        history."""
        checkpoint = self.snapshot()
        with self._lock:
            if signals is None:
                self._baselines.clear()
            else:
                for name in signals:
                    self._baselines.pop(name, None)
        return checkpoint

    def reset(self) -> None:
        with self._lock:
            self._baselines.clear()


# the process-wide sentinel: solver phase sites have no operator
# handle, so observation routes through this singleton
_shared = Sentinel()


def shared() -> Sentinel:
    return _shared


def observe(signal: str, value: float) -> bool:
    return _shared.observe(signal, value)


def observe_phase(phase: str, seconds: float) -> bool:
    """The solver phase hook — called next to every
    SOLVER_PHASE_DURATION.observe site."""
    return _shared.observe("solve." + phase, seconds)


def summary() -> dict:
    return _shared.summary()


def anomaly_total() -> int:
    return _shared.anomaly_total()


def snapshot() -> dict:
    return _shared.snapshot()


def reset_baselines(signals=None) -> dict:
    return _shared.reset_baselines(signals)


def reset() -> None:
    _shared.reset()
