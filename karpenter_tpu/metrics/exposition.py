"""Prometheus text exposition (format version 0.0.4) for the in-process
registry.

Counterpart of the metrics endpoint controller-runtime mounts for the
reference (pkg/operator/operator.go:183-222): the same `karpenter_*`
series the in-process stores publish, rendered in the text format any
Prometheus scraper consumes. Histograms are exposed with cumulative
`_bucket{le=...}` series plus `_sum`/`_count`, counters as `_total`-
named totals (names already carry the suffix), gauges as-is.
"""

from __future__ import annotations

from karpenter_tpu.metrics.store import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
)


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def _fmt_labels(pairs, extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in pairs]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    if value != value:  # NaN: the text format spells it literally —
        return "NaN"    # one poisoned series must not kill the scrape
    if value == int(value) and abs(value) < 2**53:
        return str(int(value))
    return repr(float(value))


def render(registry: Registry = REGISTRY) -> str:
    """The whole registry in Prometheus text format."""
    lines: list[str] = []
    for name, metric in registry.collect():
        if isinstance(metric, Counter):
            lines.append(f"# HELP {name} {_escape(metric.help)}")
            lines.append(f"# TYPE {name} counter")
            samples = metric.samples()
            if not samples:
                lines.append(f"{name} 0")
            for pairs, value in samples:
                lines.append(f"{name}{_fmt_labels(pairs)} {_fmt_value(value)}")
        elif isinstance(metric, Gauge):
            lines.append(f"# HELP {name} {_escape(metric.help)}")
            lines.append(f"# TYPE {name} gauge")
            for pairs, value in metric.samples():
                lines.append(f"{name}{_fmt_labels(pairs)} {_fmt_value(value)}")
        elif isinstance(metric, Histogram):
            lines.append(f"# HELP {name} {_escape(metric.help)}")
            lines.append(f"# TYPE {name} histogram")
            for pairs, counts, total_sum, total in metric.samples():
                cumulative = 0
                for bound, count in zip(metric.buckets, counts):
                    cumulative += count
                    le = 'le="' + _fmt_value(bound) + '"'
                    lines.append(
                        f"{name}_bucket{_fmt_labels(pairs, le)} {cumulative}"
                    )
                # +Inf bucket carries observations above the largest
                # bound too (observe() tallies them only in the total)
                le_inf = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket{_fmt_labels(pairs, le_inf)} {total}"
                )
                lines.append(
                    f"{name}_sum{_fmt_labels(pairs)} {_fmt_value(total_sum)}"
                )
                lines.append(f"{name}_count{_fmt_labels(pairs)} {total}")
    return "\n".join(lines) + "\n"
