"""Object-level metrics controllers.

Counterpart of the reference's gauge-republishing controllers
(`pkg/controllers/metrics/pod` 974 LoC, `/node`, `/nodepool`): each
reconcile pass re-publishes one gauge series per live object through a
diff-publishing `Store`, so deleted objects drop their series, and the
pod controller feeds the scheduling/startup latency histograms from the
cluster-state timestamps (metrics/pod/controller.go's
schedulingDuration/startupDuration from state timestamps).
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    INSTANCE_TYPE_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.metrics.store import (
    PODS_SCHEDULING_DURATION,
    PODS_STARTUP_DURATION,
    REGISTRY,
    Store,
)
from karpenter_tpu.state.cluster import Cluster

PODS_STATE = REGISTRY.gauge(
    "karpenter_pods_state", "One series per pod: phase/owner/node placement"
)
NODES_ALLOCATABLE = REGISTRY.gauge(
    "karpenter_nodes_allocatable", "Allocatable per node and resource type"
)
NODES_TOTAL_POD_REQUESTS = REGISTRY.gauge(
    "karpenter_nodes_total_pod_requests",
    "Sum of scheduled pod requests per node and resource type",
)
NODES_UTILIZATION = REGISTRY.gauge(
    "karpenter_nodes_allocatable_utilization_percent",
    "Requested share of allocatable per node and resource type",
)
NODEPOOL_USAGE = REGISTRY.gauge(
    "karpenter_nodepools_usage", "Resource usage per nodepool and resource type"
)
NODEPOOL_LIMIT = REGISTRY.gauge(
    "karpenter_nodepools_limit", "Configured limit per nodepool and resource type"
)
NODEPOOL_NODE_COUNT = REGISTRY.gauge(
    "karpenter_nodepools_node_count", "Nodes owned per nodepool"
)
NODEPOOL_WEIGHT = REGISTRY.gauge(
    "karpenter_nodepools_weight", "Priority weight per nodepool"
)


class PodMetricsController:
    """metrics/pod: per-pod state series + latency histograms.

    Histograms observe once per pod: scheduling duration when the
    scheduling decision lands, startup duration when the pod is bound
    (first_seen -> bound), both from `Cluster`'s PodSchedulingTimes.
    """

    def __init__(self, kube: KubeClient, cluster: Cluster):
        self.kube = kube
        self.cluster = cluster
        self.store = Store(PODS_STATE)
        self._observed_scheduling: set[str] = set()
        self._observed_startup: set[str] = set()

    def reconcile_all(self, now: Optional[float] = None) -> None:
        del now
        live: set[str] = set()
        for pod in self.kube.pods():
            key = pod.key
            live.add(key)
            labels = {
                "name": pod.metadata.name,
                "namespace": pod.metadata.namespace,
                "phase": pod.status.phase,
                "node": pod.spec.node_name or "",
            }
            self.store.update(key, [(labels, 1.0)])
            times = self.cluster.pod_times(key)
            if (
                times.scheduling_decision > 0
                and times.first_seen > 0
                and key not in self._observed_scheduling
            ):
                self._observed_scheduling.add(key)
                PODS_SCHEDULING_DURATION.observe(
                    max(0.0, times.scheduling_decision - times.first_seen)
                )
            if (
                times.bound > 0
                and times.first_seen > 0
                and key not in self._observed_startup
            ):
                self._observed_startup.add(key)
                PODS_STARTUP_DURATION.observe(
                    max(0.0, times.bound - times.first_seen)
                )
        self.store.prune(live)
        self._observed_scheduling &= live
        self._observed_startup &= live


class NodeMetricsController:
    """metrics/node: allocatable / requested / utilization per node."""

    def __init__(self, kube: KubeClient, cluster: Cluster):
        self.kube = kube
        self.cluster = cluster
        self.alloc = Store(NODES_ALLOCATABLE)
        self.requested = Store(NODES_TOTAL_POD_REQUESTS)
        self.util = Store(NODES_UTILIZATION)

    def reconcile_all(self, now: Optional[float] = None) -> None:
        del now
        live: set[str] = set()
        for state in self.cluster.nodes():
            name = state.name
            if not name:
                continue
            live.add(name)
            labels = state.labels()
            base = {
                "node_name": name,
                "nodepool": state.nodepool_name(),
                "instance_type": labels.get(INSTANCE_TYPE_LABEL, ""),
                "capacity_type": labels.get(CAPACITY_TYPE_LABEL, ""),
                "zone": labels.get(TOPOLOGY_ZONE_LABEL, ""),
            }
            alloc = state.allocatable()
            used = state.used()
            self.alloc.update(
                name,
                [
                    ({**base, "resource_type": k}, float(v))
                    for k, v in alloc.items()
                ],
            )
            self.requested.update(
                name,
                [
                    ({**base, "resource_type": k}, float(v))
                    for k, v in used.items()
                ],
            )
            self.util.update(
                name,
                [
                    (
                        {**base, "resource_type": k},
                        100.0 * float(used.get(k, 0.0)) / float(v)
                    )
                    for k, v in alloc.items()
                    if v
                ],
            )
        for store in (self.alloc, self.requested, self.util):
            store.prune(live)


class NodePoolMetricsController:
    """metrics/nodepool: usage vs limits, node counts, weights."""

    def __init__(self, kube: KubeClient, cluster: Cluster):
        self.kube = kube
        self.cluster = cluster
        self.usage = Store(NODEPOOL_USAGE)
        self.limit = Store(NODEPOOL_LIMIT)
        self.count = Store(NODEPOOL_NODE_COUNT)
        self.weight = Store(NODEPOOL_WEIGHT)

    def reconcile_all(self, now: Optional[float] = None) -> None:
        del now
        live: set[str] = set()
        usage = self.cluster.nodepool_resources()
        for pool in self.kube.node_pools():
            name = pool.metadata.name
            live.add(name)
            base = {"nodepool": name}
            self.usage.update(
                name,
                [
                    ({**base, "resource_type": k}, float(v))
                    for k, v in usage.get(name, {}).items()
                ],
            )
            self.limit.update(
                name,
                [
                    ({**base, "resource_type": k}, float(v))
                    for k, v in (pool.spec.limits or {}).items()
                ],
            )
            self.count.update(
                name, [(base, float(self.cluster.nodepool_node_count(name)))]
            )
            self.weight.update(name, [(base, float(pool.spec.weight or 0))])
        for store in (self.usage, self.limit, self.count, self.weight):
            store.prune(live)


# Exponential histogram buckets 0.5 * 2^k, 15 buckets (0.5s .. 8192s) —
# the reference's transition histograms (controllers.go:113-131,
# prometheus.ExponentialBuckets(0.5, 2, 15))
TRANSITION_BUCKETS = tuple(0.5 * 2**k for k in range(15))

STATUS_CONDITION_COUNT = REGISTRY.gauge(
    "karpenter_status_condition_count",
    "Current condition count per kind, condition type and status",
)
STATUS_CONDITION_TRANSITIONS = REGISTRY.counter(
    "karpenter_status_condition_transitions_total",
    "Condition status transitions per kind, condition type and new status",
)
STATUS_CONDITION_TRANSITION_SECONDS = REGISTRY.histogram(
    "karpenter_status_condition_transition_seconds",
    "Time a condition spent in its previous status before transitioning",
    buckets=TRANSITION_BUCKETS,
)
STATUS_CONDITION_CURRENT_SECONDS = REGISTRY.gauge(
    "karpenter_status_condition_current_status_seconds",
    "Time the condition has spent in its current status",
)


class StatusConditionMetricsController:
    """Status-condition observability for NodeClaim, NodePool and Node
    (the operatorpkg status.Controller trio the reference registers at
    controllers.go:113-131): per-kind/type/status condition-count
    gauges, a transitions counter, and a transition-latency histogram
    with exponential buckets that observes how long each condition
    held its PREVIOUS status."""

    def __init__(self, kube: KubeClient, clock=None):
        import time as _time

        self.kube = kube
        self.clock = clock if clock is not None else _time.time
        self.store = Store(STATUS_CONDITION_COUNT)
        self.current = Store(STATUS_CONDITION_CURRENT_SECONDS)
        # (kind, object name) -> {condition type: (status, since)}
        self._seen: dict[tuple[str, str], dict[str, tuple[str, float]]] = {}

    def _object_conditions(self):
        for claim in self.kube.node_claims():
            yield ("NodeClaim", claim.metadata.name, [
                (c.type, c.status, c.last_transition_time)
                for c in claim.status_conditions.conditions
            ])
        for pool in self.kube.node_pools():
            yield ("NodePool", pool.metadata.name, [
                (c.type, c.status, c.last_transition_time)
                for c in pool.status_conditions.conditions
            ])
        for node in self.kube.nodes():
            yield ("Node", node.metadata.name, [
                (c.type, c.status, c.last_transition_time)
                for c in node.status.conditions
            ])

    def reconcile_all(self, now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        live: set[str] = set()
        counts: dict[tuple[str, str, str], int] = {}
        for kind, name, conditions in self._object_conditions():
            obj_key = (kind, name)
            obj_id = f"{kind}/{name}"
            live.add(obj_id)
            prev = self._seen.setdefault(obj_key, {})
            # conditions REMOVED from the object (ConditionSet.clear —
            # the normal Consolidatable churn pattern) must leave the
            # tracking too: a later re-set is a fresh start, not a
            # continuation of the pre-clear status
            present = {ctype for ctype, _, _ in conditions}
            for stale in [t for t in prev if t not in present]:
                del prev[stale]
            current_rows = []
            for ctype, status, since in conditions:
                counts[(kind, ctype, status)] = (
                    counts.get((kind, ctype, status), 0) + 1
                )
                old = prev.get(ctype)
                if old is not None and old[0] != status:
                    STATUS_CONDITION_TRANSITIONS.inc(
                        {"kind": kind, "type": ctype, "status": status}
                    )
                    # the object's own lastTransitionTime bounds the
                    # previous status's duration exactly; the poll
                    # clock would inflate it by up to one reconcile
                    # interval
                    end = since if since > old[1] else now
                    STATUS_CONDITION_TRANSITION_SECONDS.observe(
                        max(0.0, end - old[1]),
                        {"kind": kind, "type": ctype, "status": old[0]},
                    )
                if old is None or old[0] != status:
                    prev[ctype] = (status, since if since > 0 else now)
                current_rows.append((
                    {"kind": kind, "type": ctype, "status": status,
                     "name": name},
                    max(0.0, now - prev[ctype][1]),
                ))
            # diff-published per object: a condition that flips status
            # drops its old-status series instead of exporting both
            self.current.update(obj_id, current_rows)
        # one diff-published series set for all condition counts
        self.store.update("all", [
            ({"kind": k, "type": t, "status": s}, float(v))
            for (k, t, s), v in counts.items()
        ])
        # drop tracking and current-status series for vanished objects
        self.current.prune(live)
        for key in [
            key for key in self._seen if f"{key[0]}/{key[1]}" not in live
        ]:
            del self._seen[key]
