"""SLO burn-rate engine over signals the system already emits
(ISSUE 13 tentpole).

"Priority Matters" frames pod-packing quality as an SLO over sustained
operation, not a per-tick verdict — the question a fleet gets paged on
is "are we meeting the objective over time", which no per-tick metric
answers. This engine evaluates declarative SLIs per operator tick and
rolls them into multi-window burn rates:

- an **SLI** maps the tick's signal dict to (good_units, total_units)
  — e.g. tick latency under budget, zero unschedulable pods, zero
  oracle divergences, gap_vs_lp under the optimality target, zero
  priority sheds;
- the **burn rate** over a window of ticks is
  bad_fraction / (1 - objective): 1.0 means the error budget is being
  consumed exactly at the sustainable rate, N means N times too fast;
- an alert fires only when BOTH the short and the long window burn
  past the threshold (the multiwindow rule: the short window catches
  the onset, the long window suppresses blips), and the alert counter
  increments on state TRANSITIONS, so replays count identically.

Determinism contract: windows are measured in TICKS, never wall-clock;
the engine's only time source is the injectable `clock` (the tick-wall
SLI), and the digest carries no timestamps — a chaos suite replaying a
byte-identical fault schedule under the same injected clock asserts
byte-identical verdicts and burn windows (tests/test_slo.py).

Signals come from three places: the operator's own tick accounting
(tick wall, unschedulable-pod gauge, divergence/shed counter deltas),
and `note()` — a process-global buffer components deeper in the stack
(the solver's gap_vs_lp) drop values into mid-tick; the operator
drains it into the tick's signal dict, so the engine itself stays a
pure function of its inputs.

Exported: `karpenter_slo_burn_rate{slo,window}`,
`karpenter_slo_ok{slo}`, `karpenter_slo_error_budget_remaining{slo}`,
`karpenter_slo_alerts_total{slo,severity}`; `/debug/slo` serves
`report()` and `readyz()["slo"]` the `digest()`.

Knobs (all read per tick, so chaos suites can flip them live):

| env | default | effect |
| --- | --- | --- |
| KARPENTER_SLO | 1 | 0 disables evaluation entirely |
| KARPENTER_SLO_WINDOW_SHORT | 12 | short burn window, in ticks |
| KARPENTER_SLO_WINDOW_LONG | 72 | long burn window (and history), in ticks |
| KARPENTER_SLO_TICK_BUDGET_MS | 1000 | tick-latency SLI budget |
| KARPENTER_SLO_GAP_MAX | 0.05 | optimality SLI: max acceptable gap_vs_lp |
| KARPENTER_SLO_BIND_P99_S | 60 | pod_to_bind_latency SLI: p99 arrival->bind budget |
| KARPENTER_SLO_WARN_BURN | 2.0 | warn when both windows burn past this |
| KARPENTER_SLO_PAGE_BURN | 10.0 | page when both windows burn past this |
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

_SEVERITIES = ("ok", "warn", "page")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def enabled() -> bool:
    return os.environ.get("KARPENTER_SLO", "1").strip().lower() not in (
        "0", "off", "false", "no",
    )


@dataclass(frozen=True)
class SLI:
    """One declarative service-level indicator.

    `evaluate(signals)` returns (good_units, total_units) for the tick,
    or None when the tick carries no data for this SLI (e.g. no cost
    solve ran, so there is no gap) — data-free ticks don't consume or
    replenish the error budget."""

    name: str
    description: str
    objective: float                 # target good fraction (0, 1)
    evaluate: Callable[[dict], Optional[tuple[float, float]]]


def _tick_latency(signals: dict) -> Optional[tuple[float, float]]:
    wall = signals.get("tick_wall_s")
    if wall is None:
        return None
    budget = _env_float("KARPENTER_SLO_TICK_BUDGET_MS", 1000.0) / 1000.0
    return (1.0, 1.0) if wall <= budget else (0.0, 1.0)


def _schedulability(signals: dict) -> Optional[tuple[float, float]]:
    unsched = signals.get("unschedulable_pods")
    if unsched is None:
        return None
    return (1.0, 1.0) if unsched <= 0 else (0.0, 1.0)


def _solve_integrity(signals: dict) -> Optional[tuple[float, float]]:
    div = signals.get("oracle_divergences")
    if div is None:
        return None
    return (1.0, 1.0) if div <= 0 else (0.0, 1.0)


def _admission(signals: dict) -> Optional[tuple[float, float]]:
    shed = signals.get("priority_shed")
    if shed is None:
        return None
    return (1.0, 1.0) if shed <= 0 else (0.0, 1.0)


def _bind_latency(signals: dict) -> Optional[tuple[float, float]]:
    # p99 arrival->bind wall: with the reactive plane on, measured
    # from the WATCH-STREAM arrival stamp (the pod's first sighting),
    # so the SLI covers debounce + micro-solve + bind — the headline
    # number event-driven placement exists to shrink. Absent when the
    # tick bound nothing (data-free, not "good"). The tick's signal
    # dict also carries pod_to_bind_p50_s for dashboards/bench; the
    # objective gates on the tail
    p99 = signals.get("pod_to_bind_p99_s")
    if p99 is None:
        return None
    budget = _env_float("KARPENTER_SLO_BIND_P99_S", 60.0)
    return (1.0, 1.0) if p99 <= budget else (0.0, 1.0)


def _optimality(signals: dict) -> Optional[tuple[float, float]]:
    gap = signals.get("gap_vs_lp")
    if gap is None:
        return None
    return (
        (1.0, 1.0)
        if gap <= _env_float("KARPENTER_SLO_GAP_MAX", 0.05)
        else (0.0, 1.0)
    )


# pod_to_bind_latency leads: with reactive placement (ISSUE 17) the
# arrival->bind tail is THE user-facing objective the control plane is
# shaped around — everything else guards how it is achieved
DEFAULT_SLIS: tuple[SLI, ...] = (
    SLI("pod_to_bind_latency",
        "p99 pod arrival->bind wall under KARPENTER_SLO_BIND_P99_S",
        0.99, _bind_latency),
    SLI("tick_latency",
        "operator tick wall under KARPENTER_SLO_TICK_BUDGET_MS",
        0.99, _tick_latency),
    SLI("schedulability",
        "no pod left unschedulable by the tick's solve",
        0.99, _schedulability),
    SLI("solve_integrity",
        "zero incremental-vs-full oracle divergences",
        0.999, _solve_integrity),
    SLI("admission",
        "zero pods shed by priority admission",
        0.95, _admission),
    SLI("optimality",
        "gap_vs_lp under KARPENTER_SLO_GAP_MAX on cost solves",
        0.90, _optimality),
)


# -- mid-tick signal buffer ---------------------------------------------------

_note_lock = threading.Lock()
_noted: dict = {}


def note(name: str, value: float) -> None:
    """Drop a signal for the CURRENT tick from anywhere in the stack
    (the solver notes gap_vs_lp here after a cost solve). The operator
    drains the buffer into observe_tick's signal dict; repeated notes
    within one tick keep the last value."""
    with _note_lock:
        _noted[name] = value


def take_noted() -> dict:
    with _note_lock:
        out = dict(_noted)
        _noted.clear()
        return out


# -- the engine ---------------------------------------------------------------

class SLOEngine:
    """Rolling tick-count SLO evaluation. One instance per operator;
    `observe_tick(signals)` is the only mutator and the whole state is
    a pure function of the observed signal sequence."""

    def __init__(self, slis: Optional[tuple[SLI, ...]] = None,
                 clock=None):
        self.slis = tuple(slis) if slis is not None else DEFAULT_SLIS
        # injectable time source for the tick-wall signal (the chaos
        # determinism contract: same clock + same signals => same
        # verdicts). perf_counter by default.
        self.clock = clock if clock is not None else time.perf_counter
        self._lock = threading.Lock()
        self.ticks = 0
        long_w = self.window_long()
        self._history: dict[str, deque] = {
            s.name: deque(maxlen=long_w) for s in self.slis
        }
        self._state: dict[str, str] = {s.name: "ok" for s in self.slis}
        self._alerts: dict[str, dict[str, int]] = {
            s.name: {"warn": 0, "page": 0} for s in self.slis
        }
        # whole-run good/total units per SLI (ISSUE 18): unlike the
        # window deques these never roll off, so a long soak can
        # convert them into burn-minutes — total error budget consumed
        # over the trace, not just over the last long window
        self._cumulative: dict[str, list[float]] = {
            s.name: [0.0, 0.0] for s in self.slis
        }
        self.unscheduled_pod_ticks = 0.0

    @staticmethod
    def window_short() -> int:
        return max(1, _env_int("KARPENTER_SLO_WINDOW_SHORT", 12))

    @staticmethod
    def window_long() -> int:
        return max(2, _env_int("KARPENTER_SLO_WINDOW_LONG", 72))

    def _burn(self, name: str, objective: float, window: int) -> float:
        """bad_fraction / error_budget over the last `window` data
        ticks. 0.0 when the window holds no data."""
        entries = list(self._history[name])[-window:]
        total = sum(t for _, t in entries)
        if total <= 0:
            return 0.0
        bad = sum(t - g for g, t in entries)
        budget = max(1.0 - objective, 1e-9)
        return (bad / total) / budget

    def observe_tick(self, signals: dict) -> dict:
        """Evaluate every SLI against this tick's signals, update the
        gauges/alert counters, and return (and remember) the digest."""
        if not enabled():
            digest = {"enabled": False, "ticks": self.ticks}
            with self._lock:
                self._digest = digest
            _remember(digest)
            return digest
        from karpenter_tpu.metrics.store import (
            SLO_ALERTS,
            SLO_BUDGET_REMAINING,
            SLO_BURN_RATE,
            SLO_OK,
        )

        short_w, long_w = self.window_short(), self.window_long()
        warn_at = _env_float("KARPENTER_SLO_WARN_BURN", 2.0)
        page_at = _env_float("KARPENTER_SLO_PAGE_BURN", 10.0)
        verdicts: dict[str, dict] = {}
        with self._lock:
            self.ticks += 1
            unsched = signals.get("unschedulable_pods")
            if unsched:
                self.unscheduled_pod_ticks += float(unsched)
            for sli in self.slis:
                history = self._history[sli.name]
                if history.maxlen != long_w:
                    self._history[sli.name] = history = deque(
                        history, maxlen=long_w
                    )
                try:
                    result = sli.evaluate(signals)
                except Exception:
                    result = None
                if result is not None:
                    good, total = result
                    history.append((float(good), float(total)))
                    cum = self._cumulative[sli.name]
                    cum[0] += float(good)
                    cum[1] += float(total)
                burn_short = self._burn(sli.name, sli.objective, short_w)
                burn_long = self._burn(sli.name, sli.objective, long_w)
                if burn_short >= page_at and burn_long >= page_at:
                    state = "page"
                elif burn_short >= warn_at and burn_long >= warn_at:
                    state = "warn"
                else:
                    state = "ok"
                prev = self._state[sli.name]
                if state != prev and state in ("warn", "page"):
                    self._alerts[sli.name][state] += 1
                    SLO_ALERTS.inc({"slo": sli.name, "severity": state})
                self._state[sli.name] = state
                labels = {"slo": sli.name}
                SLO_BURN_RATE.set(round(burn_short, 6),
                                  {**labels, "window": "short"})
                SLO_BURN_RATE.set(round(burn_long, 6),
                                  {**labels, "window": "long"})
                SLO_OK.set(1.0 if state == "ok" else 0.0, labels)
                SLO_BUDGET_REMAINING.set(
                    round(max(0.0, 1.0 - burn_long), 6), labels
                )
                verdicts[sli.name] = {
                    "state": state,
                    "burn_short": round(burn_short, 6),
                    "burn_long": round(burn_long, 6),
                    "data_ticks": len(history),
                }
            digest = {
                "enabled": True,
                "ticks": self.ticks,
                "windows": {"short": short_w, "long": long_w},
                "unscheduled_pod_ticks": round(
                    self.unscheduled_pod_ticks, 3
                ),
                "verdicts": verdicts,
                "worst": max(
                    (v["state"] for v in verdicts.values()),
                    key=_SEVERITIES.index,
                    default="ok",
                ),
            }
            self._digest = digest
        _remember(digest)
        return digest

    def cumulative(self) -> dict:
        """Whole-run per-SLI units (ISSUE 18): good/total/bad summed
        over EVERY data tick this engine ever observed — the
        window-free ledger the soak judge turns into burn-minutes
        (bad_units x tick_minutes / error_budget). Deterministic under
        the injected clock like everything else here."""
        with self._lock:
            return {
                name: {
                    "good_units": round(cum[0], 3),
                    "total_units": round(cum[1], 3),
                    "bad_units": round(cum[1] - cum[0], 3),
                }
                for name, cum in sorted(self._cumulative.items())
            }

    def digest(self) -> dict:
        """The readyz()["slo"] block: last observe_tick's digest, or a
        zero-tick placeholder before the first tick."""
        with self._lock:
            return dict(getattr(self, "_digest", None) or {
                "enabled": enabled(),
                "ticks": 0,
                "verdicts": {},
                "worst": "ok",
            })

    def report(self) -> dict:
        """The /debug/slo body: the digest plus per-SLI configuration
        and window contents — everything deterministic, no timestamps."""
        with self._lock:
            slis = {}
            for sli in self.slis:
                entries = list(self._history[sli.name])
                good = sum(g for g, _ in entries)
                total = sum(t for _, t in entries)
                slis[sli.name] = {
                    "description": sli.description,
                    "objective": sli.objective,
                    "data_ticks": len(entries),
                    "good_units": round(good, 3),
                    "total_units": round(total, 3),
                    "good_fraction": (
                        round(good / total, 6) if total > 0 else None
                    ),
                    "alerts": dict(self._alerts[sli.name]),
                    "state": self._state[sli.name],
                }
        out = self.digest()
        out["slis"] = slis
        out["cumulative"] = self.cumulative()
        out["thresholds"] = {
            "warn_burn": _env_float("KARPENTER_SLO_WARN_BURN", 2.0),
            "page_burn": _env_float("KARPENTER_SLO_PAGE_BURN", 10.0),
        }
        return out


# -- process-global last digest (bench's per-arm slo_summary) -----------------

_last_lock = threading.Lock()
_last_digest: Optional[dict] = None


def _remember(digest: dict) -> None:
    global _last_digest
    with _last_lock:
        _last_digest = digest


def last_digest() -> Optional[dict]:
    """Most recent digest ANY engine in the process produced — how
    bench arms that drive a live operator pick up their slo_summary
    (None for arms that never ticked an operator)."""
    with _last_lock:
        return dict(_last_digest) if _last_digest is not None else None


def reset_last_digest() -> None:
    global _last_digest
    with _last_lock:
        _last_digest = None
    with _note_lock:
        _noted.clear()
