"""Metrics registry: counters, gauges, histograms, and a diff-publishing
gauge store.

Counterpart of pkg/metrics (metrics.go core series names, store.go:33-110
`Store` that re-publishes per-object gauge sets and deletes stale ones).
Backend-agnostic: values live in-process and can be scraped/dumped; the
series names mirror the reference's `karpenter_*` namespace so
dashboards translate 1:1.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field
from typing import Iterable, Optional

LabelPairs = tuple[tuple[str, str], ...]


def _labels(labels: Optional[dict[str, str]]) -> LabelPairs:
    return tuple(sorted((labels or {}).items()))


class Counter:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._values: dict[LabelPairs, float] = {}
        self._lock = threading.Lock()

    def inc(self, labels: Optional[dict[str, str]] = None, value: float = 1.0) -> None:
        key = _labels(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        return self._values.get(_labels(labels), 0.0)

    def total(self) -> float:
        return sum(self._values.values())

    def samples(self) -> list[tuple[LabelPairs, float]]:
        with self._lock:
            return list(self._values.items())


class Gauge:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._values: dict[LabelPairs, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_labels(labels)] = value

    def delete(self, labels: Optional[dict[str, str]] = None) -> None:
        with self._lock:
            self._values.pop(_labels(labels), None)

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        return self._values.get(_labels(labels), 0.0)

    def series(self) -> dict[LabelPairs, float]:
        return dict(self._values)

    def samples(self) -> list[tuple[LabelPairs, float]]:
        with self._lock:
            return list(self._values.items())


class Histogram:
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 300)

    def __init__(self, name: str, help: str = "", buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = sorted(buckets)
        self._counts: dict[LabelPairs, list[int]] = {}
        self._sums: dict[LabelPairs, float] = {}
        self._totals: dict[LabelPairs, int] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        key = _labels(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            idx = bisect.bisect_left(self.buckets, value)
            if idx < len(counts):
                counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, labels: Optional[dict[str, str]] = None) -> int:
        return self._totals.get(_labels(labels), 0)

    def sum(self, labels: Optional[dict[str, str]] = None) -> float:
        return self._sums.get(_labels(labels), 0.0)

    def samples(self) -> list[tuple[LabelPairs, list[int], float, int]]:
        """(labels, per-bucket counts, sum, total) per series."""
        with self._lock:
            return [
                (key, list(counts), self._sums.get(key, 0.0),
                 self._totals.get(key, 0))
                for key, counts in self._counts.items()
            ]


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "", **kw) -> Histogram:
        return self._get(name, lambda: Histogram(name, help, **kw))

    def _get(self, name, factory):
        metric = self._metrics.get(name)
        if metric is None:
            metric = factory()
            self._metrics[name] = metric
        return metric

    def dump(self) -> dict[str, dict]:
        out = {}
        for name, metric in self._metrics.items():
            if isinstance(metric, Counter):
                out[name] = {"type": "counter", "total": metric.total()}
            elif isinstance(metric, Gauge):
                out[name] = {"type": "gauge", "series": len(metric.series())}
            elif isinstance(metric, Histogram):
                out[name] = {"type": "histogram"}
        return out

    def collect(self) -> list[tuple[str, object]]:
        """Stable-order (name, metric) pairs for exposition."""
        return sorted(self._metrics.items())


# The process-wide registry and the reference's core series
# (pkg/metrics/metrics.go:32).
REGISTRY = Registry()

NODECLAIMS_CREATED = REGISTRY.counter(
    "karpenter_nodeclaims_created_total", "NodeClaims created, by nodepool")
NODECLAIMS_TERMINATED = REGISTRY.counter(
    "karpenter_nodeclaims_terminated_total", "NodeClaims terminated, by nodepool")
NODECLAIMS_DISRUPTED = REGISTRY.counter(
    "karpenter_nodeclaims_disrupted_total", "NodeClaims disrupted, by reason")
PODS_SCHEDULING_DURATION = REGISTRY.histogram(
    "karpenter_pods_scheduling_duration_seconds",
    "Time from pod first seen to scheduling decision")
PODS_STARTUP_DURATION = REGISTRY.histogram(
    "karpenter_pods_startup_duration_seconds",
    "Time from pod first seen to bound")
SCHEDULING_DURATION = REGISTRY.histogram(
    "karpenter_provisioner_scheduling_duration_seconds",
    "Solve wall clock")
DISRUPTION_EVALUATION_DURATION = REGISTRY.histogram(
    "karpenter_disruption_evaluation_duration_seconds",
    "Disruption method evaluation wall clock")

# scheduler subsystem (provisioning/scheduling/metrics.go:33-95)
SCHEDULER_SCHEDULING_DURATION = REGISTRY.histogram(
    "karpenter_scheduler_scheduling_duration_seconds",
    "Duration of scheduling simulations (provisioning and disruption)")
SCHEDULER_QUEUE_DEPTH = REGISTRY.gauge(
    "karpenter_scheduler_queue_depth",
    "Pods currently waiting to be scheduled in an active solve")
SCHEDULER_UNFINISHED_WORK = REGISTRY.gauge(
    "karpenter_scheduler_unfinished_work_seconds",
    "Seconds of in-progress solve work not yet observed by the "
    "duration histogram")
SCHEDULER_IGNORED_PODS = REGISTRY.gauge(
    "karpenter_scheduler_ignored_pods_count",
    "Pods ignored during scheduling (foreign scheduler, invalid PVCs)")
SCHEDULER_UNSCHEDULABLE_PODS = REGISTRY.gauge(
    "karpenter_scheduler_unschedulable_pods_count",
    "Pods the last solve could not place")

# solver hot-path phase breakdown (the per-phase view the BASELINE
# "<1s p99" target is judged against: where a slow solve actually
# spent its wall clock). Buckets extend below the default histogram's
# 5ms floor — steady-state encode/dispatch phases run sub-millisecond.
SOLVER_PHASE_DURATION = REGISTRY.histogram(
    "karpenter_solver_phase_duration_seconds",
    "Solver wall clock by phase (encode/compile/transfer/execute/"
    "decode), per solve",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1, 2.5, 5, 10, 30, 120))
SOLVER_ENCODE_CACHE = REGISTRY.counter(
    "karpenter_solver_encode_cache_total",
    "Encoder compat-row cache lookups, by outcome (hit/miss/bust)")
SOLVER_INCREMENTAL_TICKS = REGISTRY.counter(
    "karpenter_solver_incremental_ticks_total",
    "Warm-start pipeline ticks, by mode (incremental/full) and reason")
SOLVER_INCREMENTAL_DUAL = REGISTRY.counter(
    "karpenter_solver_incremental_dual_total",
    "Dual-guided residual repack activity, by outcome (rank_win: the "
    "reduced-cost-ordered repack beat the unguided pack and was "
    "kept; rank_loss: the unguided pack stayed; floor_skip: a drift "
    "backstop re-solve skipped because weak duality proved the "
    "retained fleet already prices within epsilon of the LP floor)")
# incremental live tick (provisioning/incremental_tick.py): the
# provisioner's retained-state reconcile path and its self-audit
INCREMENTAL_TICK = REGISTRY.counter(
    "karpenter_incremental_tick_total",
    "Provisioner live reconcile ticks, by path (incremental: served "
    "from retained state; full_backstop: routed to the full Scheduler "
    "with the ineligibility reason; quarantined: retained state "
    "distrusted, full-solve decision served)")
INCREMENTAL_DIVERGENCE = REGISTRY.counter(
    "karpenter_incremental_oracle_divergence_total",
    "Incremental-vs-full decision divergences caught by the shadow "
    "oracle audit — every one quarantines the retained state; a "
    "nonzero rate means the dirty-set plumbing is missing changes")
INCREMENTAL_AUDITS = REGISTRY.counter(
    "karpenter_incremental_audit_total",
    "Shadow full-solve oracle audits of the incremental live tick, by "
    "verdict (ok/divergence) and trigger (cadence/fault/recovery/"
    "probation)")
INCREMENTAL_FINGERPRINT_AGE = REGISTRY.gauge(
    "karpenter_incremental_fingerprint_age_ticks",
    "Incremental ticks served since the retained fleet state was last "
    "rebuilt from scratch — the staleness horizon the oracle audit "
    "bounds")
# reactive placement (operator/reactive.py + Operator.micro_step): the
# event-driven sub-tick arrival→bind path (ISSUE 17)
MICRO_SOLVE = REGISTRY.counter(
    "karpenter_micro_solve_total",
    "Event-driven micro-solves, by outcome (served: bind plans "
    "enqueued from the O(dirty) incremental path; deferred: the "
    "envelope routed the batch to the next full tick; empty: the "
    "debounced batch resolved to nothing live to place)")
MICRO_BATCH_SIZE = REGISTRY.histogram(
    "karpenter_micro_batch_size",
    "Pod arrivals per debounced micro-solve batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
MICRO_DEBOUNCE_LATENCY = REGISTRY.histogram(
    "karpenter_micro_debounce_latency_seconds",
    "Oldest-arrival age when a debounced micro batch fires — the "
    "queueing delay the debounce window itself adds to arrival→bind",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5))
DISRUPTION_SCAN_SKIPPED = REGISTRY.counter(
    "karpenter_disruption_scan_skipped_total",
    "Disruption reconcile rounds skipped because nothing went dirty "
    "since the last empty-handed scan (the watch-driven O(changes) "
    "gate; a periodic forced scan bounds staleness)")
DISRUPTION_SNAPSHOT = REGISTRY.counter(
    "karpenter_disruption_snapshot_total",
    "Retained disruption snapshot rows, by outcome (hit: row served "
    "from the retained fleet seam; rebuild: row re-derived for a "
    "dirty/volatile node; audit: from-scratch identity audits of a "
    "retained scan; divergence: audit mismatches — each one "
    "invalidates the retained rows and serves the fresh build)")
SOLVER_DEVICE_STEPS = REGISTRY.histogram(
    "karpenter_solver_device_steps",
    "Outer-loop device steps per packing solve, by path "
    "(sequential: one step per padded pod group; wavefront: one step "
    "per committed round) — sum/count gives steps-per-solve",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096))
SOLVER_WAVEFRONT_WIDTH = REGISTRY.histogram(
    "karpenter_solver_wavefront_width",
    "Pod groups committed per wavefront round (width 1 = the round "
    "degenerated to a sequential step)",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 128))
SOLVER_WARM_COMPILES = REGISTRY.counter(
    "karpenter_solver_warm_compiles_total",
    "Kernel shape buckets AOT-compiled by the warm pool, by outcome")
SOLVER_SHARDS = REGISTRY.gauge(
    "karpenter_solver_shards",
    "Shard count the last device solve actually ran with (1 = "
    "unsharded) — makes the silent KARPENTER_SOLVER_SHARDS "
    "fallback-to-unsharded observable instead of log-only")
SOLVER_STREAM_BLOCKS = REGISTRY.counter(
    "karpenter_solver_stream_blocks_total",
    "Per-shard column blocks shipped by the streaming staging path "
    "(solver/stream.py) — zero on a sharded fleet means every solve "
    "is still paying full-materialization host peaks")
# device LP relaxation (solver/lp_device.py): the dual solve whose
# certificates guide the cost pack, the trim pass, and probe pruning
SOLVER_LP_DURATION = REGISTRY.histogram(
    "karpenter_solver_lp_duration_seconds",
    "Device LP dual-ascent wall clock per (non-cached) solve — the "
    "guidance cost the gap_vs_lp reduction is bought with",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1, 2.5, 5, 10))
SOLVER_LP_ITERATIONS = REGISTRY.histogram(
    "karpenter_solver_lp_iterations",
    "Projected-supergradient iterations per device LP solve "
    "(KARPENTER_LP_ITERS)",
    buckets=(8, 16, 32, 64, 128, 192, 256, 384, 512, 1024))
SOLVER_LP_SOLVES = REGISTRY.counter(
    "karpenter_solver_lp_total",
    "Device LP solves, by outcome (converged / maxiter: ascent hit the "
    "iteration cap still improving / cache_hit: certified duals reused "
    "/ degraded: solve failed and the unguided path served)")
SOLVER_PROBE_PRUNED = REGISTRY.counter(
    "karpenter_solver_probe_pruned_total",
    "Consolidation probes skipped because the dual certificate proved "
    "the candidates cannot be replaced strictly cheaper "
    "(decision-identical to probing: the simulation could only have "
    "returned no command)")
SOLVER_PROBE_BATCH = REGISTRY.counter(
    "karpenter_solver_probe_batch_total",
    "Batched consolidation probe activity: device dispatches (batch), "
    "lanes evaluated (lane), node-axis regrow retries (capped_retry), "
    "and lanes handed back to the sequential path (fallback_lane)")
# resilience layer (solver/resilience.py): breaker state machine,
# degradation ladder routing, watchdog deadline misses, hedge
# outcomes, and the chaos injector's fired faults
SOLVER_BREAKER_STATE = REGISTRY.gauge(
    "karpenter_solver_breaker_state",
    "Per-backend solver circuit breaker state "
    "(0 closed / 1 half-open / 2 open)")
SOLVER_BREAKER_TRANSITIONS = REGISTRY.counter(
    "karpenter_solver_breaker_transitions_total",
    "Solver circuit breaker transitions, by backend and target state")
SOLVER_LADDER = REGISTRY.counter(
    "karpenter_solver_ladder_total",
    "Degradation-ladder rung attempts, by rung "
    "(remote/sharded/device/host) and outcome (ok, skipped_open, "
    "skipped_deadline, or the classified failure)")
SOLVER_DEADLINE_EXCEEDED = REGISTRY.counter(
    "karpenter_solver_deadline_exceeded_total",
    "Watchdog deadline misses, by phase (compile/execute/total)")
SOLVER_HEDGE = REGISTRY.counter(
    "karpenter_solver_hedge_total",
    "FFD hedge activity: fired (timer elapsed mid-solve), win "
    "(hedged result served the decision), loss (device finished first)")
SOLVER_FAULTS_INJECTED = REGISTRY.counter(
    "karpenter_solver_faults_injected_total",
    "Faults fired by the deterministic injector, by site and kind")
FAULTS_REJECTED = REGISTRY.counter(
    "karpenter_faults_rejected_total",
    "Malformed KARPENTER_FAULTS entries dropped at parse — nonzero "
    "means a chaos knob is typo'd and injecting nothing")
# scenario flywheel (ISSUE 18): trace-driven chaos soak + judge
SCENARIO_EVENTS = REGISTRY.counter(
    "karpenter_scenario_events_total",
    "Workload events the scenario flywheel's composed schedule applied "
    "against the soak cluster, by layer and kind (create / delete)")
SOAK_VERDICT = REGISTRY.gauge(
    "karpenter_soak_verdict",
    "Last scenario-flywheel soak judge verdict, by scenario (1 pass / "
    "0 fail — a fail names the losing observability plane in the "
    "verdict artifact)")
# spot capacity tier (cloudprovider spot offerings, disruption/
# interruption.py, scheduler spot budget)
SPOT_INTERRUPTIONS = REGISTRY.counter(
    "karpenter_spot_interruptions_total",
    "Spot instances that received an interruption notice, by provider")
INTERRUPTION_COMMANDS = REGISTRY.counter(
    "karpenter_interruption_commands_total",
    "Drain-after-replace commands started for interrupted nodes, by "
    "nodepool")
SPOT_BUDGET_PINNED = REGISTRY.counter(
    "karpenter_spot_budget_pinned_total",
    "Planned nodes pinned off spot (onto their cheapest non-spot "
    "offering) by the per-pool spot budget (max-spot-fraction cap or "
    "min-on-demand floor), by nodepool and cause")
# control-plane fault tolerance (kube/retry.py, operator recovery):
# the kube-API analogue of the solver breaker metrics above
KUBE_RETRIES = REGISTRY.counter(
    "karpenter_kube_retries_total",
    "Kube API requests retried by the conflict/throttle-aware write "
    "wrapper, by verb and response status (409/429/5xx)")
KUBE_RELIST = REGISTRY.counter(
    "karpenter_kube_relist_total",
    "Informer relists after a watch fell off the server's event "
    "horizon (410 Gone), by kind")
# sharded state plane (state/shards.py): per-shard stream continuity
# and scoped invalidation accounting
STATE_SHARDS = REGISTRY.gauge(
    "karpenter_state_shards",
    "Configured state-plane shard count (KARPENTER_STATE_SHARDS) — "
    "the hash-partition width shared by the watch pump's logical "
    "streams, the retained-state invalidation domains, and the "
    "bind/evict queues")
STATE_SHARD_RELIST = REGISTRY.counter(
    "karpenter_state_shard_relist_total",
    "Shard-scoped informer relists (a 410 on one shard's logical "
    "stream re-LISTed only that shard's keys, leaving other shards' "
    "retained rows warm), by kind and shard")
STATE_SHARD_INVALIDATIONS = REGISTRY.counter(
    "karpenter_state_shard_invalidations_total",
    "Shard-scoped retained-state invalidations (rows dropped for the "
    "relisted shards only instead of a whole-cache bust), by layer "
    "(disruption_snapshot / incremental)")
STATE_SHARD_QUEUE_PENDING = REGISTRY.gauge(
    "karpenter_state_shard_queue_pending",
    "Items pending in a sharded operator queue, by queue (bind / "
    "evict) and shard")
OPERATOR_RECOVERY = REGISTRY.counter(
    "karpenter_operator_recovery_total",
    "Crash-recovery actions taken at operator boot, by action "
    "(readopted_claim / requeued_pod / reaped_leak)")
BINDING_RETRY = REGISTRY.counter(
    "karpenter_binding_retry_total",
    "Pod bindings re-enqueued after a retryable API failure "
    "(409/429/5xx), by status")
# priority-aware overload protection (provisioning/priority.py,
# provisioning/preemption.py, state/nodepoolhealth.py)
PRIORITY_SHED = REGISTRY.counter(
    "karpenter_priority_shed_total",
    "Pods shed by priority admission under overload — the lowest-"
    "priority tail of the admission order when demand exceeds pool "
    "limits or catalog capacity; shed pods retry next round")
PREEMPTION_EVICTIONS = REGISTRY.counter(
    "karpenter_preemption_evictions_total",
    "Victim pods evicted by the preemption controller so a pending "
    "higher-priority pod can land, by nodepool")
PREEMPTION_NOMINATIONS = REGISTRY.counter(
    "karpenter_preemption_nominations_total",
    "Pending higher-priority pods that nominated a victim node "
    "(status.nominatedNodeName stamped, victims evicted, binding "
    "queued)")
NODEPOOL_REGISTRATION_HEALTHY = REGISTRY.gauge(
    "karpenter_nodepool_registration_healthy",
    "Per-nodepool launch/registration health from the ring-buffer "
    "tracker (1 healthy / 0 degraded — the NodeRegistrationHealthy "
    "condition's signal, surfaced for operators)")
# operator tick liveness (ISSUE 9): the wedge-detection signals —
# healthz() reports unhealthy when the last tick's age exceeds
# KARPENTER_TICK_STALL_MULTIPLE x the tick interval
OPERATOR_LAST_TICK = REGISTRY.gauge(
    "karpenter_operator_last_tick_timestamp_seconds",
    "Wall-clock timestamp of the last completed operator tick — a "
    "stalled series means the reconcile loop is wedged (healthz "
    "reports unhealthy past the configured staleness multiple)")
OPERATOR_TICK_DURATION = REGISTRY.histogram(
    "karpenter_operator_tick_duration_seconds",
    "Operator tick wall clock (Operator.step), end to end across "
    "every controller",
    buckets=(0.001, 0.005, 0.025, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30,
             60, 300))
DISRUPTION_PROBE_STARVATION = REGISTRY.counter(
    "karpenter_disruption_probe_starvation_total",
    "Consolidation probes attempted vs still remaining when a method's "
    "wall-clock budget expired, by method — a growing 'remaining' "
    "series means the disruption budget is starving the scan")
# device telemetry plane (solver/telemetry.py): XLA's own cost model
# surfaced as live series — compiled-program memory/cost analyses per
# shape bucket, live per-device allocator stats, staging attribution
DEVICE_COMPILED_MEMORY = REGISTRY.gauge(
    "karpenter_device_compiled_memory_bytes",
    "XLA memory_analysis of a compiled solver program, by kernel, "
    "padded shape bucket, shard count, and component (argument/output/"
    "temp/generated_code) — the device footprint a dispatch of that "
    "bucket commits to before a byte executes")
DEVICE_COMPILED_COST = REGISTRY.gauge(
    "karpenter_device_compiled_cost",
    "XLA cost_analysis of a compiled/lowered solver program, by "
    "kernel, padded shape bucket, shard count, and stat (flops / "
    "bytes_accessed) — what one dispatch of the bucket asks of the "
    "device")
DEVICE_MEMORY = REGISTRY.gauge(
    "karpenter_device_memory_bytes",
    "Live per-device allocator stats from memory_stats(), by device "
    "and stat (bytes_in_use/peak_bytes_in_use/bytes_limit/"
    "largest_alloc_size); backends without allocator stats (XLA:CPU) "
    "publish no series")
DEVICE_STAGING = REGISTRY.gauge(
    "karpenter_device_staging_bytes",
    "Host->device staging bytes of the most recent streamed solve, by "
    "stat (peak_block: largest single host transient; full: what one "
    "full-materialization copy would have allocated) — unified with "
    "stream.py's per-solve stats")
# SLO engine (metrics/slo.py): declarative SLIs over tick signals,
# multi-window burn-rate alerting
SLO_BURN_RATE = REGISTRY.gauge(
    "karpenter_slo_burn_rate",
    "Error-budget burn rate per SLO and window (short/long): "
    "bad_fraction / (1 - objective) over the window's ticks — 1.0 "
    "consumes the budget exactly at the sustainable rate")
SLO_OK = REGISTRY.gauge(
    "karpenter_slo_ok",
    "1 while the SLO's multiwindow verdict is ok, 0 while it is "
    "warn/page (both windows burning past the threshold)")
SLO_BUDGET_REMAINING = REGISTRY.gauge(
    "karpenter_slo_error_budget_remaining",
    "1 - long-window burn rate per SLO, floored at 0 — the fraction "
    "of the error budget left at the current long-window burn")
SLO_ALERTS = REGISTRY.counter(
    "karpenter_slo_alerts_total",
    "SLO alert-state transitions into warn/page, by slo and severity "
    "— transition-counted, so byte-identical replays count identically")
# regression sentinel (metrics/sentinel.py): EWMA+MAD baselines over
# per-phase solver durations and the tick wall
SENTINEL_ANOMALIES = REGISTRY.counter(
    "karpenter_sentinel_anomaly_total",
    "Samples the regression sentinel flagged as anomalous against the "
    "signal's own EWMA+MAD baseline (after warmup), by signal — a "
    "burst on one solve phase means the last change made that phase "
    "slower before any human reran bench")
SENTINEL_BASELINE = REGISTRY.gauge(
    "karpenter_sentinel_baseline",
    "The sentinel's rolling baseline per signal and stat (ewma / mad, "
    "in the signal's own units) — what the anomaly threshold is "
    "currently judged against")
# decision explainability plane (karpenter_tpu/explain): structured
# "why" records per tick — verdicts tally once at record finish, so
# a candidate re-probed many times in one tick counts once
EXPLAIN_VERDICTS = REGISTRY.counter(
    "karpenter_explain_verdicts_total",
    "Disruption-candidate verdicts recorded by the explainability "
    "plane, by verdict (consolidated / interrupted / kept:<reason> — "
    "see README's verdict taxonomy table), tallied once per tick at "
    "record finish")
EXPLAIN_TRUNCATED = REGISTRY.counter(
    "karpenter_explain_truncated_total",
    "Explain entries dropped past the per-tick caps "
    "(KARPENTER_EXPLAIN_MAX_PODS / _MAX_NODES) — a bounded plane "
    "never drops silently")
POD_UNSCHEDULABLE_TICKS = REGISTRY.counter(
    "karpenter_pod_unschedulable_ticks",
    "Ticks a pod stayed unschedulable, by structured reason code "
    "(scheduler.reason_code) — the persistence signal the deduped "
    "FailedScheduling corev1 Event no longer repeats tick after tick")


class Store:
    """Diff-publishing gauge set per object (store.go:33-110): Update
    replaces the object's series, ReplaceAll drops stale objects."""

    def __init__(self, gauge: Gauge):
        self.gauge = gauge
        self._published: dict[str, list[dict[str, str]]] = {}

    def update(self, key: str, series: list[tuple[dict[str, str], float]]) -> None:
        for labels in self._published.get(key, []):
            self.gauge.delete(labels)
        out = []
        for labels, value in series:
            self.gauge.set(value, labels)
            out.append(labels)
        self._published[key] = out

    def delete(self, key: str) -> None:
        for labels in self._published.pop(key, []):
            self.gauge.delete(labels)

    def replace_all(self, series_by_key: dict[str, list[tuple[dict[str, str], float]]]) -> None:
        self.prune(set(series_by_key))
        for key, series in series_by_key.items():
            self.update(key, series)

    def prune(self, live_keys: set[str]) -> None:
        """Drop series for objects no longer live (the ReplaceAll
        half-step for controllers that Update incrementally)."""
        for stale in set(self._published) - live_keys:
            self.delete(stale)
