"""Declarative scenario specs and their replay-identical compilation.

A `ScenarioSpec` names a seed, a virtual trace horizon, and a tuple of
workload layers. Each layer compiles INDEPENDENTLY to a sorted event
list through a layer-scoped RNG seeded from `f"{spec.seed}:{layer
name}"` — a pure function of (spec, seed, the injected clock origin
0.0), with no wall-clock read anywhere — so `compose()` emits the same
byte-identical schedule on every call, on every machine. Layers that
model cloud weather (spot storms) contribute no pod events; they ride
along as `KARPENTER_FAULTS` entries carrying their own `#seed` suffix
(solver/faults.py), so several storms compose into one spec without
their rate schedules aliasing.

Pod shapes default to a small Pareto-weighted signature catalog — the
heavy-head/long-tail demand shape `bench.build_scaled_demand` scales
to millions of pods — drawn per layer from the layer's own RNG.

The schedule's `digest()` (sha256 over the canonical event JSON + the
composed fault spec + the seed) is the replay-identity artifact: two
runs of the same spec + seed must agree on it before their judge
reports are even worth diffing.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from dataclasses import dataclass, field
from typing import Optional

GIB = 2 ** 30

# the Pareto-weighted shape catalog layers draw from when they don't
# pin a cpu: a few signatures, heavy-head weighted (the
# build_scaled_demand convention at trace scale)
_CPU_LEVELS = (0.1, 0.25, 0.5, 1.0, 2.0)
_MEM_LEVELS_GIB = (0.25, 0.5, 1.0, 2.0)


@dataclass(frozen=True)
class Event:
    """One schedule entry: at virtual second `t` (from the injected
    clock origin), `kind` ("create" | "delete") pod `pod` for layer
    `layer`. Shape fields matter only for creates."""

    t: float
    layer: str
    kind: str
    pod: str
    cpu: float = 0.0
    memory_gib: float = 0.0
    priority: int = 0

    def sort_key(self):
        # deterministic total order: time, then layer, then kind
        # (deletes before creates at the same instant free capacity
        # first), then name
        return (round(self.t, 9), self.layer,
                0 if self.kind == "delete" else 1, self.pod)

    def canonical(self) -> dict:
        out = {"t": round(self.t, 6), "layer": self.layer,
               "kind": self.kind, "pod": self.pod}
        if self.kind == "create":
            out.update(cpu=round(self.cpu, 6),
                       memory_gib=round(self.memory_gib, 6),
                       priority=self.priority)
        return out


def _layer_rng(spec: "ScenarioSpec", name: str) -> random.Random:
    return random.Random(f"{spec.seed}:{name}")


def _catalog(rng: random.Random, n: int = 8):
    """Per-layer Pareto shape catalog: (shapes, weights)."""
    shapes = [(rng.choice(_CPU_LEVELS), rng.choice(_MEM_LEVELS_GIB))
              for _ in range(n)]
    weights = [rng.paretovariate(1.5) + 1.0 for _ in range(n)]
    return shapes, weights


def _draw(rng: random.Random, shapes, weights):
    return rng.choices(shapes, weights=weights, k=1)[0]


class _Layer:
    """Layer protocol: compile(spec) -> events, fault_entries(spec) ->
    KARPENTER_FAULTS entries (each already carrying its `#seed`)."""

    name: str

    def compile(self, spec: "ScenarioSpec") -> list[Event]:
        return []

    def fault_entries(self, spec: "ScenarioSpec") -> list[str]:
        return []

    def _seed_token(self, spec: "ScenarioSpec") -> str:
        return f"{spec.seed}-{self.name}"


@dataclass(frozen=True)
class DiurnalWave(_Layer):
    """Serving fleet tracking a sinusoidal demand wave: the pod count
    follows base*(1 + amplitude*sin(2*pi*t/period)), sampled every
    `sample_s`; scale-downs retire the NEWEST pods first so the wave's
    stable core never churns."""

    name: str = "diurnal"
    base_pods: int = 6
    amplitude: float = 0.5
    period_s: float = 120.0
    sample_s: float = 10.0
    cpu: Optional[float] = None        # None -> Pareto catalog shapes
    memory_gib: float = 1.0
    priority: int = 1000

    def compile(self, spec: "ScenarioSpec") -> list[Event]:
        rng = _layer_rng(spec, self.name)
        shapes, weights = _catalog(rng)
        events: list[Event] = []
        live: list[str] = []
        seq = 0
        t = 0.0
        while t <= spec.duration_s + 1e-9:
            phase = 2.0 * math.pi * t / self.period_s
            target = max(0, int(round(
                self.base_pods * (1.0 + self.amplitude * math.sin(phase))
            )))
            while len(live) < target:
                if self.cpu is None:
                    cpu, mem = _draw(rng, shapes, weights)
                else:
                    cpu, mem = self.cpu, self.memory_gib
                pod = f"{self.name}-{seq:04d}"
                seq += 1
                live.append(pod)
                events.append(Event(t, self.name, "create", pod,
                                    cpu, mem, self.priority))
            while len(live) > target:
                events.append(Event(t, self.name, "delete", live.pop()))
            t += self.sample_s
        return events


@dataclass(frozen=True)
class BatchTrain(_Layer):
    """Batch training jobs: every `every_s` a job of `pods_per_job`
    gang pods arrives, runs `duration_s`, and completes (deletes) —
    unless the trace ends first, in which case it runs to the end."""

    name: str = "batch"
    jobs: int = 3
    pods_per_job: int = 4
    every_s: float = 90.0
    duration_s: float = 60.0
    start_s: float = 20.0
    cpu: float = 1.0
    memory_gib: float = 2.0
    priority: int = 200

    def compile(self, spec: "ScenarioSpec") -> list[Event]:
        events: list[Event] = []
        for j in range(self.jobs):
            start = self.start_s + j * self.every_s
            if start > spec.duration_s:
                break
            end = start + self.duration_s
            for i in range(self.pods_per_job):
                pod = f"{self.name}-{j}-{i}"
                events.append(Event(start, self.name, "create", pod,
                                    self.cpu, self.memory_gib,
                                    self.priority))
                if end <= spec.duration_s:
                    events.append(Event(end, self.name, "delete", pod))
        return events


@dataclass(frozen=True)
class DemandSurgeBurst(_Layer):
    """A demand surge: `pods` arrive at once at `at_s` and (when
    `hold_s` > 0) retire together after the hold — the overload-storm
    shape priority admission and the reactive plane must absorb."""

    name: str = "surge"
    at_s: float = 60.0
    pods: int = 10
    hold_s: float = 60.0
    cpu: float = 0.25
    memory_gib: float = 0.5
    priority: int = 500

    def compile(self, spec: "ScenarioSpec") -> list[Event]:
        events: list[Event] = []
        if self.at_s > spec.duration_s:
            return events
        end = self.at_s + self.hold_s
        for i in range(self.pods):
            pod = f"{self.name}-{i:03d}"
            events.append(Event(self.at_s, self.name, "create", pod,
                                self.cpu, self.memory_gib,
                                self.priority))
            if self.hold_s > 0 and end <= spec.duration_s:
                events.append(Event(end, self.name, "delete", pod))
        return events


@dataclass(frozen=True)
class MixedTenancy(_Layer):
    """Mixed-priority serving+batch tenancy ("Priority Matters"): a
    stable high-priority serving set shares the fleet with a rotating
    low-priority batch population — every `rotate_every_s` the oldest
    batch pod completes and a fresh one arrives."""

    name: str = "tenancy"
    serving_pods: int = 4
    batch_pods: int = 4
    rotate_every_s: float = 30.0
    serving_cpu: float = 0.5
    batch_cpu: float = 0.5
    memory_gib: float = 1.0
    serving_priority: int = 1000
    batch_priority: int = 100

    def compile(self, spec: "ScenarioSpec") -> list[Event]:
        events: list[Event] = []
        for i in range(self.serving_pods):
            events.append(Event(0.0, self.name, "create",
                                f"{self.name}-serve-{i}",
                                self.serving_cpu, self.memory_gib,
                                self.serving_priority))
        live: list[str] = []
        seq = 0
        for i in range(self.batch_pods):
            pod = f"{self.name}-batch-{seq:04d}"
            seq += 1
            live.append(pod)
            events.append(Event(0.0, self.name, "create", pod,
                                self.batch_cpu, self.memory_gib,
                                self.batch_priority))
        t = self.rotate_every_s
        while t <= spec.duration_s + 1e-9 and live:
            events.append(Event(t, self.name, "delete", live.pop(0)))
            pod = f"{self.name}-batch-{seq:04d}"
            seq += 1
            live.append(pod)
            events.append(Event(t, self.name, "create", pod,
                                self.batch_cpu, self.memory_gib,
                                self.batch_priority))
            t += self.rotate_every_s
        return events


@dataclass(frozen=True)
class ExpiryChurn(_Layer):
    """Drift/expiry churn: a fixed population whose members each live
    roughly `lifetime_s` (jittered by the layer RNG), die, and are
    immediately replaced — the steady back-pressure that keeps
    consolidation, expiry, and the incremental plane honest."""

    name: str = "churn"
    pods: int = 4
    lifetime_s: float = 90.0
    jitter: float = 0.3
    cpu: float = 0.5
    memory_gib: float = 1.0
    priority: int = 800

    def compile(self, spec: "ScenarioSpec") -> list[Event]:
        rng = _layer_rng(spec, self.name)
        events: list[Event] = []
        for slot in range(self.pods):
            t = slot * self.lifetime_s / max(1, self.pods)
            gen = 0
            while t <= spec.duration_s + 1e-9:
                pod = f"{self.name}-{slot}-{gen}"
                events.append(Event(t, self.name, "create", pod,
                                    self.cpu, self.memory_gib,
                                    self.priority))
                life = self.lifetime_s * (
                    1.0 + self.jitter * (rng.random() * 2.0 - 1.0)
                )
                death = t + max(1.0, life)
                if death > spec.duration_s:
                    break  # the last generation runs to trace end
                events.append(Event(death, self.name, "delete", pod))
                t = death
                gen += 1
        return events


@dataclass(frozen=True)
class SpotStorm(_Layer):
    """Spot-interruption storm (the KubePACS regime): no pod events —
    the layer contributes a rate-based `spot_interruption` fault entry
    whose schedule draws from THIS layer's own `#seed`, so a composed
    spec can stack storms without them aliasing."""

    name: str = "spot_storm"
    rate: float = 0.03

    def fault_entries(self, spec: "ScenarioSpec") -> list[str]:
        return [
            f"spot_interruption@cloud_interrupt:*={self.rate}"
            f"#{self._seed_token(spec)}"
        ]


@dataclass(frozen=True)
class ExpectationEnvelope:
    """The spec's declared verdict expectations, judged against
    `explain.summarize_ring()` at trace end:

    - any observed node verdict outside `allowed_verdicts` (or pod
      code outside `allowed_pod_codes`) is an UNEXPLAINED verdict;
    - the normalized L1 distance between the observed node-verdict
      histogram and `expected_verdicts` (reference SHARES, not
      counts) past `max_distance` is verdict DRIFT.

    Empty tuples disable the respective check — but a spec that wants
    the judge's explain plane armed declares all three."""

    allowed_verdicts: tuple = ()
    allowed_pod_codes: tuple = ()
    expected_verdicts: tuple = ()   # ((verdict, share), ...)
    max_distance: float = 0.35


@dataclass(frozen=True)
class ScenarioSpec:
    name: str
    seed: int
    duration_s: float
    tick_s: float = 4.0
    micro_per_tick: int = 2
    drain_s: float = 120.0
    layers: tuple = ()
    faults: tuple = ()              # extra raw KARPENTER_FAULTS entries
    envelope: Optional[ExpectationEnvelope] = None
    phases: tuple = ()              # sentinel checkpoint offsets (s)
    pool_cpu_limit: Optional[float] = None
    consolidate_after: str = "30s"


@dataclass(frozen=True)
class Schedule:
    """compose()'s output: the merged, sorted, replay-identical event
    stream plus the composed fault spec that rides along with it."""

    spec: ScenarioSpec
    events: tuple
    faults_spec: str
    counts: dict = field(default_factory=dict)

    def canonical_events(self) -> list[dict]:
        return [e.canonical() for e in self.events]

    def digest(self) -> str:
        body = json.dumps({
            "seed": self.spec.seed,
            "events": self.canonical_events(),
            "faults": self.faults_spec,
        }, sort_keys=True)
        return hashlib.sha256(body.encode()).hexdigest()


def compose(spec: ScenarioSpec) -> Schedule:
    """Compile every layer and merge: the schedule is a pure function
    of (spec, seed) — byte-identical across runs and machines."""
    names = [layer.name for layer in spec.layers]
    if len(names) != len(set(names)):
        raise ValueError(f"duplicate layer names in {spec.name}: {names}")
    events: list[Event] = []
    counts: dict[str, dict[str, int]] = {}
    fault_entries: list[str] = list(spec.faults)
    for layer in spec.layers:
        layer_events = layer.compile(spec)
        events.extend(layer_events)
        per = counts.setdefault(layer.name, {"create": 0, "delete": 0})
        for e in layer_events:
            per[e.kind] = per.get(e.kind, 0) + 1
        fault_entries.extend(layer.fault_entries(spec))
    events.sort(key=Event.sort_key)
    return Schedule(
        spec=spec,
        events=tuple(events),
        faults_spec=",".join(fault_entries),
        counts=counts,
    )


# -- presets ------------------------------------------------------------------

_CALM_ENVELOPE = ExpectationEnvelope(
    # every verdict/code the explain taxonomy can emit on a healthy
    # composed trace (kept:* reasons, consolidation, interruptions):
    # anything OUTSIDE this set at trace end is an unexplained verdict
    allowed_verdicts=(
        "consolidated", "interrupted",
        "kept:not_consolidatable", "kept:replacement_would_cost_more",
        "kept:pdb_blocked", "kept:do_not_disrupt", "kept:budget",
        "kept:nominated", "kept:min_nodes", "kept:recently_nominated",
        "kept:not_empty", "kept:not_expired", "kept:not_drifted",
        "kept:candidate_filtered", "kept:no_capacity",
        "kept:probe_kept_node", "kept:validation",
    ),
    allowed_pod_codes=(),           # pod codes free-form (informational)
    # reference shares for a calm run (pinned from the smoke trace's
    # observed histogram): dominated by nominated-keep decisions, with
    # an interruption tail from the spot storm and room for a
    # consolidation tail at longer horizons. Judged by normalized-L1
    # SHAPE distance, so absolute counts — a longer soak — don't move
    # the needle
    expected_verdicts=(
        ("kept:nominated", 0.85),
        ("interrupted", 0.10),
        ("consolidated", 0.05),
    ),
    max_distance=0.35,
)


def smoke_spec(seed: int = 18, duration_s: float = 160.0) -> ScenarioSpec:
    """The tier-1 smoke trace: every layer kind composed over a small
    horizon — diurnal wave + batch train + surge + mixed tenancy +
    churn + spot storm — sized to soak in seconds under the
    accelerated injected clock."""
    return ScenarioSpec(
        name="smoke_flywheel",
        seed=seed,
        duration_s=duration_s,
        tick_s=4.0,
        micro_per_tick=2,
        drain_s=120.0,
        layers=(
            DiurnalWave(base_pods=5, amplitude=0.6, period_s=80.0,
                        sample_s=8.0, cpu=0.5, memory_gib=1.0),
            BatchTrain(jobs=2, pods_per_job=3, every_s=60.0,
                       duration_s=40.0, start_s=16.0, cpu=1.0),
            DemandSurgeBurst(at_s=72.0, pods=8, hold_s=48.0, cpu=0.25),
            MixedTenancy(serving_pods=3, batch_pods=3,
                         rotate_every_s=24.0),
            ExpiryChurn(pods=3, lifetime_s=64.0),
            SpotStorm(rate=0.03),
        ),
        envelope=_CALM_ENVELOPE,
        phases=(duration_s / 2.0,),
    )


def flywheel_spec(seed: int = 18,
                  duration_s: float = 14400.0) -> ScenarioSpec:
    """The full long-horizon trace (default four virtual hours): the
    same layer composition at fleet scale and diurnal period — the
    bench `soak_flywheel` arm and the `slow`-marked soak test replay
    this."""
    return ScenarioSpec(
        name="flywheel",
        seed=seed,
        duration_s=duration_s,
        tick_s=5.0,
        micro_per_tick=2,
        drain_s=300.0,
        layers=(
            DiurnalWave(base_pods=24, amplitude=0.5, period_s=3600.0,
                        sample_s=30.0),
            BatchTrain(jobs=max(2, int(duration_s // 900)),
                       pods_per_job=8, every_s=900.0, duration_s=600.0,
                       start_s=120.0, cpu=2.0, memory_gib=4.0),
            DemandSurgeBurst(at_s=duration_s * 0.4, pods=60,
                             hold_s=600.0, cpu=0.25),
            MixedTenancy(serving_pods=12, batch_pods=12,
                         rotate_every_s=120.0),
            ExpiryChurn(pods=10, lifetime_s=1200.0),
            SpotStorm(rate=0.02),
        ),
        envelope=_CALM_ENVELOPE,
        phases=(duration_s / 3.0, 2.0 * duration_s / 3.0),
    )
