"""The soak judge: observability planes in, one verdict artifact out.

No hand-pinned fleet walls here — the judge only asks the planes the
system already maintains, so every future change inherits the soak as
a regression oracle without re-pinning anything:

- **slo** — FAIL when any SLI consumed its whole-trace error budget
  (whole-run burn >= 1.0 over the cumulative good/total ledger, which
  survives operator reboots). burn-minutes per SLI quantify HOW MUCH
  budget went, for bench_compare trend gating.
- **sentinel** — FAIL on any anomaly transition of the soak-scoped
  baselines (virtual tick wall only: a calm trace is flat 0.0s, so
  any movement is injected, never machine jitter).
- **oracle** — FAIL on any incremental-vs-full divergence (audits are
  forced every solve for the soak's duration).
- **explain** — FAIL on verdicts outside the spec's expectation
  envelope (unexplained), or when the observed verdict histogram
  drifts past `max_distance` from the declared shares
  (explain.verdict_distance: shape, never volume).
- **leaks** — FAIL on any no-leak invariant violation at trace end
  (wedged claims, unlaunched claims, cloud/claim/node mismatches,
  stranded unbound pods).

The report is canonical-JSON digestible: `report_digest` is the
sha256 over everything above it, so the replay-identity acceptance —
same spec + seed, twice → byte-identical reports — is one string
compare. `karpenter_soak_verdict{scenario}` mirrors the pass/fail."""

from __future__ import annotations

import hashlib
import json

from karpenter_tpu.scenarios.spec import ScenarioSpec, Schedule


def _judge_slo(spec: ScenarioSpec, obs: dict) -> dict:
    from karpenter_tpu.metrics.slo import DEFAULT_SLIS

    objectives = {s.name: s.objective for s in DEFAULT_SLIS}
    tick_minutes = spec.tick_s / 60.0
    burn = {}
    burn_minutes = {}
    exhausted = []
    for name, cum in sorted(obs["slo"]["cumulative"].items()):
        budget = max(1.0 - objectives.get(name, 0.99), 1e-9)
        total = cum["total_units"]
        bad = cum["bad_units"]
        whole_run = (bad / total) / budget if total > 0 else 0.0
        burn[name] = round(whole_run, 3)
        # error-budget-weighted minutes of badness: one data tick fully
        # bad costs tick_minutes/budget (drain ticks are longer than
        # tick_s, so this is a trace-scale approximation, applied
        # identically to baseline and current)
        burn_minutes[name] = round(bad * tick_minutes / budget, 3)
        if whole_run >= 1.0:
            exhausted.append(name)
    return {
        "pass": not exhausted,
        "budget_exhausted": exhausted,
        "whole_run_burn": burn,
        "burn_minutes": burn_minutes,
        "max_burn": obs["slo"]["max_burn"],
        "alerts": obs["slo"]["alerts"],
    }


def _judge_sentinel(obs: dict) -> dict:
    total = obs["sentinel"]["anomaly_total"]
    return {
        "pass": total == 0,
        "anomaly_total": total,
        "checkpoints": obs["sentinel"]["checkpoints"],
    }


def _judge_oracle(obs: dict) -> dict:
    div = obs["oracle_divergences"]
    return {"pass": div == 0, "divergences": div}


def _judge_explain(spec: ScenarioSpec, obs: dict) -> dict:
    from karpenter_tpu import explain

    env = spec.envelope
    observed = obs["explain"].get("verdicts", {})
    pod_codes = obs["explain"].get("pod_codes", {})
    if env is None:
        return {"pass": True, "enabled": False}
    unexplained = (
        sorted(v for v in observed if v not in env.allowed_verdicts)
        if env.allowed_verdicts else []
    )
    unexplained_codes = (
        sorted(c for c in pod_codes if c not in env.allowed_pod_codes)
        if env.allowed_pod_codes else []
    )
    distance = None
    if env.expected_verdicts:
        distance = explain.verdict_distance(
            observed, dict(env.expected_verdicts)
        )
    drifted = distance is not None and distance > env.max_distance
    return {
        "pass": not unexplained and not unexplained_codes and not drifted,
        "enabled": True,
        "unexplained_verdicts": unexplained,
        "unexplained_pod_codes": unexplained_codes,
        "verdict_histogram_distance": distance,
        "max_distance": env.max_distance,
        "observed_verdicts": dict(sorted(observed.items())),
    }


def _judge_leaks(obs: dict) -> dict:
    leaks = list(obs["leaks"])
    return {"pass": not leaks, "leaks": leaks}


def judge(spec: ScenarioSpec, schedule: Schedule, obs: dict) -> dict:
    """Render the verdict artifact from one soak run's observations
    (the dict soak.run_soak assembles). Sets
    karpenter_soak_verdict{scenario}."""
    from karpenter_tpu.metrics.store import SOAK_VERDICT

    planes = {
        "slo": _judge_slo(spec, obs),
        "sentinel": _judge_sentinel(obs),
        "oracle": _judge_oracle(obs),
        "explain": _judge_explain(spec, obs),
        "leaks": _judge_leaks(obs),
    }
    failures = sorted(
        name for name, plane in planes.items() if not plane["pass"]
    )
    report = {
        "scenario": spec.name,
        "seed": spec.seed,
        "schedule_digest": schedule.digest(),
        "pass": not failures,
        "failures": failures,
        "planes": planes,
        "observations": {
            k: v for k, v in obs.items() if k != "fault_log"
        },
        "fault_log": [list(entry) for entry in obs.get("fault_log", [])],
    }
    report["report_digest"] = hashlib.sha256(
        json.dumps(report, sort_keys=True).encode()
    ).hexdigest()
    SOAK_VERDICT.set(1.0 if report["pass"] else 0.0,
                     {"scenario": spec.name})
    return report
