"""Scenario flywheel (ISSUE 18): composable trace-driven workloads +
a deterministic chaos soak judged by the observability planes.

Three pieces:

- **spec.py** — declarative scenario specs whose layers (diurnal
  serving waves, batch trains, demand surges, mixed-priority tenancy,
  expiry churn, spot-interruption storms) each compile to a pure
  function of (spec, seed, the injected clock origin): `compose()`
  emits a byte-identical pod/fault event schedule every run —
  extending the fault injector's replay-identity contract from fault
  LOGS to workload SCHEDULES (composed KARPENTER_FAULTS specs ride
  along with per-layer `#seed`s);
- **soak.py** — the long-horizon soak harness: replays a composed
  trace against the full reactive Operator (full ticks + micro-solves,
  crash-and-reboot on injected operator death) under accelerated
  injected time, with forced oracle audits on;
- **judge.py** — renders the structured verdict artifact, FAILING on
  SLO error-budget exhaustion, sentinel anomaly transitions, oracle
  divergence, unexplained-verdict drift against the spec's declared
  expectation envelope, or leaked claims/pods at trace end.

The planes do the judging — there are no hand-pinned walls here, so
every future scale PR inherits this as its regression oracle (the
`soak_flywheel` bench arm + tools/bench_compare.py gate the artifact).
"""

from karpenter_tpu.scenarios.judge import judge
from karpenter_tpu.scenarios.soak import run_soak
from karpenter_tpu.scenarios.spec import (
    BatchTrain,
    DemandSurgeBurst,
    DiurnalWave,
    ExpectationEnvelope,
    ExpiryChurn,
    MixedTenancy,
    ScenarioSpec,
    Schedule,
    SpotStorm,
    compose,
    flywheel_spec,
    smoke_spec,
)

__all__ = [
    "BatchTrain",
    "DemandSurgeBurst",
    "DiurnalWave",
    "ExpectationEnvelope",
    "ExpiryChurn",
    "MixedTenancy",
    "ScenarioSpec",
    "Schedule",
    "SpotStorm",
    "compose",
    "flywheel_spec",
    "judge",
    "run_soak",
    "smoke_spec",
]
