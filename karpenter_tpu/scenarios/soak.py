"""Long-horizon soak harness: replay a composed scenario schedule
against the full reactive Operator under accelerated injected time.

Determinism is the whole point, so every time source is pinned:

- the trace clock starts at a fixed epoch (`_NOW0`) and advances by
  `spec.tick_s` per full tick, with micro-solve slots spaced evenly
  between ticks (the reactive-chaos Harness loop shape);
- the operator's SLO engine is rebuilt with a `VirtualClock` before
  the first step, so `tick_wall_s` is 0.0 on a calm trace and inflated
  ONLY by injected delay faults — which themselves advance the virtual
  clock instead of real-sleeping (the injector's `_sleep` is replaced);
- sentinel judging runs on a soak-scoped `Sentinel` instance fed the
  virtual tick wall per completed step (the process singleton keeps
  observing real wall from inside op.step — real machine jitter must
  never flip a soak verdict);
- arrival->bind latencies already ride the injected clock
  (bindqueue._record_latency under the operator-supplied now).

The harness mirrors the chaos suite's crash contract: an injected
`operator_crash` unwinds the tick, the operator reboots with fresh
memory against the surviving API server and cloud, and the dying SLO
engine's cumulative ledger is merged into the run's accumulator so
burn-minutes survive reboots.

At trace end the fault spec is retired (fault-quiet drain), surge pods
are deleted, the clock rides past the GC interval, and a fixed count
of drain ticks converges the fleet before the no-leak sweep — which
REPORTS leaks instead of asserting, so the judge can render them as a
failing plane."""

from __future__ import annotations

import os
from typing import Optional

from karpenter_tpu.scenarios.spec import GIB, ScenarioSpec, Schedule, compose

# fixed trace epoch: every run of every spec starts its injected clock
# here, so absolute timestamps in artifacts are replay-identical too
_NOW0 = 1_600_000_000.0

# the soak's pinned environment: forced oracle audits (every
# incremental solve shadow-checked), open churn gate, instant kube
# retries/relists (virtual time never waits on real backoff), and both
# judged planes explicitly armed
_SOAK_ENV = {
    "KARPENTER_INCR_AUDIT_EVERY": "1",
    "KARPENTER_INCR_CHURN_MAX": "1.0",
    "KARPENTER_KUBE_RETRY_BASE_MS": "1",
    "KARPENTER_KUBE_RELIST_MIN_MS": "0",
    "KARPENTER_SLO": "1",
    "KARPENTER_SENTINEL": "1",
}


def _soak_kube(server):
    """The operator's client, with workload-controller simulation ON:
    the InMemoryApiServer substrate has no ReplicaSet controller
    behind it, so without this an interruption-drained pod dies for
    good and a storm silently depopulates the soak (the
    EvictionQueue's rebirth path — same-name successors — is gated on
    this flag)."""
    from karpenter_tpu.kube.real import RealKubeClient

    client = RealKubeClient(server)
    client.simulates_workload_controllers = True
    return client


class VirtualClock:
    """The soak's injected time source: a callable (the SLOEngine
    clock protocol) whose `sleep` ADVANCES virtual time — installed as
    the fault injector's sleep so `*_delay` faults cost virtual tick
    wall, deterministically, instead of real-sleeping the test."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def sleep(self, seconds: float) -> None:
        self.t += max(0.0, float(seconds))


class _SoakRun:
    """One soak attempt's mutable state (split out of run_soak so the
    crash-reboot path stays readable)."""

    def __init__(self, spec: ScenarioSpec, vclock: VirtualClock):
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.kube.real import InMemoryApiServer, RealKubeClient
        from karpenter_tpu.metrics.sentinel import Sentinel
        from karpenter_tpu.testing import mk_nodepool

        self.spec = spec
        self.vclock = vclock
        self.server = InMemoryApiServer()
        kube = _soak_kube(self.server)
        self.cloud = KwokCloudProvider(kube)
        self.user = RealKubeClient(self.server)
        self.op = self._make_operator(kube)
        self.sentinel = Sentinel()
        self.crashes = 0
        self.micro_crashes = 0
        self.micro_steps = 0
        self.ticks = 0
        self.slo_max: dict[str, dict[str, float]] = {}
        self.dead_cumulative: dict[str, dict[str, float]] = {}
        self.dead_alerts: dict[str, dict[str, int]] = {}
        if spec.pool_cpu_limit is not None:
            pool = mk_nodepool("default",
                               limits={"cpu": spec.pool_cpu_limit})
        else:
            pool = mk_nodepool("default")
        pool.spec.disruption.consolidate_after = spec.consolidate_after
        self.user.create(pool)

    def _make_operator(self, kube):
        from karpenter_tpu.metrics.slo import SLOEngine
        from karpenter_tpu.operator.operator import Operator

        op = Operator(kube=kube, cloud_provider=self.cloud)
        # rebuild the engine under the virtual clock BEFORE the first
        # step (the operator's documented determinism seam)
        op.slo = SLOEngine(clock=self.vclock)
        return op

    def _bury_engine(self) -> None:
        """Merge a dying operator's SLO ledger into the run
        accumulator before the reboot discards the engine."""
        report = self.op.slo.report()
        for name, cum in self.op.slo.cumulative().items():
            acc = self.dead_cumulative.setdefault(
                name, {"good_units": 0.0, "total_units": 0.0}
            )
            acc["good_units"] += cum["good_units"]
            acc["total_units"] += cum["total_units"]
        for name, sli in report.get("slis", {}).items():
            acc = self.dead_alerts.setdefault(name, {"warn": 0, "page": 0})
            for sev, n in sli.get("alerts", {}).items():
                acc[sev] = acc.get(sev, 0) + n

    def _restart(self) -> None:
        self._bury_engine()
        kube = _soak_kube(self.server)
        self.cloud.kube = kube
        self.op = self._make_operator(kube)

    def _after_step(self, virtual_wall: float) -> None:
        self.sentinel.observe("tick_wall", virtual_wall)
        digest = self.op.slo.digest()
        for name, v in digest.get("verdicts", {}).items():
            peak = self.slo_max.setdefault(
                name, {"burn_short": 0.0, "burn_long": 0.0}
            )
            peak["burn_short"] = max(peak["burn_short"], v["burn_short"])
            peak["burn_long"] = max(peak["burn_long"], v["burn_long"])

    def step(self, now: float) -> bool:
        """One full tick at trace offset `now`; returns False when the
        operator crashed (and was rebooted)."""
        from karpenter_tpu.solver import faults

        w0 = self.vclock.t
        try:
            self.op.step(now=_NOW0 + now)
        except faults.OperatorCrashError:
            self.crashes += 1
            self._restart()
            return False
        self.ticks += 1
        self._after_step(self.vclock.t - w0)
        return True

    def micro(self, now: float) -> bool:
        from karpenter_tpu.solver import faults

        try:
            self.op.micro_step(now=_NOW0 + now)
        except faults.OperatorCrashError:
            self.crashes += 1
            self.micro_crashes += 1
            self._restart()
            return False
        self.micro_steps += 1
        return True

    def merged_cumulative(self) -> dict:
        """The whole-run per-SLI ledger: the live engine's cumulative
        plus every buried (crashed) engine's."""
        merged: dict[str, dict[str, float]] = {}
        for name, cum in self.op.slo.cumulative().items():
            merged[name] = {
                "good_units": cum["good_units"],
                "total_units": cum["total_units"],
            }
        for name, acc in self.dead_cumulative.items():
            slot = merged.setdefault(
                name, {"good_units": 0.0, "total_units": 0.0}
            )
            slot["good_units"] += acc["good_units"]
            slot["total_units"] += acc["total_units"]
        return {
            name: {
                "good_units": round(v["good_units"], 3),
                "total_units": round(v["total_units"], 3),
                "bad_units": round(
                    v["total_units"] - v["good_units"], 3
                ),
            }
            for name, v in sorted(merged.items())
        }

    def merged_alerts(self) -> dict:
        merged: dict[str, dict[str, int]] = {}
        for name, sli in self.op.slo.report().get("slis", {}).items():
            merged[name] = dict(sli.get("alerts", {}))
        for name, acc in self.dead_alerts.items():
            slot = merged.setdefault(name, {"warn": 0, "page": 0})
            for sev, n in acc.items():
                slot[sev] = slot.get(sev, 0) + n
        return {name: merged[name] for name in sorted(merged)}

    def retire_surge(self) -> int:
        from karpenter_tpu.provisioning.provisioner import SURGE_LABEL

        self.user.deliver()
        retired = 0
        for pod in list(self.user.pods()):
            if SURGE_LABEL in pod.metadata.labels:
                self.user.delete(pod)
                retired += 1
        return retired

    def leak_check(self) -> list[str]:
        """The reactive-chaos fingerprint invariants, REPORTED instead
        of asserted (the judge renders them as the `leaks` plane).
        Messages carry counts and schedule-stable pod names only —
        claim/instance names embed process-global counters and would
        break report byte-identity across back-to-back runs."""
        leaks: list[str] = []
        kube = self.op.kube
        claims = kube.node_claims()
        wedged = sum(
            1 for c in claims if c.metadata.deletion_timestamp is not None
        )
        if wedged:
            leaks.append(f"{wedged} wedged-deleting nodeclaim(s)")
        unlaunched = sum(1 for c in claims if not c.status.provider_id)
        if unlaunched:
            leaks.append(f"{unlaunched} nodeclaim(s) never launched")
        claim_pids = sorted(
            c.status.provider_id for c in claims if c.status.provider_id
        )
        inst_pids = sorted(i.status.provider_id for i in self.cloud.list())
        if inst_pids != claim_pids:
            leaks.append(
                "cloud/claim mismatch: "
                f"{len(inst_pids)} instances vs {len(claim_pids)} claims"
            )
        node_pids = sorted(n.spec.provider_id for n in kube.nodes())
        if node_pids != claim_pids:
            leaks.append(
                "node/claim mismatch: "
                f"{len(node_pids)} nodes vs {len(claim_pids)} claims"
            )
        stranded = sorted(
            p.metadata.name
            for p in kube.pods()
            if p.metadata.deletion_timestamp is None
            and not p.spec.node_name
        )
        if stranded:
            shown = ", ".join(stranded[:8])
            extra = f" (+{len(stranded) - 8} more)" if len(stranded) > 8 else ""
            leaks.append(
                f"{len(stranded)} stranded unbound pod(s): {shown}{extra}"
            )
        return leaks

    def fleet(self) -> dict:
        kube = self.op.kube
        live = [
            p for p in kube.pods()
            if p.metadata.deletion_timestamp is None
        ]
        return {
            "nodes": len(kube.nodes()),
            "node_claims": len(kube.node_claims()),
            "live_pods": len(live),
            "bound_pods": sum(1 for p in live if p.spec.node_name),
        }


def _apply_events(run: _SoakRun, schedule: Schedule, cursor: int,
                  until: float, applied: dict) -> int:
    """Deliver every schedule event with t <= until (the cursor is a
    monotonic index into the pre-sorted event tuple)."""
    from karpenter_tpu.metrics.store import SCENARIO_EVENTS
    from karpenter_tpu.testing import mk_pod

    events = schedule.events
    while cursor < len(events) and events[cursor].t <= until + 1e-9:
        ev = events[cursor]
        cursor += 1
        if ev.kind == "create":
            pod = mk_pod(name=ev.pod, cpu=ev.cpu,
                         memory=ev.memory_gib * GIB)
            pod.spec.priority = ev.priority
            pod.metadata.creation_timestamp = _NOW0 + ev.t
            run.user.create(pod)
        else:
            run.user.deliver()
            pod = run.user.get_pod("default", ev.pod)
            if pod is None or pod.metadata.deletion_timestamp is not None:
                applied["skipped_delete"] = applied.get(
                    "skipped_delete", 0
                ) + 1
                continue
            run.user.delete(pod)
        applied[ev.kind] = applied.get(ev.kind, 0) + 1
        SCENARIO_EVENTS.inc({"layer": ev.layer, "kind": ev.kind})
    return cursor


def run_soak(spec: ScenarioSpec,
             schedule: Optional[Schedule] = None) -> dict:
    """Replay `spec`'s composed schedule end to end and return the
    judge's verdict artifact (soak observations included). Pure
    function of (spec, seed): two calls return reports with the same
    report_digest."""
    from karpenter_tpu import explain
    from karpenter_tpu.metrics import slo as _slo
    from karpenter_tpu.metrics.store import (
        INCREMENTAL_DIVERGENCE,
        SCHEDULER_UNSCHEDULABLE_PODS,
    )
    from karpenter_tpu.scenarios.judge import judge
    from karpenter_tpu.solver import faults

    schedule = schedule if schedule is not None else compose(spec)
    vclock = VirtualClock()

    env_keys = ["KARPENTER_FAULTS", "KARPENTER_FAULT_SEED",
                *sorted(_SOAK_ENV)]
    saved_env = {k: os.environ.get(k) for k in env_keys}
    saved_injector = faults.snapshot_active()
    try:
        for k, v in _SOAK_ENV.items():
            os.environ[k] = v
        if schedule.faults_spec:
            os.environ["KARPENTER_FAULTS"] = schedule.faults_spec
        else:
            os.environ.pop("KARPENTER_FAULTS", None)
        os.environ["KARPENTER_FAULT_SEED"] = str(spec.seed)
        faults.reset()
        inj = faults.get()
        if inj is not None:
            inj._sleep = vclock.sleep
        # process-global planes the judge reads: start them clean, and
        # drop the live-provisioning gauge series a previous run in
        # this process may have left behind (the first tick must read
        # an ABSENT series either way)
        explain.clear()
        _slo.reset_last_digest()
        SCHEDULER_UNSCHEDULABLE_PODS.delete({"controller": "provisioner"})
        divergences0 = INCREMENTAL_DIVERGENCE.total()

        run = _SoakRun(spec, vclock)
        applied: dict[str, int] = {}
        cursor = 0
        checkpoints: list[dict] = []
        buried_anomalies = 0
        phases = sorted(
            p for p in spec.phases if 0.0 < p < spec.duration_s
        )
        phase_i = 0
        now = 0.0
        n_ticks = int(spec.duration_s / spec.tick_s) + 1
        for _ in range(n_ticks):
            now += spec.tick_s
            while phase_i < len(phases) and phases[phase_i] <= now:
                # regime boundary: checkpoint + deterministic re-warmup
                checkpoint = run.sentinel.reset_baselines()
                buried_anomalies += checkpoint["anomaly_total"]
                checkpoints.append({
                    "at_s": phases[phase_i],
                    "anomaly_total": checkpoint["anomaly_total"],
                    "signals": sorted(checkpoint["signals"]),
                })
                phase_i += 1
            cursor = _apply_events(run, schedule, cursor, now, applied)
            if not run.step(now):
                continue
            for j in range(1, spec.micro_per_tick + 1):
                tm = now + spec.tick_s * j / (spec.micro_per_tick + 1)
                cursor = _apply_events(run, schedule, cursor, tm, applied)
                if not run.micro(tm):
                    break

        # trace over: capture the replay artifact, then drain
        # fault-quiet (the judge scores the trace, not the teardown)
        inj = faults.get()
        fault_log = inj.snapshot_log() if inj is not None else []
        os.environ.pop("KARPENTER_FAULTS", None)
        faults.reset()
        surge_retired = run.retire_surge()
        now += 130.0  # ride past the GC interval
        drain_ticks = max(4, int(spec.drain_s / 15.0))
        for _ in range(drain_ticks):
            now += 15.0
            run.step(now)

        final_sentinel = run.sentinel.snapshot()
        obs = {
            "schedule_digest": schedule.digest(),
            "events_applied": {k: applied.get(k, 0) for k in
                               ("create", "delete", "skipped_delete")},
            "layer_counts": schedule.counts,
            "ticks": run.ticks,
            "micro_steps": run.micro_steps,
            "crashes": run.crashes,
            "micro_crashes": run.micro_crashes,
            "surge_retired": surge_retired,
            "virtual_seconds": round(now, 3),
            "fault_log_len": len(fault_log),
            "fault_kinds": sorted({kind for _, _, kind in fault_log}),
            "slo": {
                "max_burn": {
                    name: dict(sorted(v.items()))
                    for name, v in sorted(run.slo_max.items())
                },
                "alerts": run.merged_alerts(),
                "cumulative": run.merged_cumulative(),
            },
            "sentinel": {
                "anomaly_total": (
                    buried_anomalies + final_sentinel["anomaly_total"]
                ),
                "checkpoints": checkpoints,
                "final": final_sentinel,
            },
            "oracle_divergences": int(
                INCREMENTAL_DIVERGENCE.total() - divergences0
            ),
            "explain": explain.summarize_ring(),
            "leaks": run.leak_check(),
            "fleet": run.fleet(),
        }
        obs["fault_log"] = fault_log
        return judge(spec, schedule, obs)
    finally:
        faults.restore_active(saved_injector)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
