"""Wire codec for the solver service.

The solver boundary (SURVEY §5.8/§7: control plane on the cluster,
solver service on the TPU hosts, gRPC over DCN between them) carries
exactly the dense arrays the packing kernel consumes — nothing richer
crosses the wire. Requests/responses are compressed npz bundles with a
tiny JSON header; gRPC's custom-serializer API ships them as-is, so no
protoc codegen is needed and the payload stays numpy end to end.

The decode back into NodePlans (pools, instance types, offerings)
stays client-side: those are control-plane objects the solver host
never needs.
"""

from __future__ import annotations

import io
import json
from dataclasses import dataclass

import numpy as np

from karpenter_tpu.solver.encode import Encoded
from karpenter_tpu.solver.pack import PackResult

_ARRAY_FIELDS = (
    "group_req", "group_count", "compat", "cfg_alloc", "cfg_price",
    "cfg_pool", "pool_overhead", "existing_used",
)
_OPTIONAL_ARRAY_FIELDS = (
    "cfg_rsv", "rsv_cap", "group_cap", "conflict", "existing_quota",
)


@dataclass
class _StubConfig:
    """Server-side stand-in for ConfigInfo: the kernel entry only needs
    the existing-node column marker."""

    existing_index: int


def encode_request(
    enc: Encoded, mode: str, max_nodes: int, shards: int, plan=None,
    trace_id: str = "",
) -> bytes:
    header = {
        "mode": mode,
        "max_nodes": max_nodes,
        "shards": shards,
        "n_existing": enc.n_existing,
        "existing_index": [c.existing_index for c in enc.configs],
        "has_plan": plan is not None,
    }
    if trace_id:
        # optional on the wire (old peers never read it): the caller's
        # flight-recorder trace id, adopted by the server so its ring
        # segment resolves to the same tick
        header["trace_id"] = trace_id
    arrays = {name: getattr(enc, name) for name in _ARRAY_FIELDS}
    for name in _OPTIONAL_ARRAY_FIELDS:
        value = getattr(enc, name)
        if value is not None:
            arrays[name] = value
    if plan is not None:
        arrays["plan_cols"] = plan.planned_cols
        arrays["plan_quota"] = plan.planned_quota
    buf = io.BytesIO()
    np.savez_compressed(
        buf, __header__=np.frombuffer(
            json.dumps(header).encode(), dtype=np.uint8
        ), **arrays,
    )
    return buf.getvalue()


def decode_request(payload: bytes):
    """-> (Encoded-compatible object, mode, max_nodes, shards, plan,
    trace_id). `trace_id` is "" for requests from peers that predate
    the flight recorder (the header field is optional both ways)."""
    data = np.load(io.BytesIO(payload), allow_pickle=False)
    header = json.loads(bytes(data["__header__"]).decode())
    kwargs = {name: data[name] for name in _ARRAY_FIELDS}
    for name in _OPTIONAL_ARRAY_FIELDS:
        kwargs[name] = data[name] if name in data.files else None
    enc = Encoded(
        resource_keys=[],
        groups=[],
        configs=[_StubConfig(i) for i in header["existing_index"]],
        n_existing=header["n_existing"],
        **kwargs,
    )
    plan = None
    if header["has_plan"]:
        from karpenter_tpu.solver.lp_plan import FleetPlan

        plan = FleetPlan(
            planned_cols=data["plan_cols"],
            planned_quota=data["plan_quota"],
            lower_bound=0.0,
            objective_estimate=0.0,
        )
    return (enc, header["mode"], header["max_nodes"], header["shards"],
            plan, header.get("trace_id", ""))


def encode_result(result: PackResult) -> bytes:
    buf = io.BytesIO()
    extra = {}
    if result.device_steps:
        extra["device_steps"] = np.asarray([result.device_steps], np.int64)
    if result.wavefront_widths is not None:
        extra["wavefront_widths"] = result.wavefront_widths
    np.savez_compressed(
        buf,
        assign=result.assign,
        node_mask=result.node_mask,
        node_used=result.node_used,
        node_active=result.node_active,
        node_count=np.asarray([result.node_count], np.int64),
        unschedulable=result.unschedulable,
        **extra,
    )
    return buf.getvalue()


def decode_result(payload: bytes) -> PackResult:
    data = np.load(io.BytesIO(payload), allow_pickle=False)
    return PackResult(
        assign=data["assign"],
        node_mask=data["node_mask"],
        node_used=data["node_used"],
        node_active=data["node_active"],
        node_count=int(data["node_count"][0]),
        unschedulable=data["unschedulable"],
        # optional on the wire: an older server simply doesn't ship the
        # step accounting, and the client-side metrics stay silent
        device_steps=(
            int(data["device_steps"][0])
            if "device_steps" in data.files else 0
        ),
        wavefront_widths=(
            data["wavefront_widths"]
            if "wavefront_widths" in data.files else None
        ),
    )
