"""Solver service: the TPU-host side of the gRPC seam.

SURVEY §5.8/§7: the control plane keeps the API-server fabric; the new
distributed piece is a stateless solver service on the TPU hosts —
request in, solution out, reached over gRPC (DCN), with intra-solve
parallelism over ICI via the sharded kernel (solve_packing shards).

One RPC: /karpenter.tpu.Solver/Solve, bytes in / bytes out (npz
codec). Solves are serialized per process: the packing kernel owns the
chip, and concurrent jit dispatch from server threads would interleave
on one device anyway.
"""

from __future__ import annotations

import logging
import threading
from concurrent import futures
from typing import Optional

from karpenter_tpu.service import codec

log = logging.getLogger("karpenter.solver-service")

SERVICE_NAME = "karpenter.tpu.Solver"
SOLVE_METHOD = f"/{SERVICE_NAME}/Solve"


def resolve_service_shards(shards) -> int:
    """Resolve the service's mesh width. `"auto"` (or any negative
    int) spans EVERY device the host sees — the multi-host pjit mode
    ISSUE 11 lands: one logical solve partitioned over the service's
    whole device set. `0` inherits solve_packing's own default
    (KARPENTER_SOLVER_SHARDS / unsharded); a positive int is taken
    literally. With "auto" on a single-device host the resolution is 0
    (nothing to span — the solve runs unsharded rather than paying
    mesh setup for one device)."""
    if shards == "auto" or (isinstance(shards, int) and shards < 0):
        from karpenter_tpu.solver.pack import visible_devices

        visible = visible_devices(1)
        return visible if visible > 1 else 0
    return int(shards)


class SolverServer:
    def __init__(self, port: int = 0, shards=0, max_workers: int = 4,
                 bind: str = "127.0.0.1"):
        """`shards`: device-mesh width the service solves with — its own
        ICI parallelism, authoritative over anything a client sends (a
        control plane has no idea how many chips this host has).
        `"auto"` / a negative int spans every visible device (see
        resolve_service_shards). `port=0` picks a free port, exposed
        as `self.port` after start(). `bind`: loopback by default
        (tests/sidecar); a standalone TPU host serves on all
        interfaces via serve()."""
        import grpc

        self._default_shards = resolve_service_shards(shards)
        self._solve_lock = threading.Lock()
        self.requests_served = 0
        self.requests_started = 0
        # set the moment the FIRST request enters the handler: lets
        # chaos/kill tests land a shutdown deterministically mid-stream
        # instead of racing a sleep against the serve loop
        self.request_started = threading.Event()

        def solve_handler(request: bytes, context) -> bytes:
            from karpenter_tpu import tracing
            from karpenter_tpu.solver import faults
            from karpenter_tpu.solver.pack import solve_packing

            with self._solve_lock:
                self.requests_started += 1
            self.request_started.set()
            faults.fire("rpc_server")
            (enc, mode, max_nodes, _, plan,
             trace_id) = codec.decode_request(request)
            # the caller's flight-recorder trace id survives the RPC
            # hop: this host's span segment records under the SAME id,
            # so /debug/traces?trace_id= on either side resolves the
            # solve (old peers send no id -> a fresh local trace)
            with tracing.adopt(trace_id, "solve.remote") as root:
                root.annotate(mode=mode, shards=self._default_shards)
                with self._solve_lock:
                    result = solve_packing(
                        enc, max_nodes=max_nodes, mode=mode, plan=plan,
                        shards=self._default_shards,
                    )
                    self.requests_served += 1
            return codec.encode_result(result)

        handler = grpc.method_handlers_generic_handler(
            SERVICE_NAME,
            {
                "Solve": grpc.unary_unary_rpc_method_handler(
                    solve_handler,
                    request_deserializer=None,   # raw bytes
                    response_serializer=None,
                )
            },
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{bind}:{port}")

    def start(self) -> "SolverServer":
        self._server.start()
        log.info("solver service listening on :%d", self.port)
        return self

    def stop(self, grace: Optional[float] = 1.0) -> None:
        self._server.stop(grace)


def serve(port: int = 50151, shards="auto",
          bind: str = "[::]") -> None:  # pragma: no cover
    """Blocking entry point for a standalone solver host: listens on
    all interfaces so the control plane can reach it over DCN. Default
    mesh width is "auto" — one logical solve pjit-spans every chip the
    host owns (pass an explicit int to pin a narrower mesh)."""
    server = SolverServer(port=port, shards=shards, bind=bind).start()
    server._server.wait_for_termination()
