"""Solver-service client with in-process fallback.

The control plane calls `RemoteSolver.solve_packing` exactly where it
would call the local kernel; connection failures and deadline misses
fall back to the in-process solve, so a dead or slow solver host
degrades to round-1 behavior instead of wedging provisioning (the
fallback the SURVEY §7 seam requires).

Enable by setting KARPENTER_SOLVER_ENDPOINT=host:port — solver.solve_
encoded routes every device solve through it.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from karpenter_tpu.service import codec
from karpenter_tpu.service.server import SOLVE_METHOD
from karpenter_tpu.solver.pack import PackResult, solve_packing

log = logging.getLogger("karpenter.solver-client")

DEFAULT_TIMEOUT_SECONDS = 55.0  # under the 60s Solve wall-clock bound
BREAKER_FAILURES = 2            # consecutive failures that trip it
BREAKER_COOLDOWN_SECONDS = 60.0      # base; doubles per consecutive open
BREAKER_COOLDOWN_MAX_SECONDS = 600.0


def endpoint_from_env() -> Optional[str]:
    return os.environ.get("KARPENTER_SOLVER_ENDPOINT") or None


class RemoteSolver:
    def __init__(self, endpoint: str,
                 timeout: float = DEFAULT_TIMEOUT_SECONDS,
                 fallback_local: bool = True):
        import grpc

        self.endpoint = endpoint
        self.timeout = timeout
        self.fallback_local = fallback_local
        self._channel = grpc.insecure_channel(endpoint)
        self._solve = self._channel.unary_unary(
            SOLVE_METHOD, request_serializer=None, response_deserializer=None
        )
        # circuit breaker: a routable-but-black-holed endpoint costs a
        # full deadline per RPC; after BREAKER_FAILURES consecutive
        # misses every solve goes straight local until the cooldown
        # elapses, so provisioning never serializes repeated stalls.
        # Locked: the cost objective solves from two threads, and an
        # interleaved failure count would keep the breaker from opening.
        import threading

        self._breaker_lock = threading.Lock()
        self._failures = 0
        self._open_cycles = 0
        self._skip_until = 0.0

    def solve_packing(self, enc, max_nodes: int = 0, mode: str = "ffd",
                      plan=None, shards: int = 0,
                      fallback: Optional[bool] = None) -> PackResult:
        """`fallback` overrides `fallback_local` per call: the
        resilience ladder passes False so an RPC failure propagates to
        ITS ladder (which owns the device/host fallback and the
        breaker bookkeeping) instead of silently solving here."""
        from karpenter_tpu.utils.backoff import jitter

        fallback_local = (
            self.fallback_local if fallback is None else fallback
        )

        def local() -> PackResult:
            return solve_packing(
                enc, max_nodes=max_nodes, mode=mode, plan=plan, shards=shards
            )

        with self._breaker_lock:
            # only the STATE read happens under the lock — the local
            # solve must run outside it or concurrent solves serialize
            # on one breaker for multiple seconds each
            skip = fallback_local and time.monotonic() < self._skip_until
        if skip:
            return local()
        try:
            from karpenter_tpu import tracing
            from karpenter_tpu.solver import faults

            # attrs stay deterministic under replay (the structure
            # contract): endpoint + mode only, no payload sizes — the
            # compressed request embeds the per-run trace id
            with tracing.span("solve.rpc", endpoint=self.endpoint,
                              mode=mode):
                faults.fire("rpc")
                request = codec.encode_request(
                    enc, mode, max_nodes, shards, plan,
                    trace_id=tracing.current_trace_id(),
                )
                response = self._solve(request, timeout=self.timeout)
            with self._breaker_lock:
                self._failures = 0
                self._open_cycles = 0
            return codec.decode_result(response)
        except Exception as err:
            if not fallback_local:
                # the caller (the resilience ladder) owns fallback AND
                # breaker bookkeeping for this endpoint — running the
                # internal breaker here too would log "open" cooldowns
                # that never actually skip (skip is gated on
                # fallback_local) and double-count every outage
                raise
            with self._breaker_lock:
                self._failures += 1
                if self._failures >= BREAKER_FAILURES:
                    # cooldown from NOW, not from before the RPC — a
                    # deadline-miss failure burns the timeout first and
                    # must still keep the breaker open a full cooldown.
                    # Jittered exponential: doubles per consecutive
                    # open cycle (capped), scaled by a desynchronizing
                    # [0.5, 1.0) factor so a fleet of control planes
                    # tripped together never re-probes in lockstep.
                    from karpenter_tpu.utils.backoff import (
                        capped_exponential,
                    )

                    cooldown = capped_exponential(
                        self._open_cycles + 1,
                        BREAKER_COOLDOWN_SECONDS,
                        BREAKER_COOLDOWN_MAX_SECONDS,
                    ) * jitter()
                    self._open_cycles += 1
                    self._skip_until = time.monotonic() + cooldown
                    log.warning(
                        "solver service %s: %d consecutive failures; "
                        "breaker open for %.0fs", self.endpoint,
                        self._failures, cooldown,
                    )
            log.warning(
                "solver service %s unavailable (%s); solving in-process",
                self.endpoint, type(err).__name__,
            )
            return local()

    def close(self) -> None:
        self._channel.close()
