"""Status conditions with transition tracking.

Counterpart of operatorpkg status conditions used throughout the
reference's CRD statuses (Launched/Registered/Initialized/...).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

TRUE = "True"
FALSE = "False"
UNKNOWN = "Unknown"


@dataclass
class Condition:
    type: str
    status: str = UNKNOWN
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0
    observed_generation: int = 0


@dataclass
class ConditionSet:
    """A set of typed conditions; Ready aggregates the root types."""

    conditions: list[Condition] = field(default_factory=list)
    root_types: list[str] = field(default_factory=list)

    def get(self, ctype: str) -> Optional[Condition]:
        for cond in self.conditions:
            if cond.type == ctype:
                return cond
        return None

    def set(
        self,
        ctype: str,
        status: str,
        reason: str = "",
        message: str = "",
        now: Optional[float] = None,
    ) -> bool:
        """Set a condition; returns True if status transitioned."""
        now = time.time() if now is None else now
        cond = self.get(ctype)
        if cond is None:
            self.conditions.append(
                Condition(type=ctype, status=status, reason=reason, message=message,
                          last_transition_time=now)
            )
            return True
        changed = cond.status != status
        cond.reason = reason
        cond.message = message
        if changed:
            cond.status = status
            cond.last_transition_time = now
        return changed

    def set_true(self, ctype: str, reason: str = "", now: Optional[float] = None) -> bool:
        return self.set(ctype, TRUE, reason or ctype, now=now)

    def set_false(self, ctype: str, reason: str = "", message: str = "",
                  now: Optional[float] = None) -> bool:
        return self.set(ctype, FALSE, reason, message, now=now)

    def clear(self, ctype: str) -> bool:
        for i, cond in enumerate(self.conditions):
            if cond.type == ctype:
                del self.conditions[i]
                return True
        return False

    def is_true(self, ctype: str) -> bool:
        cond = self.get(ctype)
        return cond is not None and cond.status == TRUE

    def is_false(self, ctype: str) -> bool:
        cond = self.get(ctype)
        return cond is not None and cond.status == FALSE

    def root(self) -> Condition:
        """Aggregate Ready condition over the declared root types."""
        for ctype in self.root_types:
            cond = self.get(ctype)
            if cond is None or cond.status == UNKNOWN:
                return Condition(type="Ready", status=UNKNOWN, reason="AwaitingReconciliation")
            if cond.status == FALSE:
                return Condition(type="Ready", status=FALSE, reason=cond.reason or cond.type)
        return Condition(type="Ready", status=TRUE, reason="Ready")
