"""NodePool API type: declarative pool of nodes.

Counterpart of pkg/apis/v1/nodepool.go: template for NodeClaims,
disruption policy (consolidation policy/after, cron-scheduled budgets),
resource limits, weight priority, and alpha `replicas` (static pools).
Includes the spec hash used for drift detection
(nodepool.go:297-305, NodePoolHashVersion "v3").
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.apis.v1.condition import ConditionSet
from karpenter_tpu.apis.v1.nodeclaim import NodeClaimSpec, RequirementSpec
from karpenter_tpu.kube.objects import ObjectMeta
from karpenter_tpu.utils.duration import CronSchedule, parse_duration
from karpenter_tpu.utils.resources import ResourceList

CONSOLIDATION_WHEN_EMPTY = "WhenEmpty"
CONSOLIDATION_WHEN_EMPTY_OR_UNDERUTILIZED = "WhenEmptyOrUnderutilized"

REASON_UNDERUTILIZED = "Underutilized"
REASON_EMPTY = "Empty"
REASON_DRIFTED = "Drifted"
# spot interruption notice (KubePACS-style forced reclaim): the cloud
# takes the capacity whether or not the controller acts, so commands
# with this reason bypass graceful pod-block rules and budgets
REASON_INTERRUPTED = "Interrupted"

COND_VALIDATION_SUCCEEDED = "ValidationSucceeded"
COND_NODE_CLASS_READY = "NodeClassReady"
COND_NODE_REGISTRATION_HEALTHY = "NodeRegistrationHealthy"


@dataclass
class Budget:
    """Disruption budget window (nodepool.go:100-117).

    nodes: int-string or percentage ("10%"); schedule: cron (UTC);
    duration: window length; reasons: which disruption reasons it caps
    (None = all).
    """

    nodes: str = "10%"
    schedule: Optional[str] = None
    duration: Optional[str] = None
    reasons: Optional[list[str]] = None

    def is_active(self, now: float) -> bool:
        """Reference Budget.IsActive: walk back `duration` and see if
        the schedule fired within the window."""
        if self.schedule is None and self.duration is None:
            return True
        cron = CronSchedule.parse(self.schedule or "* * * * *")
        duration = parse_duration(self.duration) or 0.0
        last = cron.last_fire_before(now)
        return last is not None and last >= _floor_minute(now - duration)

    def allowed_disruptions(self, now: float, num_nodes: int) -> int:
        """MaxInt when inactive; else scaled value, percentages round up
        (matching PDB MaxUnavailable semantics — nodepool.go:345-367)."""
        if not self.is_active(now):
            return 2**31 - 1
        if self.nodes.endswith("%"):
            pct = int(self.nodes[:-1])
            return math.ceil(pct * num_nodes / 100.0)
        return int(self.nodes)


def _floor_minute(ts: float) -> float:
    return float(int(ts // 60) * 60)


@dataclass
class Disruption:
    consolidate_after: Optional[str] = "0s"  # duration | "Never"
    consolidation_policy: str = CONSOLIDATION_WHEN_EMPTY_OR_UNDERUTILIZED
    budgets: list[Budget] = field(default_factory=list)


@dataclass
class NodeClaimTemplate:
    """spec.template: metadata + NodeClaimSpec minus status-ish fields."""

    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)


@dataclass
class NodePoolSpec:
    template: NodeClaimTemplate = field(default_factory=NodeClaimTemplate)
    disruption: Disruption = field(default_factory=Disruption)
    limits: ResourceList = field(default_factory=dict)
    weight: int = 0          # higher = tried first
    replicas: Optional[int] = None  # set -> static pool (alpha)


@dataclass
class NodePoolStatus:
    resources: ResourceList = field(default_factory=dict)
    nodes: int = 0


@dataclass
class NodePool:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodePoolSpec = field(default_factory=NodePoolSpec)
    status: NodePoolStatus = field(default_factory=NodePoolStatus)
    status_conditions: ConditionSet = field(default_factory=lambda: ConditionSet(
        root_types=[COND_VALIDATION_SUCCEEDED, COND_NODE_CLASS_READY]))

    kind = "NodePool"

    @property
    def key(self) -> str:
        return self.metadata.name

    def is_static(self) -> bool:
        return self.spec.replicas is not None

    def hash(self) -> str:
        """Static-field template hash for drift detection.

        Mirrors NodePool.Hash() (nodepool.go:297-305): covers the
        template's labels/annotations/taints/startup taints and
        behavior fields, excluding requirements and nodeClassRef
        (which drift via requirement-compat / nodeclass hash checks).
        """
        spec = self.spec.template.spec
        payload = {
            "labels": sorted(self.spec.template.labels.items()),
            "annotations": sorted(self.spec.template.annotations.items()),
            "taints": [(t.key, t.value, t.effect) for t in spec.taints],
            "startup_taints": [(t.key, t.value, t.effect) for t in spec.startup_taints],
            "expire_after": spec.expire_after,
            "termination_grace_period": spec.termination_grace_period,
        }
        digest = hashlib.sha256(json.dumps(payload, sort_keys=True).encode()).hexdigest()
        return digest[:16]

    def allowed_disruptions(self, now: float, num_nodes: int, reason: str) -> int:
        """Min over budgets matching `reason` (nodepool.go:318-340)."""
        allowed = 2**31 - 1
        for budget in self.spec.disruption.budgets:
            if budget.reasons is None or reason in budget.reasons:
                allowed = min(allowed, budget.allowed_disruptions(now, num_nodes))
        return allowed

    def must_get_allowed_disruptions(self, now: float, num_nodes: int, reason: str) -> int:
        try:
            return self.allowed_disruptions(now, num_nodes, reason)
        except Exception:
            return 0  # fail closed on misconfigured budgets


def template_requirements(pool: NodePool) -> list[RequirementSpec]:
    """Template requirements plus single-value label requirements."""
    out = list(pool.spec.template.spec.requirements)
    for key, value in pool.spec.template.labels.items():
        out.append(RequirementSpec(key=key, operator="In", values=(value,)))
    return out


def nodepool_owner_ref(pool: "NodePool"):
    """The controller reference a NodePool stamps on objects it owns
    (claims; nodepool.go sets it so deleting the pool cascades)."""
    from karpenter_tpu.kube.objects import OwnerReference

    return OwnerReference(
        kind="NodePool", name=pool.metadata.name, uid=pool.metadata.uid,
        controller=True, api_version="karpenter.sh/v1",
    )


def order_by_weight(pools: list[NodePool]) -> list[NodePool]:
    """Descending weight, then name for determinism (utils/nodepool)."""
    return sorted(pools, key=lambda p: (-p.spec.weight, p.metadata.name))
