"""NodeClaim API type: one requested machine.

Counterpart of pkg/apis/v1/nodeclaim.go + nodeclaim_status.go. The
spec is immutable after creation (the reference enforces this with CEL;
here the in-memory API server rejects spec updates). Requirements carry
optional minValues flexibility floors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.apis.v1.condition import ConditionSet
from karpenter_tpu.kube.objects import ObjectMeta, Taint
from karpenter_tpu.utils.resources import ResourceList

# Condition types (reference nodeclaim_status.go:26-35)
COND_LAUNCHED = "Launched"
COND_REGISTERED = "Registered"
COND_INITIALIZED = "Initialized"
COND_CONSOLIDATABLE = "Consolidatable"
COND_DRIFTED = "Drifted"
COND_DRAINED = "Drained"
COND_VOLUMES_DETACHED = "VolumesDetached"
COND_INSTANCE_TERMINATING = "InstanceTerminating"
COND_CONSISTENT_STATE_FOUND = "ConsistentStateFound"
COND_DISRUPTION_REASON = "DisruptionReason"
# spot capacity holding a cloud interruption notice (set by the
# interruption controller the tick the provider reports the notice)
COND_INTERRUPTED = "Interrupted"
COND_NODE_CLASS_READY = "NodeClassReady"

LIFECYCLE_ROOT_CONDITIONS = [COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED]


@dataclass(frozen=True)
class NodeClassRef:
    group: str = ""
    kind: str = ""
    name: str = ""


@dataclass(frozen=True)
class RequirementSpec:
    """NodeSelectorRequirementWithMinValues (nodeclaim.go:81-89)."""

    key: str
    operator: str
    values: tuple[str, ...] = ()
    min_values: Optional[int] = None


@dataclass
class NodeClaimSpec:
    requirements: list[RequirementSpec] = field(default_factory=list)
    resources: ResourceList = field(default_factory=dict)  # resource requests
    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    node_class_ref: Optional[NodeClassRef] = None
    expire_after: Optional[str] = None              # duration string | "Never"
    termination_grace_period: Optional[str] = None  # duration string


@dataclass
class NodeClaimStatus:
    provider_id: str = ""
    image_id: str = ""
    node_name: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    last_pod_event_time: Optional[float] = None


@dataclass
class NodeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)
    status_conditions: ConditionSet = field(default_factory=lambda: ConditionSet(
        root_types=list(LIFECYCLE_ROOT_CONDITIONS)))

    kind = "NodeClaim"

    @property
    def key(self) -> str:
        return self.metadata.name
