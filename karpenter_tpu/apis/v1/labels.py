"""Well-known labels, annotations, taints and restricted-label rules.

Counterpart of the reference's pkg/apis/v1/labels.go:42-150 and
pkg/apis/v1/taints.go:27-41 — the shared vocabulary the scheduler's
set algebra operates over.
"""

from __future__ import annotations

from karpenter_tpu.kube.objects import Taint

GROUP = "karpenter.sh"
COMPATIBILITY_GROUP = "compatibility.karpenter.sh"

# Kubernetes well-known node labels
TOPOLOGY_ZONE_LABEL = "topology.kubernetes.io/zone"
TOPOLOGY_REGION_LABEL = "topology.kubernetes.io/region"
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"
ARCH_LABEL = "kubernetes.io/arch"
OS_LABEL = "kubernetes.io/os"
HOSTNAME_LABEL = "kubernetes.io/hostname"
WINDOWS_BUILD_LABEL = "node.kubernetes.io/windows-build"

# Capacity types / architectures
ARCH_AMD64 = "amd64"
ARCH_ARM64 = "arm64"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_RESERVED = "reserved"

# Karpenter-specific labels
NODEPOOL_LABEL = f"{GROUP}/nodepool"
NODE_INITIALIZED_LABEL = f"{GROUP}/initialized"
NODE_REGISTERED_LABEL = f"{GROUP}/registered"
DO_NOT_SYNC_TAINTS_LABEL = f"{GROUP}/do-not-sync-taints"
CAPACITY_TYPE_LABEL = f"{GROUP}/capacity-type"
RESERVATION_ID_LABEL = f"{GROUP}/reservation-id"

# Per-NodePool spot availability targets (annotations so no schema
# migration is needed; the env knobs KARPENTER_SPOT_MAX_FRACTION /
# KARPENTER_SPOT_MIN_ON_DEMAND give the fleet-wide defaults).
SPOT_MAX_FRACTION_ANNOTATION = f"{GROUP}/spot-max-fraction"
SPOT_MIN_ON_DEMAND_ANNOTATION = f"{GROUP}/spot-min-on-demand"

# Annotations
DO_NOT_DISRUPT_ANNOTATION = f"{GROUP}/do-not-disrupt"
NODEPOOL_HASH_ANNOTATION = f"{GROUP}/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION = f"{GROUP}/nodepool-hash-version"
NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION = f"{GROUP}/nodeclaim-termination-timestamp"
NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION = f"{GROUP}/nodeclaim-min-values-relaxed"
NODEPOOL_HASH_VERSION = "v3"

# Finalizers
TERMINATION_FINALIZER = f"{GROUP}/termination"

# Taints applied by the framework (reference taints.go:27-41)
DISRUPTED_TAINT_KEY = f"{GROUP}/disrupted"
UNREGISTERED_TAINT_KEY = f"{GROUP}/unregistered"
DISRUPTED_NO_SCHEDULE_TAINT = Taint(key=DISRUPTED_TAINT_KEY, effect="NoSchedule")
UNREGISTERED_NO_EXECUTE_TAINT = Taint(key=UNREGISTERED_TAINT_KEY, effect="NoExecute")

RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", GROUP})
LABEL_DOMAIN_EXCEPTIONS = frozenset({
    "kops.k8s.io",
    "node.kubernetes.io",
    "node-restriction.kubernetes.io",
})

WELL_KNOWN_LABELS = frozenset({
    NODEPOOL_LABEL,
    TOPOLOGY_ZONE_LABEL,
    TOPOLOGY_REGION_LABEL,
    INSTANCE_TYPE_LABEL,
    ARCH_LABEL,
    OS_LABEL,
    CAPACITY_TYPE_LABEL,
    WINDOWS_BUILD_LABEL,
})

WELL_KNOWN_VALUES_FOR_REQUIREMENTS: dict[str, frozenset[str]] = {
    CAPACITY_TYPE_LABEL: frozenset(
        {CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT, CAPACITY_TYPE_RESERVED}
    ),
}

WELL_KNOWN_LABELS_FOR_OFFERINGS = frozenset({TOPOLOGY_ZONE_LABEL, CAPACITY_TYPE_LABEL})

RESTRICTED_LABELS = frozenset({HOSTNAME_LABEL})

# Aliased -> canonical label translation (labels.go NormalizedLabels)
NORMALIZED_LABELS: dict[str, str] = {
    "failure-domain.beta.kubernetes.io/zone": TOPOLOGY_ZONE_LABEL,
    "failure-domain.beta.kubernetes.io/region": TOPOLOGY_REGION_LABEL,
    "beta.kubernetes.io/arch": ARCH_LABEL,
    "beta.kubernetes.io/os": OS_LABEL,
    "beta.kubernetes.io/instance-type": INSTANCE_TYPE_LABEL,
}


def label_domain(key: str) -> str:
    return key.split("/", 1)[0] if "/" in key else ""


def is_restricted_node_label(key: str) -> bool:
    """True if the framework must not inject this label onto nodes."""
    if key in RESTRICTED_LABELS:
        return True
    domain = label_domain(key)
    if not domain:
        return False
    if domain in LABEL_DOMAIN_EXCEPTIONS or any(
        domain.endswith("." + exc) for exc in LABEL_DOMAIN_EXCEPTIONS
    ):
        return False
    if key in WELL_KNOWN_LABELS:
        return False
    return domain in RESTRICTED_LABEL_DOMAINS or any(
        domain.endswith("." + rd) for rd in RESTRICTED_LABEL_DOMAINS
    )


def is_restricted_label(key: str) -> str | None:
    """Returns an error string if a user-supplied label key is restricted."""
    if key in WELL_KNOWN_LABELS:
        return None
    if is_restricted_node_label(key):
        return (
            f"label {key} is restricted; specify a well known label "
            f"or a custom label that does not use a restricted domain"
        )
    return None


def has_known_values(key: str, values: list[str]) -> str | None:
    """Error if a well-known requirement carries only unknown values."""
    known = WELL_KNOWN_VALUES_FOR_REQUIREMENTS.get(key)
    if known is None:
        return None
    if not any(v in known for v in values):
        return f"invalid values {values} for key {key}, expected one of {sorted(known)}"
    return None
