"""Admission-time spec validation — the CEL analogue.

The reference embeds its invariants as CEL rules in kubebuilder
markers (nodepool.go:39-41, nodeclaim.go:38-40,145,197-205) plus the
post-codegen patch scripts (hack/validation/{requirements,labels,
taint}.sh); the API server rejects bad specs before any controller
sees them. Here the in-memory client plays the API server, so the same
rules run as plain functions at create/update time and raise
InvalidError on violation.

Implemented rule set (reference source for each):
- requirements: valid operator; In needs values; Gt/Lt need exactly one
  non-negative integer; minValues in [1, 50] and <= len(values) for In;
  <= 100 requirements; restricted label keys rejected
  (nodeclaim.go:38-41,85-86; hack/validation/requirements.sh)
- template labels: restricted domains rejected
  (hack/validation/labels.sh)
- taints: non-empty key, valid effect (hack/validation/taint.sh)
- durations: expireAfter / consolidateAfter are "<n>(s|m|h)..." or
  "Never"; terminationGracePeriod never "Never" (nodeclaim.go:63,72)
- budgets: nodes is int or percentage; schedule only with duration;
  <= 50 budgets (nodepool.go:99-129)
- weight in [0, 10000] (nodepool.go:60-61 scaled; 0 = unset here)
- static pools: only limits.nodes; no weight; replicas >= 0; and the
  static/dynamic mode is immutable on update (nodepool.go:39-41)
- NodeClaim spec immutability lives in the client (nodeclaim.go:145)
"""

from __future__ import annotations

import re
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    NODEPOOL_LABEL,
    RESERVATION_ID_LABEL,
    is_restricted_label,
)

# keys the framework itself stamps onto claims (FinalizeScheduling adds
# the reservation-id pin, scheduling/nodeclaim.go:252); the reference
# admits them via its feature-gated WellKnownLabels extension
_SYSTEM_REQUIREMENT_KEYS = frozenset({RESERVATION_ID_LABEL})

VALID_OPERATORS = frozenset({"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"})
VALID_TAINT_EFFECTS = frozenset({"NoSchedule", "PreferNoSchedule", "NoExecute"})
_DURATION_RE = re.compile(r"^([0-9]+(s|m|h))+$")
_BUDGET_NODES_RE = re.compile(r"^((100|[0-9]{1,2})%|[0-9]+)$")
MAX_REQUIREMENTS = 100
MAX_BUDGETS = 50


class ValidationError(ValueError):
    """A spec the admission layer must reject."""


def _validate_duration(raw, field: str, allow_never: bool) -> Optional[str]:
    if raw is None:
        return None
    if isinstance(raw, (int, float)):
        return None  # already-parsed seconds (internal callers)
    if raw == "Never":
        return None if allow_never else f"{field}: 'Never' is not allowed"
    if not _DURATION_RE.match(str(raw)):
        return f"{field}: invalid duration {raw!r}"
    return None


def validate_requirements(requirements, field: str) -> list[str]:
    errs: list[str] = []
    if len(requirements) > MAX_REQUIREMENTS:
        errs.append(f"{field}: more than {MAX_REQUIREMENTS} requirements")
    for spec in requirements:
        where = f"{field}[{spec.key}]"
        if spec.key == NODEPOOL_LABEL:
            # well-known on nodes, but user specs may not constrain it
            # (hack/validation/labels.sh: 'karpenter.sh/nodepool' is
            # restricted — the system stamps it)
            errs.append(f"{where}: label {NODEPOOL_LABEL} is restricted")
        elif spec.key not in _SYSTEM_REQUIREMENT_KEYS:
            restricted = is_restricted_label(spec.key)
            if restricted:
                errs.append(f"{where}: {restricted}")
        if spec.operator not in VALID_OPERATORS:
            errs.append(f"{where}: unknown operator {spec.operator!r}")
            continue
        if spec.operator == "In" and not spec.values:
            errs.append(f"{where}: operator 'In' must have a value defined")
        if spec.operator in ("Gt", "Lt"):
            ok = len(spec.values) == 1
            if ok:
                try:
                    ok = int(spec.values[0]) >= 0
                except ValueError:
                    ok = False
            if not ok:
                errs.append(
                    f"{where}: operator '{spec.operator}' must have a "
                    "single positive integer value"
                )
        if spec.operator in ("Exists", "DoesNotExist") and spec.values:
            errs.append(
                f"{where}: operator '{spec.operator}' must not define values"
            )
        if spec.min_values is not None:
            if not 1 <= spec.min_values <= 50:
                errs.append(f"{where}: minValues must be in [1, 50]")
            elif spec.operator == "In" and len(spec.values) < spec.min_values:
                errs.append(
                    f"{where}: 'minValues' must have at least that many "
                    "values in 'values'"
                )
    return errs


def _validate_taints(taints, field: str) -> list[str]:
    errs = []
    for taint in taints:
        if not taint.key:
            errs.append(f"{field}: taint key must not be empty")
        if taint.effect not in VALID_TAINT_EFFECTS:
            errs.append(f"{field}: invalid taint effect {taint.effect!r}")
    return errs


def _validate_template(template) -> list[str]:
    errs = validate_requirements(
        template.spec.requirements, "spec.template.spec.requirements"
    )
    for key in template.labels:
        restricted = is_restricted_label(key)
        if restricted:
            errs.append(f"spec.template.labels[{key}]: {restricted}")
    errs += _validate_taints(template.spec.taints, "spec.template.spec.taints")
    errs += _validate_taints(
        template.spec.startup_taints, "spec.template.spec.startupTaints"
    )
    err = _validate_duration(
        template.spec.expire_after, "spec.template.spec.expireAfter",
        allow_never=True,
    )
    if err:
        errs.append(err)
    err = _validate_duration(
        template.spec.termination_grace_period,
        "spec.template.spec.terminationGracePeriod", allow_never=False,
    )
    if err:
        errs.append(err)
    return errs


def validate_node_pool(pool, old=None) -> None:
    """Admission check; raises ValidationError with every violation.
    `old` enables update-only (transition) rules."""
    errs = _validate_template(pool.spec.template)
    disruption = pool.spec.disruption
    err = _validate_duration(
        disruption.consolidate_after, "spec.disruption.consolidateAfter",
        allow_never=True,
    )
    if err:
        errs.append(err)
    if len(disruption.budgets) > MAX_BUDGETS:
        errs.append(f"spec.disruption.budgets: more than {MAX_BUDGETS} budgets")
    for i, budget in enumerate(disruption.budgets):
        where = f"spec.disruption.budgets[{i}]"
        if not _BUDGET_NODES_RE.match(str(budget.nodes)):
            errs.append(f"{where}.nodes: must be an integer or percentage")
        if (budget.schedule is None) != (budget.duration is None):
            errs.append(f"{where}: 'schedule' must be set with 'duration'")
        if budget.duration is not None:
            err = _validate_duration(budget.duration, f"{where}.duration",
                                     allow_never=False)
            if err:
                errs.append(err)
    if not 0 <= pool.spec.weight <= 10000:
        errs.append("spec.weight: must be in [0, 10000]")
    for key, value in pool.spec.limits.items():
        if value < 0:
            errs.append(f"spec.limits[{key}]: must be non-negative")
    if pool.is_static():
        if pool.spec.replicas < 0:
            errs.append("spec.replicas: must be non-negative")
        if pool.spec.weight:
            errs.append("'weight' is not supported on static NodePools")
        if pool.spec.limits and set(pool.spec.limits) != {"nodes"}:
            errs.append("only 'limits.nodes' is supported on static NodePools")
    if old is not None and (old.spec.replicas is None) != (
        pool.spec.replicas is None
    ):
        errs.append(
            "Cannot transition NodePool between static (replicas set) and "
            "dynamic (replicas unset) provisioning modes"
        )
    if errs:
        raise ValidationError("; ".join(errs))


def validate_node_claim(claim) -> None:
    """Admission check for NodeClaim create (spec immutability on
    update is enforced by the client, nodeclaim.go:145)."""
    errs = validate_requirements(claim.spec.requirements, "spec.requirements")
    errs += _validate_taints(claim.spec.taints, "spec.taints")
    errs += _validate_taints(claim.spec.startup_taints, "spec.startupTaints")
    err = _validate_duration(claim.spec.expire_after, "spec.expireAfter",
                             allow_never=True)
    if err:
        errs.append(err)
    err = _validate_duration(
        claim.spec.termination_grace_period, "spec.terminationGracePeriod",
        allow_never=False,
    )
    if err:
        errs.append(err)
    ref = claim.spec.node_class_ref
    if ref is not None:
        for attr in ("group", "kind", "name"):
            if not getattr(ref, attr, ""):
                errs.append(f"spec.nodeClassRef.{attr}: may not be empty")
    if errs:
        raise ValidationError("; ".join(errs))
