"""Admission-time spec validation — the CEL analogue.

The reference embeds its invariants as CEL rules in kubebuilder
markers (nodepool.go:39-41, nodeclaim.go:38-40,145,197-205) plus the
post-codegen patch scripts (hack/validation/{requirements,labels,
taint}.sh); the API server rejects bad specs before any controller
sees them. Here the in-memory client plays the API server, so the same
rules run as plain functions at create/update time and raise
InvalidError on violation.

Implemented rule set (reference source for each):
- requirements: valid operator; In needs values; Gt/Lt need exactly one
  non-negative integer; minValues in [1, 50] and <= len(values) for In;
  <= 100 requirements; restricted label keys rejected
  (nodeclaim.go:38-41,85-86; hack/validation/requirements.sh)
- template labels: restricted domains rejected
  (hack/validation/labels.sh)
- taints: non-empty key, valid effect (hack/validation/taint.sh)
- durations: expireAfter / consolidateAfter are "<n>(s|m|h)..." or
  "Never"; terminationGracePeriod never "Never" (nodeclaim.go:63,72)
- budgets: nodes is int or percentage; schedule only with duration;
  <= 50 budgets (nodepool.go:99-129)
- weight in [0, 10000] (nodepool.go:60-61 scaled; 0 = unset here)
- static pools: only limits.nodes; no weight; replicas >= 0; and the
  static/dynamic mode is immutable on update (nodepool.go:39-41)
- NodeClaim spec immutability lives in the client (nodeclaim.go:145)
"""

from __future__ import annotations

import re
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    NODEPOOL_LABEL,
    RESERVATION_ID_LABEL,
    is_restricted_label,
)

# keys the framework itself stamps onto claims (FinalizeScheduling adds
# the reservation-id pin, scheduling/nodeclaim.go:252); the reference
# admits them via its feature-gated WellKnownLabels extension
_SYSTEM_REQUIREMENT_KEYS = frozenset({RESERVATION_ID_LABEL})

VALID_OPERATORS = frozenset({"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"})
VALID_TAINT_EFFECTS = frozenset({"NoSchedule", "PreferNoSchedule", "NoExecute"})
VALID_CONSOLIDATION_POLICIES = frozenset(
    {"WhenEmpty", "WhenEmptyOrUnderutilized"}  # nodepool.go:92
)
VALID_BUDGET_REASONS = frozenset(
    {"Underutilized", "Empty", "Drifted"}  # nodepool.go:152
)
_DURATION_RE = re.compile(r"^([0-9]+(s|m|h))+$")
_BUDGET_NODES_RE = re.compile(r"^((100|[0-9]{1,2})%|[0-9]+)$")
# budget window length: hours/minutes only (nodepool.go:138)
_BUDGET_DURATION_RE = re.compile(r"^((([0-9]+(h|m))|([0-9]+h[0-9]+m))(0s)?)$")
# budget schedule: @-macros or 5-field cron (nodepool.go:129; the
# alternation is parenthesized as a whole so BOTH branches anchor)
_BUDGET_SCHEDULE_RE = re.compile(
    r"^(@(annually|yearly|monthly|weekly|daily|midnight|hourly)"
    r"|(.+\s){4}.+)$"
)
# label / taint qualified-name syntax (hack/validation/{labels,taint,
# requirements}.sh: key <= 316 chars with optional DNS-subdomain
# prefix; values <= 63 chars of [A-Za-z0-9-_.] with alnum ends)
_QUALIFIED_KEY_RE = re.compile(
    r"^([a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*(\/))?"
    r"([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]$"
)
_LABEL_VALUE_RE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")
MAX_REQUIREMENTS = 100
MAX_BUDGETS = 50
MAX_KEY_LENGTH = 316
MAX_VALUE_LENGTH = 63
MAX_TEMPLATE_LABELS = 100
MAX_WEIGHT = 100  # nodepool.go:60-61


def _validate_qualified_key(key: str, where: str) -> list[str]:
    errs = []
    if not key or len(key) > MAX_KEY_LENGTH:
        errs.append(f"{where}: key must be 1-{MAX_KEY_LENGTH} characters")
    elif not _QUALIFIED_KEY_RE.match(key):
        errs.append(f"{where}: key must be a qualified name")
    return errs


def _validate_label_value(value: str, where: str) -> list[str]:
    errs = []
    if len(value) > MAX_VALUE_LENGTH:
        errs.append(f"{where}: value must be at most {MAX_VALUE_LENGTH} characters")
    elif not _LABEL_VALUE_RE.match(value):
        errs.append(f"{where}: invalid label value syntax")
    return errs


class ValidationError(ValueError):
    """A spec the admission layer must reject."""


def _validate_duration(raw, field: str, allow_never: bool) -> Optional[str]:
    if raw is None:
        return None
    if isinstance(raw, (int, float)):
        return None  # already-parsed seconds (internal callers)
    if raw == "Never":
        return None if allow_never else f"{field}: 'Never' is not allowed"
    if not _DURATION_RE.match(str(raw)):
        return f"{field}: invalid duration {raw!r}"
    return None


def validate_requirements(requirements, field: str) -> list[str]:
    errs: list[str] = []
    if len(requirements) > MAX_REQUIREMENTS:
        errs.append(f"{field}: more than {MAX_REQUIREMENTS} requirements")
    for spec in requirements:
        where = f"{field}[{spec.key}]"
        errs += _validate_qualified_key(spec.key, where)
        for value in spec.values:
            # Gt/Lt operands are integers, exempt from label-value
            # syntax (they pass it anyway); In/NotIn values are labels
            errs += _validate_label_value(str(value), where)
        if spec.key == NODEPOOL_LABEL:
            # well-known on nodes, but user specs may not constrain it
            # (hack/validation/labels.sh: 'karpenter.sh/nodepool' is
            # restricted — the system stamps it)
            errs.append(f"{where}: label {NODEPOOL_LABEL} is restricted")
        elif spec.key not in _SYSTEM_REQUIREMENT_KEYS:
            restricted = is_restricted_label(spec.key)
            if restricted:
                errs.append(f"{where}: {restricted}")
        if spec.operator not in VALID_OPERATORS:
            errs.append(f"{where}: unknown operator {spec.operator!r}")
            continue
        if spec.operator == "In" and not spec.values:
            errs.append(f"{where}: operator 'In' must have a value defined")
        if spec.operator in ("Gt", "Lt"):
            ok = len(spec.values) == 1
            if ok:
                try:
                    ok = int(spec.values[0]) >= 0
                except ValueError:
                    ok = False
            if not ok:
                errs.append(
                    f"{where}: operator '{spec.operator}' must have a "
                    "single positive integer value"
                )
        if spec.operator in ("Exists", "DoesNotExist") and spec.values:
            errs.append(
                f"{where}: operator '{spec.operator}' must not define values"
            )
        if spec.min_values is not None:
            if not 1 <= spec.min_values <= 50:
                errs.append(f"{where}: minValues must be in [1, 50]")
            elif spec.operator == "In" and len(spec.values) < spec.min_values:
                errs.append(
                    f"{where}: 'minValues' must have at least that many "
                    "values in 'values'"
                )
    return errs


def _validate_taints(taints, field: str) -> list[str]:
    errs = []
    for taint in taints:
        if not taint.key:
            errs.append(f"{field}: taint key must not be empty")
        else:
            errs += _validate_qualified_key(taint.key, f"{field}[{taint.key}]")
        if taint.value:
            errs += _validate_label_value(
                taint.value, f"{field}[{taint.key}].value"
            )
        if taint.effect not in VALID_TAINT_EFFECTS:
            errs.append(f"{field}: invalid taint effect {taint.effect!r}")
    return errs


def _validate_template(template) -> list[str]:
    errs = validate_requirements(
        template.spec.requirements, "spec.template.spec.requirements"
    )
    if len(template.labels) > MAX_TEMPLATE_LABELS:
        errs.append(
            f"spec.template.labels: more than {MAX_TEMPLATE_LABELS} labels"
        )
    for key, value in template.labels.items():
        restricted = is_restricted_label(key)
        if restricted:
            errs.append(f"spec.template.labels[{key}]: {restricted}")
        errs += _validate_qualified_key(key, f"spec.template.labels[{key}]")
        errs += _validate_label_value(
            str(value), f"spec.template.labels[{key}]"
        )
    errs += _validate_taints(template.spec.taints, "spec.template.spec.taints")
    errs += _validate_taints(
        template.spec.startup_taints, "spec.template.spec.startupTaints"
    )
    err = _validate_duration(
        template.spec.expire_after, "spec.template.spec.expireAfter",
        allow_never=True,
    )
    if err:
        errs.append(err)
    err = _validate_duration(
        template.spec.termination_grace_period,
        "spec.template.spec.terminationGracePeriod", allow_never=False,
    )
    if err:
        errs.append(err)
    return errs


def validate_node_pool(pool, old=None) -> None:
    """Admission check; raises ValidationError with every violation.
    `old` enables update-only (transition) rules."""
    errs = _validate_template(pool.spec.template)
    disruption = pool.spec.disruption
    err = _validate_duration(
        disruption.consolidate_after, "spec.disruption.consolidateAfter",
        allow_never=True,
    )
    if err:
        errs.append(err)
    if disruption.consolidation_policy not in VALID_CONSOLIDATION_POLICIES:
        errs.append(
            "spec.disruption.consolidationPolicy: must be one of "
            f"{sorted(VALID_CONSOLIDATION_POLICIES)}"
        )
    if len(disruption.budgets) > MAX_BUDGETS:
        errs.append(f"spec.disruption.budgets: more than {MAX_BUDGETS} budgets")
    for i, budget in enumerate(disruption.budgets):
        where = f"spec.disruption.budgets[{i}]"
        if not _BUDGET_NODES_RE.match(str(budget.nodes)):
            errs.append(f"{where}.nodes: must be an integer or percentage")
        if (budget.schedule is None) != (budget.duration is None):
            errs.append(f"{where}: 'schedule' must be set with 'duration'")
        if budget.schedule is not None and not _BUDGET_SCHEDULE_RE.match(
            str(budget.schedule)
        ):
            errs.append(f"{where}.schedule: invalid cron schedule")
        if budget.duration is not None and not isinstance(
            budget.duration, (int, float)
        ) and not _BUDGET_DURATION_RE.match(str(budget.duration)):
            errs.append(
                f"{where}.duration: must be hours/minutes (e.g. 30m, 1h30m)"
            )
        if budget.reasons is not None:
            for reason in budget.reasons:
                if reason not in VALID_BUDGET_REASONS:
                    errs.append(
                        f"{where}.reasons: {reason!r} not in "
                        f"{sorted(VALID_BUDGET_REASONS)}"
                    )
    # reference weight is 1-100, nil = unset; 0 plays nil here. The cap
    # RATCHETS: it binds on create and on writes that change weight, so
    # an object stored under an older, wider rule stays updatable as
    # long as the weight itself is untouched
    weight_changed = old is None or old.spec.weight != pool.spec.weight
    if weight_changed and not 0 <= pool.spec.weight <= MAX_WEIGHT:
        errs.append(f"spec.weight: must be in [1, {MAX_WEIGHT}] (0 = unset)")
    for key, value in pool.spec.limits.items():
        if value < 0:
            errs.append(f"spec.limits[{key}]: must be non-negative")
    if pool.is_static():
        if pool.spec.replicas < 0:
            errs.append("spec.replicas: must be non-negative")
        if pool.spec.weight:
            errs.append("'weight' is not supported on static NodePools")
        if pool.spec.limits and set(pool.spec.limits) != {"nodes"}:
            errs.append("only 'limits.nodes' is supported on static NodePools")
    if old is not None:
        if (old.spec.replicas is None) != (pool.spec.replicas is None):
            errs.append(
                "Cannot transition NodePool between static (replicas set) "
                "and dynamic (replicas unset) provisioning modes"
            )
        # nodeClassRef group/kind immutability (nodepool.go:204-205)
        old_ref = old.spec.template.spec.node_class_ref
        new_ref = pool.spec.template.spec.node_class_ref
        if old_ref is not None and new_ref is not None:
            if getattr(old_ref, "group", "") != getattr(new_ref, "group", ""):
                errs.append("nodeClassRef.group is immutable")
            if getattr(old_ref, "kind", "") != getattr(new_ref, "kind", ""):
                errs.append("nodeClassRef.kind is immutable")
    if errs:
        raise ValidationError("; ".join(errs))


def validate_node_claim(claim) -> None:
    """Admission check for NodeClaim create (spec immutability on
    update is enforced by the client, nodeclaim.go:145)."""
    errs = validate_requirements(claim.spec.requirements, "spec.requirements")
    errs += _validate_taints(claim.spec.taints, "spec.taints")
    errs += _validate_taints(claim.spec.startup_taints, "spec.startupTaints")
    err = _validate_duration(claim.spec.expire_after, "spec.expireAfter",
                             allow_never=True)
    if err:
        errs.append(err)
    err = _validate_duration(
        claim.spec.termination_grace_period, "spec.terminationGracePeriod",
        allow_never=False,
    )
    if err:
        errs.append(err)
    ref = claim.spec.node_class_ref
    if ref is not None:
        for attr in ("group", "kind", "name"):
            if not getattr(ref, attr, ""):
                errs.append(f"spec.nodeClassRef.{attr}: may not be empty")
    if errs:
        raise ValidationError("; ".join(errs))
