"""CRD schema artifacts — the pkg/apis/crds analogue.

The reference ships generated CRD YAML whose openAPIV3Schema carries
every admission rule: kubebuilder markers become patterns/enums/
bounds, and hack/validation/*.sh patches in the CEL rules. This
runtime has no API server to install CRDs into, but the SCHEMA is
still the contract users program against — so the same rule corpus
that `validation.py` enforces at admission is emitted here as a
schema artifact, generated from the SAME constants (single source:
drift between the enforced rules and the published schema is a test
failure, mirroring `make verify` codegen checks).

Artifacts live at karpenter_tpu/apis/crds/karpenter.sh_{nodepools,
nodeclaims}.json; regenerate with `python -m karpenter_tpu.apis.crds`.
"""

from __future__ import annotations

import json
import os

from karpenter_tpu.apis.v1.validation import (
    MAX_BUDGETS,
    MAX_KEY_LENGTH,
    MAX_REQUIREMENTS,
    MAX_TEMPLATE_LABELS,
    MAX_VALUE_LENGTH,
    MAX_WEIGHT,
    VALID_BUDGET_REASONS,
    VALID_CONSOLIDATION_POLICIES,
    VALID_OPERATORS,
    VALID_TAINT_EFFECTS,
    _BUDGET_DURATION_RE,
    _BUDGET_NODES_RE,
    _BUDGET_SCHEDULE_RE,
    _DURATION_RE,
    _LABEL_VALUE_RE,
    _QUALIFIED_KEY_RE,
)

ARTIFACT_DIR = os.path.join(os.path.dirname(__file__), "crds")


def _requirement_schema() -> dict:
    """NodeSelectorRequirementWithMinValues (nodeclaim.go:80-89 plus
    the hack/validation/requirements.sh patches)."""
    return {
        "type": "array",
        "maxItems": MAX_REQUIREMENTS,
        "x-kubernetes-validations": [
            {"message": "requirements with operator 'In' must have a value defined",
             "rule": "self.all(x, x.operator == 'In' ? x.values.size() != 0 : true)"},
            {"message": "requirements operator 'Gt' or 'Lt' must have a single positive integer value",
             "rule": "self.all(x, (x.operator == 'Gt' || x.operator == 'Lt') ? (x.values.size() == 1 && int(x.values[0]) >= 0) : true)"},
            {"message": "requirements with 'minValues' must have at least that many values specified in the 'values' field",
             "rule": "self.all(x, (x.operator == 'In' && has(x.minValues)) ? x.values.size() >= x.minValues : true)"},
        ],
        "items": {
            "type": "object",
            "required": ["key", "operator"],
            "properties": {
                "key": {
                    "type": "string",
                    "maxLength": MAX_KEY_LENGTH,
                    "pattern": _QUALIFIED_KEY_RE.pattern,
                },
                "operator": {
                    "type": "string",
                    "enum": sorted(VALID_OPERATORS),
                },
                "values": {
                    "type": "array",
                    "items": {
                        "type": "string",
                        "maxLength": MAX_VALUE_LENGTH,
                        "pattern": _LABEL_VALUE_RE.pattern,
                    },
                },
                "minValues": {
                    "type": "integer", "minimum": 1, "maximum": 50,
                },
            },
        },
    }


def _taints_schema() -> dict:
    return {
        "type": "array",
        "items": {
            "type": "object",
            "required": ["key", "effect"],
            "properties": {
                "key": {
                    "type": "string",
                    "minLength": 1,
                    "pattern": _QUALIFIED_KEY_RE.pattern,
                },
                "value": {
                    "type": "string",
                    "pattern": _LABEL_VALUE_RE.pattern,
                },
                "effect": {
                    "type": "string",
                    "enum": sorted(VALID_TAINT_EFFECTS),
                },
            },
        },
    }


def _claim_spec_properties() -> dict:
    return {
        "requirements": _requirement_schema(),
        "taints": _taints_schema(),
        "startupTaints": _taints_schema(),
        "expireAfter": {
            "type": "string",
            "pattern": rf"^({_DURATION_RE.pattern[1:-1]}|Never)$",
        },
        "terminationGracePeriod": {
            "type": "string",
            "pattern": _DURATION_RE.pattern,
        },
        "nodeClassRef": {
            "type": "object",
            "required": ["group", "kind", "name"],
            "properties": {
                "group": {"type": "string"},
                "kind": {"type": "string"},
                "name": {"type": "string"},
            },
        },
    }


def nodeclaim_schema() -> dict:
    return {
        "group": "karpenter.sh",
        "kind": "NodeClaim",
        "versions": ["v1"],
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "spec": {
                    "type": "object",
                    "properties": _claim_spec_properties(),
                },
            },
        },
    }


def nodepool_schema() -> dict:
    return {
        "group": "karpenter.sh",
        "kind": "NodePool",
        "versions": ["v1"],
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "spec": {
                    "type": "object",
                    # the transition rules address spec fields, so they
                    # hang on the SPEC schema where `self` resolves
                    # them (nodepool.go:39-41 places the markers on the
                    # spec struct for the same reason)
                    "x-kubernetes-validations": [
                        {"message": "Cannot transition NodePool between static (replicas set) and dynamic (replicas unset) provisioning modes",
                         "rule": "has(self.replicas) == has(oldSelf.replicas)"},
                        {"message": "only 'limits.nodes' is supported on static NodePools",
                         "rule": "!has(self.replicas) || (!has(self.limits) || size(self.limits) == 0 || (size(self.limits) == 1 && 'nodes' in self.limits))"},
                        {"message": "'weight' is not supported on static NodePools",
                         "rule": "!has(self.replicas) || !has(self.weight)"},
                    ],
                    "properties": {
                        "weight": {
                            # 0 plays the reference's nil (= unset);
                            # 1-100 is the reference's declared range
                            "type": "integer",
                            "minimum": 0,
                            "maximum": MAX_WEIGHT,
                        },
                        "replicas": {"type": "integer", "minimum": 0},
                        "limits": {
                            "type": "object",
                            "additionalProperties": {"type": "number"},
                        },
                        "disruption": {
                            "type": "object",
                            "properties": {
                                "consolidateAfter": {
                                    "type": "string",
                                    "pattern": rf"^({_DURATION_RE.pattern[1:-1]}|Never)$",
                                },
                                "consolidationPolicy": {
                                    "type": "string",
                                    "enum": sorted(VALID_CONSOLIDATION_POLICIES),
                                },
                                "budgets": {
                                    "type": "array",
                                    "maxItems": MAX_BUDGETS,
                                    "x-kubernetes-validations": [
                                        {"message": "'schedule' must be set with 'duration'",
                                         "rule": "self.all(x, has(x.schedule) == has(x.duration))"},
                                    ],
                                    "items": {
                                        "type": "object",
                                        "properties": {
                                            "nodes": {
                                                "type": "string",
                                                "pattern": _BUDGET_NODES_RE.pattern,
                                            },
                                            "schedule": {
                                                "type": "string",
                                                "pattern": _BUDGET_SCHEDULE_RE.pattern,
                                            },
                                            "duration": {
                                                "type": "string",
                                                "pattern": _BUDGET_DURATION_RE.pattern,
                                            },
                                            "reasons": {
                                                "type": "array",
                                                "items": {
                                                    "type": "string",
                                                    "enum": sorted(VALID_BUDGET_REASONS),
                                                },
                                            },
                                        },
                                    },
                                },
                            },
                        },
                        "template": {
                            "type": "object",
                            "properties": {
                                "metadata": {
                                    "type": "object",
                                    "properties": {
                                        "labels": {
                                            "type": "object",
                                            "maxProperties": MAX_TEMPLATE_LABELS,
                                            "additionalProperties": {
                                                "type": "string",
                                                "maxLength": MAX_VALUE_LENGTH,
                                                "pattern": _LABEL_VALUE_RE.pattern,
                                            },
                                        },
                                    },
                                },
                                "spec": {
                                    "type": "object",
                                    "properties": _claim_spec_properties(),
                                },
                            },
                        },
                    },
                },
            },
        },
    }


ARTIFACTS = {
    "karpenter.sh_nodepools.json": nodepool_schema,
    "karpenter.sh_nodeclaims.json": nodeclaim_schema,
}


def render() -> dict[str, str]:
    return {
        name: json.dumps(fn(), indent=2, sort_keys=True) + "\n"
        for name, fn in ARTIFACTS.items()
    }


def write(directory: str = ARTIFACT_DIR) -> None:
    os.makedirs(directory, exist_ok=True)
    for name, content in render().items():
        with open(os.path.join(directory, name), "w") as fh:
            fh.write(content)


if __name__ == "__main__":  # pragma: no cover
    write()
    print(f"wrote {len(ARTIFACTS)} artifacts to {ARTIFACT_DIR}")
