"""NodeOverlay (alpha): runtime overrides of instance-type attributes.

Counterpart of pkg/apis/v1alpha1/nodeoverlay.go + the overlay store and
cloudprovider decorator (pkg/controllers/nodeoverlay/store.go:47-260,
pkg/cloudprovider/overlay/cloudprovider.go:30-60): operator-supplied
price overrides / adjustments and extended-capacity injection, selected
by requirements, with weight-based conflict resolution.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.apis.v1.condition import ConditionSet
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    Offering,
    Offerings,
)
from karpenter_tpu.kube.objects import NodeSelectorRequirement, ObjectMeta
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.utils.resources import ResourceList


@dataclass
class NodeOverlaySpec:
    requirements: list[NodeSelectorRequirement] = field(default_factory=list)
    price_adjustment: Optional[str] = None  # "+0.5" | "-1.2" | "+10%" | "-5%"
    price: Optional[str] = None             # absolute override
    capacity: ResourceList = field(default_factory=dict)  # extended resources only
    weight: int = 0


COND_OVERLAY_VALIDATION = "ValidationSucceeded"


@dataclass
class NodeOverlay:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeOverlaySpec = field(default_factory=NodeOverlaySpec)
    status_conditions: ConditionSet = field(
        default_factory=lambda: ConditionSet(
            root_types=[COND_OVERLAY_VALIDATION]
        )
    )

    kind = "NodeOverlay"

    @property
    def key(self) -> str:
        return self.metadata.name


def adjusted_price(base: float, change: Optional[str]) -> float:
    """types.go:369-401: percent or absolute signed adjustment,
    clamped at zero."""
    if not change:
        return base
    if change.endswith("%"):
        out = base * (1 + float(change[:-1]) / 100.0)
    else:
        out = base + float(change)
    return max(0.0, out)


class OverlayStore:
    """Immutable snapshot applying overlays to instance types
    (store.go:47-260). Overlays sorted by weight descending; the
    heaviest matching overlay wins per attribute."""

    def __init__(self, overlays: list[NodeOverlay], snapshot: bool = True):
        # snapshot the SPECS: a controller-owned store must be immutable
        # under overlay churn (store.go's internal store is rebuilt,
        # never mutated) — holding live references would leak spec edits
        # into an already-taken snapshot between controller passes. The
        # lazy read-through path (no controller) builds a throwaway
        # store per call and skips the copy.
        if snapshot:
            import copy

            overlays = [copy.deepcopy(o) for o in overlays]
        self.overlays = sorted(
            overlays, key=lambda o: (-o.spec.weight, o.metadata.name)
        )
        # parse each overlay's selector once; matching runs per
        # (instance type x offering) on the scheduler hot path
        self._overlay_reqs = [
            Requirements.from_node_selector_requirements(o.spec.requirements)
            for o in self.overlays
        ]
        # applied-result memo keyed by input object identity (the
        # stored input ref keeps the id valid). A store is an immutable
        # snapshot — rebuilt, never mutated, when overlays change — so
        # the memo's lifetime is exactly the window the applied result
        # stays correct. This keeps output OBJECT IDENTITY stable
        # across calls, which the solver's encoder cache fingerprints
        # on: without it, every overlay-touched tick rebuilds the
        # whole catalog's InstanceTypes and busts the cache.
        self._applied: dict[int, tuple[InstanceType, InstanceType]] = {}

    def _matching(self, it: InstanceType, offering: Offering) -> list[NodeOverlay]:
        out = []
        combined = it.requirements.copy()
        combined.add(*offering.requirements.values())
        for overlay, reqs in zip(self.overlays, self._overlay_reqs):
            if combined.intersects(reqs) is None:
                out.append(overlay)
        return out

    def apply(self, it: InstanceType) -> InstanceType:
        hit = self._applied.get(id(it))
        if hit is not None and hit[0] is it:
            return hit[1]
        out = self._apply(it)
        self._applied[id(it)] = (it, out)
        return out

    def _apply(self, it: InstanceType) -> InstanceType:
        new_offerings = Offerings()
        price_touched = False
        capacity_extra: ResourceList = {}
        for offering in it.offerings:
            price = offering.price
            applied_price = False
            for overlay in self._matching(it, offering):
                if not applied_price and overlay.spec.price is not None:
                    price = max(0.0, float(overlay.spec.price))
                    applied_price = True
                elif not applied_price and overlay.spec.price_adjustment is not None:
                    price = adjusted_price(price, overlay.spec.price_adjustment)
                    applied_price = True
                # extended resources merge across overlays, heaviest
                # writer wins per key (store.go:173-176)
                for key, value in overlay.spec.capacity.items():
                    if key not in it.capacity and key not in capacity_extra:
                        capacity_extra[key] = value
            price_touched = price_touched or applied_price
            new_offerings.append(
                Offering(
                    requirements=offering.requirements,
                    price=price,
                    available=offering.available,
                    reservation_capacity=offering.reservation_capacity,
                )
            )
        if not price_touched and not capacity_extra:
            return it
        capacity = dict(it.capacity)
        capacity.update(capacity_extra)
        return InstanceType(
            name=it.name,
            requirements=it.requirements,
            offerings=new_offerings,
            capacity=capacity,
            overhead=it.overhead,
        )


# well-known resources an overlay may NOT override — capacity injection
# is for EXTENDED resources only (nodeoverlay_validation.go:50-57)
WELL_KNOWN_RESOURCES = frozenset(
    ("cpu", "memory", "pods", "ephemeral-storage", "hugepages-2Mi",
     "hugepages-1Gi")
)
_VALID_OPERATORS = frozenset(
    ("In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt")
)


def runtime_validate(overlay: NodeOverlay) -> Optional[str]:
    """RuntimeValidate (nodeoverlay_validation.go:31-57): the rules a
    webhook would enforce beyond CRD schema — requirement operator
    sanity, capacity restricted to extended resources, parseable price
    fields. Returns a reason string, or None when valid."""
    spec = overlay.spec
    for req in spec.requirements:
        if req.operator not in _VALID_OPERATORS:
            return f"invalid operator {req.operator!r} for key {req.key}"
        if req.operator in ("In", "NotIn") and not req.values:
            return (
                f"key {req.key} with operator {req.operator} must have a "
                f"value defined"
            )
    for resource in spec.capacity:
        if resource in WELL_KNOWN_RESOURCES:
            return f"invalid capacity: {resource} is restricted"
    if not 0 <= spec.weight <= 100:
        # same bound the published CRD schema enforces at admission —
        # simulation and cluster behavior must agree
        return f"weight {spec.weight} out of range [0, 100]"
    if spec.price is not None and spec.price_adjustment is not None:
        return "price and priceAdjustment are mutually exclusive"
    import math

    if spec.price is not None:
        try:
            value = float(spec.price)
        except ValueError:
            return f"price {spec.price!r} is not a number"
        if not math.isfinite(value) or value < 0:
            # nan slips past a `< 0` check and max(0, nan) zero-prices
            # every matched offering downstream
            return f"price {spec.price!r} must be a non-negative number"
    if spec.price_adjustment is not None:
        raw = spec.price_adjustment
        body = raw[:-1] if raw.endswith("%") else raw
        try:
            value = float(body)
        except ValueError:
            return f"priceAdjustment {raw!r} is malformed"
        if not math.isfinite(value):
            return f"priceAdjustment {raw!r} must be finite"
    return None


def detect_conflicts(
    overlays: list[NodeOverlay],
    instance_types_by_pool: dict[Optional[str], list[InstanceType]],
) -> dict[str, str]:
    """Conflicts against ACTUAL instance types, the reference's
    semantics (store.go:185-258 + controller.go:144-160): walk overlays
    in descending weight (name-ascending on ties), record which overlay
    last wrote each (pool, instance, offering) price cell and each
    (pool, instance, capacity-resource) cell, and flag an overlay that
    writes a cell already written AT THE SAME WEIGHT by a different
    overlay — regardless of the value; equal-weight double-writes are
    ambiguous by definition. A flagged overlay is excluded from the
    store ENTIRELY (atomicity: validate-then-store,
    controller.go:152-159). Selector algebra alone would flag overlays
    whose selectors intersect but never co-match a real offering; the
    concrete evaluation does not."""
    ordered = sorted(
        overlays, key=lambda o: (-o.spec.weight, o.metadata.name)
    )
    reqs = {
        o.metadata.name: Requirements.from_node_selector_requirements(
            o.spec.requirements
        )
        for o in ordered
    }
    conflicts: dict[str, str] = {}
    # cell -> (weight, overlay name) of the last writer
    price_writer: dict[tuple, tuple[int, str]] = {}
    capacity_writer: dict[tuple, tuple[int, str]] = {}
    for overlay in ordered:
        name = overlay.metadata.name
        writes_price = (
            overlay.spec.price is not None
            or overlay.spec.price_adjustment is not None
        )
        clash: Optional[str] = None
        touched_price: list[tuple] = []
        touched_capacity: list[tuple] = []
        for pool_name, its in instance_types_by_pool.items():
            for it in its:
                combined_base = it.requirements
                for offering in it.offerings:
                    combined = combined_base.copy()
                    combined.add(*offering.requirements.values())
                    if combined.intersects(reqs[name]) is not None:
                        continue
                    if writes_price:
                        cell = (pool_name, it.name,
                                offering.zone, offering.capacity_type,
                                offering.reservation_id)
                        prior = price_writer.get(cell)
                        if (
                            prior is not None
                            and prior[0] == overlay.spec.weight
                            and prior[1] != name
                        ):
                            clash = (
                                f"price conflicts with {prior[1]} at weight "
                                f"{overlay.spec.weight} on {it.name}"
                            )
                            break
                        touched_price.append(cell)
                    for resource in overlay.spec.capacity:
                        cell = (pool_name, it.name, resource)
                        prior = capacity_writer.get(cell)
                        if (
                            prior is not None
                            and prior[0] == overlay.spec.weight
                            and prior[1] != name
                        ):
                            clash = (
                                f"capacity {resource} conflicts with "
                                f"{prior[1]} at weight {overlay.spec.weight} "
                                f"on {it.name}"
                            )
                            break
                        touched_capacity.append(cell)
                    if clash:
                        break
                if clash:
                    break
            if clash:
                break
        if clash:
            conflicts[name] = clash
            continue  # atomic: none of its writes land
        # record this overlay as the LATEST writer of its cells: the
        # heaviest writer owns the value (apply() honors that), while
        # clash checks above compare against the most recent — i.e.
        # lowest-so-far — weight, exactly the reference's lowestWeight
        # tracking (store.go:198-205, 232-246)
        for cell in touched_price:
            price_writer[cell] = (overlay.spec.weight, name)
        for cell in touched_capacity:
            capacity_writer[cell] = (overlay.spec.weight, name)
    return conflicts


class UnevaluatedNodePoolError(Exception):
    """GetInstanceTypes called for a pool the overlay controller has
    not evaluated yet (store.go:64-67, 121-124) — new pools stay gated
    until the next controller pass; the provisioner skips them."""


class NodeOverlayController:
    """Singleton revalidation loop (controller.go:69-160): runtime-
    validates every overlay, detects conflicts against each pool's
    ACTUAL instance types, publishes results to overlay status
    conditions and Warning events, then atomically swaps an immutable
    snapshot (valid overlays + the evaluated-pool set) into the
    decorator and marks the cluster unconsolidated so consolidation
    re-evaluates against the new prices."""

    # full re-evaluation cadence with an unchanged input set — catches
    # provider catalog drift the object watch can't see (the reference
    # requeues on a long timer, controller.go:120 RequeueAfter)
    REEVALUATE_SECONDS = 6 * 3600.0

    def __init__(self, kube, provider: "OverlayCloudProvider",
                 recorder=None, cluster=None):
        self.kube = kube
        self.provider = provider
        self.recorder = recorder
        self.cluster = cluster
        self._fingerprint: Optional[tuple] = None
        self._evaluated_at = 0.0
        provider.gated = True  # serve only controller snapshots

    def _publish(self, overlay: NodeOverlay, reason: str, message: str,
                 now: Optional[float]) -> None:
        changed = overlay.status_conditions.set_false(
            COND_OVERLAY_VALIDATION, reason=reason, message=message, now=now
        )
        if changed:
            # announce the transition (and push it to a real API server)
            self.kube.touch(overlay)
        if self.recorder is not None:
            from karpenter_tpu.events.recorder import Event

            self.recorder.publish(Event(
                kind="NodeOverlay", name=overlay.metadata.name,
                type="Warning", reason=reason, message=message,
            ), now=now)

    def reconcile(self, now: Optional[float] = None) -> None:
        import time as _time

        overlays = list(self.kube.list("NodeOverlay"))
        # deleting pools stay evaluated: their nodes serve (and may be
        # disrupted/priced) until they are actually gone — permanent
        # gating would wedge disruption's price lookups for them
        pools = list(self.kube.list("NodePool"))
        # change detection: re-evaluation is O(overlays x pools x
        # catalog); skip it while the input objects are unchanged (the
        # reference controller is watch-triggered), re-running on a
        # long timer to catch provider catalog drift
        def current_fingerprint():
            return (
                tuple(sorted(
                    (o.metadata.name, o.metadata.resource_version)
                    for o in overlays
                )),
                tuple(sorted(
                    (p.metadata.name, p.metadata.resource_version)
                    for p in pools
                )),
            )

        wall = _time.monotonic()
        if (
            current_fingerprint() == self._fingerprint
            and wall - self._evaluated_at < self.REEVALUATE_SECONDS
        ):
            return
        # conflict evaluation runs against the RAW catalog (the inner
        # provider) per pool — reserved offerings are injected per pool,
        # so an overlay targeting them must be validated per pool
        # (controller.go:144-150). A pool whose catalog fetch FAILS is
        # neither conflict-checked nor marked evaluated: degrading to
        # "no conflicts" would open the gate on an unchecked snapshot.
        inner = self.provider.inner
        its_by_pool: dict[Optional[str], list[InstanceType]] = {}
        fetch_failed: set[str] = set()
        for pool in pools:
            try:
                its_by_pool[pool.metadata.name] = inner.get_instance_types(pool)
            except Exception:
                fetch_failed.add(pool.metadata.name)
        if not pools:
            # poolless (direct/simulation) use still needs a catalog to
            # validate against
            try:
                its_by_pool[None] = inner.get_instance_types(None)
            except Exception:
                return  # no catalog at all: keep the previous snapshot

        valid: list[NodeOverlay] = []
        evaluatable: list[NodeOverlay] = []
        for overlay in overlays:
            reason = runtime_validate(overlay)
            if reason is not None:
                self._publish(overlay, "ValidationFailed", reason, now)
            else:
                evaluatable.append(overlay)
        conflicts = detect_conflicts(evaluatable, its_by_pool)
        for overlay in evaluatable:
            message = conflicts.get(overlay.metadata.name)
            if message:
                self._publish(overlay, "Conflict", message, now)
            else:
                if overlay.status_conditions.set_true(
                    COND_OVERLAY_VALIDATION, now=now
                ):
                    self.kube.touch(overlay)
                valid.append(overlay)
        self.provider.set_store(
            OverlayStore(valid),
            evaluated_pools={
                p.metadata.name for p in pools
                if p.metadata.name not in fetch_failed
            },
        )
        if not fetch_failed:
            # re-read AFTER the touch loop above bumped overlay rvs —
            # storing the pre-touch fingerprint would force one wasted
            # full re-evaluation (and a spurious unconsolidated mark)
            # on the very next pass. A pass with failed fetches commits
            # nothing, so the gated pools are retried next tick instead
            # of staying gated for REEVALUATE_SECONDS.
            self._fingerprint = current_fingerprint()
            self._evaluated_at = wall
        if self.cluster is not None:
            # prices moved: force consolidation to re-evaluate
            # (controller.go:119 MarkUnconsolidated) — only on a real
            # snapshot swap, never on the per-tick no-op path above
            self.cluster.mark_unconsolidated(now=now)


class OverlayCloudProvider(CloudProvider):
    """Decorator applying the overlay store to GetInstanceTypes
    (overlay/cloudprovider.go:30-60). Serves the controller's snapshot;
    before the first evaluation — and per pool, for pools created AFTER
    the snapshot was built — requests are gated behind
    UnevaluatedNodePoolError (store.go:64-67)."""

    def __init__(self, inner: CloudProvider, kube):
        self.inner = inner
        self.kube = kube
        self._snapshot: Optional[OverlayStore] = None
        self._evaluated_pools: set[str] = set()
        # set by NodeOverlayController: once a controller owns this
        # decorator, only its snapshots are served (the reference's
        # UnevaluatedNodePoolError gate); standalone use builds lazily
        self.gated = False

    def set_store(self, store: OverlayStore,
                  evaluated_pools: Optional[set[str]] = None) -> None:
        self._evaluated_pools = set(evaluated_pools or ())
        self._snapshot = store

    def _store(self, node_pool: Optional[NodePool]) -> OverlayStore:
        if self._snapshot is not None:
            if (
                self.gated
                and node_pool is not None
                and node_pool.metadata.name not in self._evaluated_pools
            ):
                # a pool created after the snapshot: its (possibly
                # reserved) offerings were never conflict-checked —
                # gate it until the next controller pass
                raise UnevaluatedNodePoolError(
                    f"node pool {node_pool.metadata.name} not yet evaluated"
                )
            return self._snapshot
        if self.gated:
            raise UnevaluatedNodePoolError("node overlays not yet evaluated")
        # standalone (no controller): read-through, no caching
        return OverlayStore(list(self.kube.list("NodeOverlay")), snapshot=False)

    def get_instance_types(self, node_pool: Optional[NodePool]) -> list[InstanceType]:
        store = self._store(node_pool)
        return [store.apply(it) for it in self.inner.get_instance_types(node_pool)]

    # passthrough SPI
    def create(self, node_claim):
        return self.inner.create(node_claim)

    def delete(self, node_claim):
        return self.inner.delete(node_claim)

    def get(self, provider_id):
        return self.inner.get(provider_id)

    def list(self):
        return self.inner.list()

    def is_drifted(self, node_claim):
        return self.inner.is_drifted(node_claim)

    def repair_policies(self):
        return self.inner.repair_policies()

    # spot-tier hooks (optional on the SPI)
    def reprice(self, now):
        fn = getattr(self.inner, "reprice", None)
        return 0 if fn is None else fn(now)

    def poll_interruptions(self, now=None):
        fn = getattr(self.inner, "poll_interruptions", None)
        return [] if fn is None else fn(now)

    @property
    def interrupted(self):
        return getattr(self.inner, "interrupted", set())

    def name(self):
        return self.inner.name()

    def get_supported_node_classes(self):
        return self.inner.get_supported_node_classes()
