"""NodeOverlay (alpha): runtime overrides of instance-type attributes.

Counterpart of pkg/apis/v1alpha1/nodeoverlay.go + the overlay store and
cloudprovider decorator (pkg/controllers/nodeoverlay/store.go:47-260,
pkg/cloudprovider/overlay/cloudprovider.go:30-60): operator-supplied
price overrides / adjustments and extended-capacity injection, selected
by requirements, with weight-based conflict resolution.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.apis.v1.condition import ConditionSet
from karpenter_tpu.apis.v1.nodepool import NodePool
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    Offering,
    Offerings,
)
from karpenter_tpu.kube.objects import NodeSelectorRequirement, ObjectMeta
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.utils.resources import ResourceList


@dataclass
class NodeOverlaySpec:
    requirements: list[NodeSelectorRequirement] = field(default_factory=list)
    price_adjustment: Optional[str] = None  # "+0.5" | "-1.2" | "+10%" | "-5%"
    price: Optional[str] = None             # absolute override
    capacity: ResourceList = field(default_factory=dict)  # extended resources only
    weight: int = 0


COND_OVERLAY_VALIDATION = "ValidationSucceeded"


@dataclass
class NodeOverlay:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeOverlaySpec = field(default_factory=NodeOverlaySpec)
    status_conditions: ConditionSet = field(
        default_factory=lambda: ConditionSet(
            root_types=[COND_OVERLAY_VALIDATION]
        )
    )

    kind = "NodeOverlay"

    @property
    def key(self) -> str:
        return self.metadata.name


def adjusted_price(base: float, change: Optional[str]) -> float:
    """types.go:369-401: percent or absolute signed adjustment,
    clamped at zero."""
    if not change:
        return base
    if change.endswith("%"):
        out = base * (1 + float(change[:-1]) / 100.0)
    else:
        out = base + float(change)
    return max(0.0, out)


class OverlayStore:
    """Immutable snapshot applying overlays to instance types
    (store.go:47-260). Overlays sorted by weight descending; the
    heaviest matching overlay wins per attribute."""

    def __init__(self, overlays: list[NodeOverlay]):
        self.overlays = sorted(
            overlays, key=lambda o: (-o.spec.weight, o.metadata.name)
        )
        # parse each overlay's selector once; matching runs per
        # (instance type x offering) on the scheduler hot path
        self._overlay_reqs = [
            Requirements.from_node_selector_requirements(o.spec.requirements)
            for o in self.overlays
        ]

    def _matching(self, it: InstanceType, offering: Offering) -> list[NodeOverlay]:
        out = []
        combined = it.requirements.copy()
        combined.add(*offering.requirements.values())
        for overlay, reqs in zip(self.overlays, self._overlay_reqs):
            if combined.intersects(reqs) is None:
                out.append(overlay)
        return out

    def apply(self, it: InstanceType) -> InstanceType:
        new_offerings = Offerings()
        price_touched = False
        capacity_extra: ResourceList = {}
        for offering in it.offerings:
            price = offering.price
            applied_price = False
            for overlay in self._matching(it, offering):
                if not applied_price and overlay.spec.price is not None:
                    price = max(0.0, float(overlay.spec.price))
                    applied_price = True
                elif not applied_price and overlay.spec.price_adjustment is not None:
                    price = adjusted_price(price, overlay.spec.price_adjustment)
                    applied_price = True
                # extended resources merge across overlays, heaviest
                # writer wins per key (store.go:173-176)
                for key, value in overlay.spec.capacity.items():
                    if key not in it.capacity and key not in capacity_extra:
                        capacity_extra[key] = value
            price_touched = price_touched or applied_price
            new_offerings.append(
                Offering(
                    requirements=offering.requirements,
                    price=price,
                    available=offering.available,
                    reservation_capacity=offering.reservation_capacity,
                )
            )
        if not price_touched and not capacity_extra:
            return it
        capacity = dict(it.capacity)
        capacity.update(capacity_extra)
        return InstanceType(
            name=it.name,
            requirements=it.requirements,
            offerings=new_offerings,
            capacity=capacity,
            overhead=it.overhead,
        )


def detect_conflicts(overlays: list[NodeOverlay]) -> dict[str, str]:
    """Equal-weight overlays that can select the same instances AND
    write the same attribute with different values conflict; the
    lexicographically-later one is flagged (nodeoverlay/controller.go
    conflict detection by weight)."""
    conflicts: dict[str, str] = {}
    by_weight: dict[int, list[NodeOverlay]] = {}
    for o in overlays:
        by_weight.setdefault(o.spec.weight, []).append(o)
    for weight, group in by_weight.items():
        group = sorted(group, key=lambda o: o.metadata.name)
        reqs = {
            o.metadata.name: Requirements.from_node_selector_requirements(
                o.spec.requirements
            )
            for o in group
        }
        for i, a in enumerate(group):
            for b in group[i + 1 :]:
                # disjoint selectors can never target the same instance
                if reqs[a.metadata.name].intersects(reqs[b.metadata.name]) is not None:
                    continue
                a_price = a.spec.price is not None or a.spec.price_adjustment is not None
                b_price = b.spec.price is not None or b.spec.price_adjustment is not None
                price_conflict = (
                    a_price and b_price
                    and (a.spec.price, a.spec.price_adjustment)
                    != (b.spec.price, b.spec.price_adjustment)
                )
                capacity_conflict = any(
                    a.spec.capacity[k] != b.spec.capacity[k]
                    for k in set(a.spec.capacity) & set(b.spec.capacity)
                )
                if price_conflict or capacity_conflict:
                    conflicts[b.metadata.name] = (
                        f"conflicts with {a.metadata.name} at weight {weight}"
                    )
    return conflicts


class UnevaluatedNodePoolError(Exception):
    """GetInstanceTypes called before the overlay controller produced
    its first store snapshot (nodeoverlay/controller.go:69-140) — the
    provisioner skips the pool until evaluation completes."""


class NodeOverlayController:
    """Singleton revalidation loop: builds immutable store snapshots
    from the live overlays, flags conflicts via status conditions, and
    hands the snapshot to the decorator (controller.go:69-140)."""

    def __init__(self, kube, provider: "OverlayCloudProvider"):
        self.kube = kube
        self.provider = provider
        provider.gated = True  # serve only controller snapshots

    def reconcile(self, now: Optional[float] = None) -> None:
        overlays = list(self.kube.list("NodeOverlay"))
        conflicts = detect_conflicts(overlays)
        valid = []
        for overlay in overlays:
            reason = conflicts.get(overlay.metadata.name)
            if reason:
                overlay.status_conditions.set_false(
                    COND_OVERLAY_VALIDATION, reason="Conflict", message=reason,
                    now=now,
                )
            else:
                overlay.status_conditions.set_true(
                    COND_OVERLAY_VALIDATION, now=now
                )
                valid.append(overlay)
        self.provider.set_store(OverlayStore(valid))


class OverlayCloudProvider(CloudProvider):
    """Decorator applying the overlay store to GetInstanceTypes
    (overlay/cloudprovider.go:30-60). Serves the controller's snapshot;
    before the first evaluation, pools are gated behind
    UnevaluatedNodePoolError."""

    def __init__(self, inner: CloudProvider, kube):
        self.inner = inner
        self.kube = kube
        self._snapshot: Optional[OverlayStore] = None
        # set by NodeOverlayController: once a controller owns this
        # decorator, only its snapshots are served (the reference's
        # UnevaluatedNodePoolError gate); standalone use builds lazily
        self.gated = False

    def set_store(self, store: OverlayStore) -> None:
        self._snapshot = store

    def _store(self) -> OverlayStore:
        if self._snapshot is not None:
            return self._snapshot
        if self.gated:
            raise UnevaluatedNodePoolError("node overlays not yet evaluated")
        # standalone (no controller): read-through, no caching
        return OverlayStore(list(self.kube.list("NodeOverlay")))

    def get_instance_types(self, node_pool: Optional[NodePool]) -> list[InstanceType]:
        store = self._store()
        return [store.apply(it) for it in self.inner.get_instance_types(node_pool)]

    # passthrough SPI
    def create(self, node_claim):
        return self.inner.create(node_claim)

    def delete(self, node_claim):
        return self.inner.delete(node_claim)

    def get(self, provider_id):
        return self.inner.get(provider_id)

    def list(self):
        return self.inner.list()

    def is_drifted(self, node_claim):
        return self.inner.is_drifted(node_claim)

    def repair_policies(self):
        return self.inner.repair_policies()

    def name(self):
        return self.inner.name()

    def get_supported_node_classes(self):
        return self.inner.get_supported_node_classes()
