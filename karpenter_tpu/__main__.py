"""Runnable operator binary: `python -m karpenter_tpu`.

Counterpart of kwok/main.go:29-51 — wire flags/env into Options, build
the kwok simulation provider over a store, construct the Operator with
the full controller set, mount observability, and run until signalled.

The store is the in-memory API server (kube/client.py) with optional
checkpoint persistence: `--state-file` loads existing state on boot
(the provider rehydrates its instances from claims, the
checkpoint/resume analogue) and saves on shutdown. A real-cluster
adapter can replace the store behind the same KubeClient interface.

Demo mode (`--demo N`) seeds a default NodePool and N pending pods so
a first run visibly provisions nodes and binds pods:

    python -m karpenter_tpu --demo 50 --run-seconds 15 --log-level info
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    # flag names mirror pkg/operator/options/options.go:67-131; env
    # fallbacks use the reference's env names where they exist
    p = argparse.ArgumentParser(
        prog="karpenter_tpu",
        description="TPU-native node autoscaler (kwok simulation provider)",
    )
    p.add_argument("--cluster-name",
                   default=os.environ.get("CLUSTER_NAME", "kwok-cluster"))
    p.add_argument("--metrics-port", type=int,
                   default=int(os.environ.get("METRICS_PORT", "8080")))
    p.add_argument("--metrics-bind-host",
                   default=os.environ.get("METRICS_BIND_HOST", "0.0.0.0"),
                   help="bind address for /metrics, /healthz, /readyz")
    p.add_argument("--batch-idle-duration", type=float, default=1.0)
    p.add_argument("--batch-max-duration", type=float, default=10.0)
    p.add_argument("--preference-policy", choices=("Respect", "Ignore"),
                   default="Respect")
    p.add_argument("--min-values-policy", choices=("Strict", "BestEffort"),
                   default="Strict")
    p.add_argument("--feature-gates",
                   default=os.environ.get("FEATURE_GATES", ""),
                   help='e.g. "SpotToSpotConsolidation=true,NodeRepair=true"')
    p.add_argument("--log-level", default=os.environ.get("LOG_LEVEL", "info"),
                   choices=("debug", "info", "warning", "error"))
    p.add_argument("--enable-profiling", action="store_true")
    p.add_argument("--leader-elect", action="store_true",
                   help="standby unless holding the lease (active/passive HA)")
    p.add_argument("--identity", default=os.environ.get("HOSTNAME", "karpenter-0"))
    p.add_argument("--registration-delay", type=float, default=0.0,
                   help="seconds a kwok instance takes to register as a Node")
    p.add_argument("--state-file", default="",
                   help="checkpoint path: load on boot, save on shutdown")
    p.add_argument("--api-server", default=os.environ.get("KUBE_API_SERVER", ""),
                   help="real API server URL; empty = in-memory store")
    p.add_argument("--api-token-file",
                   default=os.environ.get("KUBE_TOKEN_FILE", ""),
                   help="bearer token file for --api-server")
    p.add_argument("--api-ca-file", default=os.environ.get("KUBE_CA_FILE", ""),
                   help="CA bundle for --api-server TLS")
    p.add_argument("--solver-endpoint",
                   default=os.environ.get("KARPENTER_SOLVER_ENDPOINT", ""),
                   help="gRPC solver service (TPU hosts); empty = in-process")
    p.add_argument("--solver-shards", type=int,
                   default=int(os.environ.get("KARPENTER_SOLVER_SHARDS", "0") or 0))
    p.add_argument("--tick-seconds", type=float, default=1.0)
    p.add_argument("--run-seconds", type=float, default=0.0,
                   help="exit after this many seconds (0 = run forever)")
    p.add_argument("--demo", type=int, default=0, metavar="N",
                   help="seed a default NodePool and N pending demo pods")
    return p


def seed_demo(kube, n_pods: int) -> None:
    from karpenter_tpu.kube.objects import (
        Container, ObjectMeta, OwnerReference, Pod, PodSpec,
    )
    from karpenter_tpu.apis.v1.nodepool import NodePool

    if kube.get_node_pool("default") is None:
        kube.create(NodePool(metadata=ObjectMeta(name="default")))
    for i in range(n_pods):
        name = f"demo-{i}"
        if kube.get_pod("default", name) is None:
            kube.create(Pod(
                metadata=ObjectMeta(name=name, owner_references=[
                    # ReplicaSet-owned so demo drains visibly reschedule
                    OwnerReference(kind="ReplicaSet", name="demo",
                                   uid="uid-demo-rs", controller=True),
                ]),
                spec=PodSpec(containers=[
                    Container(requests={"cpu": 1.0, "memory": 2.0 * 2**30})
                ]),
            ))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(levelname)-7s %(name)s %(message)s",
    )
    log = logging.getLogger("karpenter")

    if args.solver_endpoint:
        os.environ["KARPENTER_SOLVER_ENDPOINT"] = args.solver_endpoint
    if args.solver_shards:
        os.environ["KARPENTER_SOLVER_SHARDS"] = str(args.solver_shards)

    from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
    from karpenter_tpu.kube.client import KubeClient
    from karpenter_tpu.operator.operator import Operator
    from karpenter_tpu.operator.options import FeatureGates, Options

    options = Options(
        batch_idle_duration=args.batch_idle_duration,
        batch_max_duration=args.batch_max_duration,
        preference_policy=args.preference_policy,
        min_values_policy=args.min_values_policy,
        feature_gates=FeatureGates.parse(args.feature_gates),
        metrics_port=args.metrics_port,
        metrics_bind_host=args.metrics_bind_host,
        log_level=args.log_level,
        cluster_name=args.cluster_name,
        enable_profiling=args.enable_profiling,
    )

    if args.api_server:
        # real cluster: the adapter speaks CRs over HTTP with
        # resourceVersion conflict semantics (kube/real.py)
        from karpenter_tpu.kube.real import HTTPTransport, RealKubeClient

        kube = RealKubeClient(HTTPTransport(
            args.api_server,
            token_file=args.api_token_file or None,
            ca_file=args.api_ca_file or None,
        ))
        log.info("connected to API server %s", args.api_server)
    elif args.state_file and os.path.exists(args.state_file):
        kube = KubeClient.load(args.state_file)
        log.info("state loaded from %s", args.state_file)
    else:
        kube = KubeClient()
    cloud = KwokCloudProvider(
        kube, registration_delay=args.registration_delay
    )
    restored = cloud.restore()
    if restored:
        log.info("rehydrated %d instances from the store", restored)

    operator = Operator(
        kube=kube,
        cloud_provider=cloud,
        options=options,
        identity=args.identity,
        leader_election=args.leader_elect,
    )
    if args.demo:
        seed_demo(kube, args.demo)
        log.info("demo: seeded default NodePool + %d pending pods", args.demo)

    stop = {"flag": False}

    def on_signal(signum, frame):
        log.info("signal %d: shutting down", signum)
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)

    server = operator.serve_observability()
    log.info(
        "operator up: cluster=%s provider=%s metrics=%s:%d",
        args.cluster_name, cloud.name(), args.metrics_bind_host, server.port,
    )
    try:
        operator.run(
            stop_after=args.run_seconds if args.run_seconds > 0 else None,
            tick_seconds=args.tick_seconds,
            should_stop=lambda: stop["flag"],
        )
    finally:
        if args.state_file and hasattr(kube, "save"):
            kube.save(args.state_file)
            log.info("state saved to %s", args.state_file)
        if hasattr(kube, "close"):
            kube.close()  # tear down watch-stream readers
    nodes = len(kube.nodes())
    bound = sum(1 for p in kube.pods() if p.spec.node_name)
    log.info("shutdown: %d nodes, %d bound pods", nodes, bound)
    return 0


if __name__ == "__main__":
    sys.exit(main())
