"""Disruption command validation.

Counterpart of pkg/controllers/disruption/validation.go:52-316: a
command is computed against a snapshot, and cluster state moves on
while replacements launch. Before the orchestration queue executes the
candidate deletions it re-verifies, against *current* state:

- every candidate's claim still exists, nothing re-armed
  do-not-disrupt (node or pods), and no candidate was nominated for a
  pod during validation (validation.go:242-246),
- no freshly-arrived pod on a candidate is PDB-blocked,
- per-pool budgets still admit the deletions (candidates' own
  marked-for-deletion state is excluded from the deleting count so the
  command doesn't collide with itself),
- for consolidation commands, the ECONOMICS still hold: each launched
  replacement is priced at its ACTUAL materialized offering (the node
  exists by validation time; not the plan's optimistic minimum), the
  offering must still exist in the current catalog, and the total must
  stay strictly below the candidates' current (re-priced) cost — the
  reference gets this via re-running computeConsolidation's price
  filter after the TTL (validation.go:256-316); here prices are
  re-resolved directly,
- for consolidation commands older than the TTL, the scheduling
  simulation is RE-RUN against current state (validateCommand,
  validation.go:262-310) using the candidates' LIVE pod sets (pods
  that bound after compute time included, since-gone pods excluded —
  the reference rebuilds candidates the same way): every candidate pod
  must still be reschedulable, and because the launched replacements
  already count as existing capacity, NO new node may be needed for
  them — needing one means the cluster changed underneath the
  decision.

Raises ValidationError -> the queue rolls the command back (un-taints
candidates and deletes replacement claims that never took load — the
reference launches replacements only after validation, so execution-
time validation must clean up what early launch created). Transient
infrastructure failures (catalog fetch blips) raise ValidationRetry
instead: the queue keeps the command and re-validates next cycle,
bounded by its retry deadline.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, TYPE_CHECKING

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    DO_NOT_DISRUPT_ANNOTATION,
    INSTANCE_TYPE_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.apis.v1.nodepool import (
    REASON_DRIFTED,
    REASON_INTERRUPTED,
    REASON_UNDERUTILIZED,
)
from karpenter_tpu.utils.pdb import PdbLimits

if TYPE_CHECKING:  # pragma: no cover
    from karpenter_tpu.disruption.engine import Command, DisruptionEngine
    from karpenter_tpu.kube.objects import Pod

# The reference re-validates after this TTL (validation.go consolidationTTL);
# in the tick-driven runtime validation happens at execution time, which is
# at least one queue cycle after computation. Commands validated within the
# TTL skip the (expensive) re-simulation but never the price re-check.
VALIDATION_TTL_SECONDS = 15.0


class ValidationError(Exception):
    """The command is stale; roll it back."""


class ValidationRetry(Exception):
    """Validation could not complete (transient failure); try again."""


class Validator:
    def __init__(self, engine: "DisruptionEngine"):
        self.engine = engine

    def validate_for_execution(self, command: "Command",
                               now: Optional[float] = None) -> None:
        """Raises ValidationError (roll back) / ValidationRetry
        (defer). An invalid verdict is a DECISION about the command's
        candidates — the explain plane records it on each of them
        (`kept:validation-failed`, carrying the validator's own
        message) before the queue rolls the command back."""
        try:
            self._validate_for_execution(command, now)
        except ValidationError as err:
            from karpenter_tpu import explain

            for candidate in command.candidates:
                explain.note_candidate(
                    candidate.state_node.name, explain.KEPT_VALIDATION,
                    reason=str(err), command=command.reason,
                )
            raise

    def _validate_for_execution(self, command: "Command",
                                now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        kube = self.engine.kube
        if command.reason == REASON_INTERRUPTED:
            # forced reclaim: the cloud takes the capacity whether the
            # drain happens or not, so graceful pod-block rules
            # (do-not-disrupt, PDBs, nominations) and disruption
            # budgets never veto — a planned drain strictly dominates
            # the forced one. Only existence is checked: a vanished
            # claim means there is nothing left to drain.
            for candidate in command.candidates:
                claim = candidate.state_node.node_claim
                if claim is None or kube.get_node_claim(
                    claim.metadata.name
                ) is None:
                    raise ValidationError(
                        f"interrupted candidate "
                        f"{candidate.state_node.name} claim vanished"
                    )
            return
        pdb = PdbLimits(kube)
        # Execution-time revalidation applies the GRACEFUL pod-block
        # rules, and the reference runs it for CONSOLIDATION commands
        # only (queue.go validation; validation.go:224-225 hardcodes
        # GracefulDisruptionClass). A drift candidate whose claim
        # carries a TerminationGracePeriod was admitted as EVENTUAL —
        # re-judging it gracefully would invalidate it the moment a
        # do-not-disrupt pod exists, which is exactly the case TGP is
        # for. The gate is PER CANDIDATE (the reference's
        # eventualDisruptionCandidate is evaluated per NodeClaim,
        # types.go): a command mixing TGP and non-TGP candidates keeps
        # graceful re-checks on the non-TGP ones only.
        def _eventual(candidate) -> bool:
            claim = candidate.state_node.node_claim
            return (
                command.reason == REASON_DRIFTED
                and claim is not None
                and claim.spec.termination_grace_period is not None
            )
        # live (current) reschedulable pods per candidate, rebuilt from
        # state the way the reference's validateCandidates re-runs
        # GetCandidates: pods that bound after compute time are counted,
        # since-terminated pods are not
        live_pods: dict[str, list["Pod"]] = {}
        for candidate in command.candidates:
            eventual = _eventual(candidate)
            node = candidate.state_node
            claim = node.node_claim
            if claim is None or kube.get_node_claim(claim.metadata.name) is None:
                raise ValidationError(
                    f"candidate {node.name} claim vanished"
                )
            if node.annotations().get(DO_NOT_DISRUPT_ANNOTATION) == "true":
                raise ValidationError(f"candidate {node.name} re-armed do-not-disrupt")
            live = self.engine.cluster.node_for_name(node.name)
            if live is not None and live.nominated(now):
                # a pod was nominated onto the candidate while the
                # command was in flight (validation.go:242-246)
                raise ValidationError(
                    f"candidate {node.name} was nominated during validation"
                )
            pod_keys = live.pod_keys if live is not None else node.pod_keys
            live_pods[node.name] = []
            for pod_key in pod_keys:
                pod = kube.get_pod(*pod_key.split("/", 1))
                if pod is None or pod.is_terminal() or pod.is_terminating():
                    continue
                # blocking checks BEFORE the daemonset skip, mirroring
                # _build_candidate: a daemonset pod freshly armed with
                # do-not-disrupt (or a PDB dropping to zero) must fail
                # revalidation just like it would fail admission
                if (
                    pod.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION)
                    == "true"
                    and not eventual
                ):
                    raise ValidationError(
                        f"pod {pod_key} on candidate {node.name} is do-not-disrupt"
                    )
                if pdb.can_evict(pod) is not None and not eventual:
                    raise ValidationError(
                        f"pod {pod_key} on candidate {node.name} is PDB-blocked"
                    )
                if pod.owner_kind() == "DaemonSet":
                    continue
                live_pods[node.name].append(pod)
        # budgets against current state, excluding this command's own marks
        needed: dict[str, int] = {}
        for candidate in command.candidates:
            pool = candidate.node_pool.metadata.name
            needed[pool] = needed.get(pool, 0) + 1
        # the same accounting as admission (engine.budget_mapping —
        # uninitialized/terminating excluded from the total, NotReady +
        # deleting consume), with this command's own candidates carved
        # out so it can't collide with its own marks
        candidate_node_names = frozenset(
            c.state_node.name for c in command.candidates
        )
        budgets = self.engine.budget_mapping(
            command.reason, now, exclude_names=candidate_node_names
        )
        for pool_name, count in needed.items():
            if kube.get_node_pool(pool_name) is None:
                raise ValidationError(f"nodepool {pool_name} vanished")
            if budgets.get(pool_name, 0) < count:
                raise ValidationError(f"budget for nodepool {pool_name} closed")
        if command.reason == REASON_UNDERUTILIZED:
            self._validate_economics(command)
            if command.started_at and now - command.started_at >= VALIDATION_TTL_SECONDS:
                self._validate_resimulation(command, live_pods)

    # -- consolidation economics re-check ----------------------------------

    def _fresh_catalog(self, cache: dict, pool_name: str,
                       available_only: bool = False) -> dict:
        """(instance-type, zone, capacity-type) -> current price, from a
        fresh provider fetch. With available_only, offerings absent from
        the result have vanished FOR NEW LAUNCHES (sold out / retired)
        since the command was computed — availability gates
        launchability, never the price of a node that already exists.
        A fetch failure is transient -> ValidationRetry, not rollback."""
        key = (pool_name, available_only)
        if key not in cache:
            try:
                cache[key] = self.engine.offering_price_index(
                    pool_name, available_only=available_only
                )
            except Exception as err:
                raise ValidationRetry(
                    f"catalog re-fetch failed for pool {pool_name}: {err}"
                )
        return cache[key]

    def _replacement_price(self, cache: dict, plan) -> float:
        """Current price of one replacement plan. By validation time the
        plan's claim has materialized into a node with concrete
        instance-type/zone/capacity-type labels — price THAT offering
        (an optimistic min over surviving fallbacks would mask an
        expensive actual launch). The running node's offering may have
        gone unavailable for NEW launches without affecting it, so the
        lookup uses the full catalog; an offering gone entirely keeps
        the plan's computed price (same tolerance the candidate side
        gets). Falls back to the cheapest surviving LAUNCHABLE planned
        offering only while the node's labels are unknown."""
        state_node = self.engine.cluster.node_for_key(plan.claim_name)
        if state_node is not None:
            labels = state_node.labels()
            key = (
                labels.get(INSTANCE_TYPE_LABEL, ""),
                labels.get(TOPOLOGY_ZONE_LABEL, ""),
                labels.get(CAPACITY_TYPE_LABEL, ""),
            )
            if all(key):
                prices = self._fresh_catalog(cache, plan.pool.metadata.name)
                cur = prices.get(key)
                return plan.price if cur is None else cur
        prices = self._fresh_catalog(
            cache, plan.pool.metadata.name, available_only=True
        )
        surviving = []
        for it in plan.instance_types:
            for off in it.offerings:
                if off not in plan.offerings:
                    continue
                cur = prices.get((it.name, off.zone, off.capacity_type))
                if cur is not None:
                    surviving.append(cur)
        if not surviving:
            raise ValidationError(
                "replacement offerings vanished for a planned node"
            )
        return min(surviving)

    def _validate_economics(self, command: "Command") -> None:
        """Replacements at their current (actual-launch) prices must
        stay STRICTLY below the candidates' current price — prices move
        between compute and execute (validation.go:297-310 guards the
        same regression through the instance-type subset check;
        re-pricing directly is exact)."""
        results = command.results
        if results is None or not results.new_node_plans:
            return
        cache: dict = {}
        retired = 0.0
        for c in command.candidates:
            prices = self._fresh_catalog(cache, c.node_pool.metadata.name)
            cur = prices.get((c.instance_type_name, c.zone, c.capacity_type))
            # a candidate whose own offering vanished keeps its computed
            # price: deleting it can only get MORE attractive
            retired += c.price if cur is None else cur
        new_total = sum(
            self._replacement_price(cache, plan)
            for plan in results.new_node_plans
        )
        if new_total >= retired:
            raise ValidationError(
                f"replacement no longer cheaper "
                f"({new_total:.4f}/hr >= {retired:.4f}/hr)"
            )

    def _validate_resimulation(
        self, command: "Command", live_pods: dict[str, list["Pod"]]
    ) -> None:
        """Past the TTL, re-run the scheduling simulation against
        current state (validateCommand, validation.go:262-310) with the
        candidates' LIVE pod sets, solving those pods ALONE (pending
        pods excluded — an unrelated pending pod forcing a new node,
        onto which the packer opportunistically tops off a candidate
        pod, must not read as the command going stale). The command's
        replacements are already live capacity by the time the queue
        validates, so every candidate pod should land on them (or other
        existing room): a NEW node needed means the cluster changed
        underneath the decision."""
        fresh = [
            dataclasses.replace(
                c, reschedulable_pods=live_pods.get(c.state_node.name, [])
            )
            for c in command.candidates
        ]
        # unrelated capacity still materializing (routine during any
        # provisioning) aborts the simulation via the uninitialized-node
        # guard — a TRANSIENT condition, so defer rather than roll back
        # a still-valid command and destroy its replacements
        if self.engine.has_uninitialized_capacity(
            exclude_names={c.state_node.name for c in fresh}
        ):
            raise ValidationRetry(
                "cluster has uninitialized capacity; deferring re-simulation"
            )
        results, all_ok = self.engine.simulate_scheduling(
            fresh, include_pending=False
        )
        if not all_ok:
            raise ValidationError(
                "re-simulation: candidate pods no longer reschedulable"
            )
        if results.new_node_plans:
            raise ValidationError(
                f"re-simulation produced new results "
                f"({len(results.new_node_plans)} new nodes needed for "
                f"candidate pods)"
            )
