"""Disruption command validation.

Counterpart of pkg/controllers/disruption/validation.go:52-280: a
command is computed against a snapshot, and cluster state moves on
while replacements launch. Before the orchestration queue executes the
candidate deletions it re-verifies, against *current* state:

- every candidate's claim still exists and nothing re-armed
  do-not-disrupt (node or pods),
- no freshly-arrived pod on a candidate is PDB-blocked,
- per-pool budgets still admit the deletions (candidates' own
  marked-for-deletion state is excluded from the deleting count so the
  command doesn't collide with itself).

Raises ValidationError -> the queue rolls the command back.
"""

from __future__ import annotations

import time
from typing import Optional, TYPE_CHECKING

from karpenter_tpu.apis.v1.labels import DO_NOT_DISRUPT_ANNOTATION
from karpenter_tpu.utils.pdb import PdbLimits

if TYPE_CHECKING:  # pragma: no cover
    from karpenter_tpu.disruption.engine import Command, DisruptionEngine

# The reference re-validates after this TTL (validation.go consolidationTTL);
# in the tick-driven runtime validation happens at execution time, which is
# at least one queue cycle after computation.
VALIDATION_TTL_SECONDS = 15.0


class ValidationError(Exception):
    pass


class Validator:
    def __init__(self, engine: "DisruptionEngine"):
        self.engine = engine

    def validate_for_execution(self, command: "Command",
                               now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        kube = self.engine.kube
        pdb = PdbLimits(kube)
        candidate_names = {
            c.state_node.node_claim.metadata.name
            for c in command.candidates
            if c.state_node.node_claim is not None
        }
        for candidate in command.candidates:
            node = candidate.state_node
            claim = node.node_claim
            if claim is None or kube.get_node_claim(claim.metadata.name) is None:
                raise ValidationError(
                    f"candidate {node.name} claim vanished"
                )
            if node.annotations().get(DO_NOT_DISRUPT_ANNOTATION) == "true":
                raise ValidationError(f"candidate {node.name} re-armed do-not-disrupt")
            live = self.engine.cluster.node_for_name(node.name)
            pod_keys = live.pod_keys if live is not None else node.pod_keys
            for pod_key in pod_keys:
                pod = kube.get_pod(*pod_key.split("/", 1))
                if pod is None or pod.is_terminal() or pod.is_terminating():
                    continue
                if pod.owner_kind() == "DaemonSet":
                    continue
                if pod.metadata.annotations.get(DO_NOT_DISRUPT_ANNOTATION) == "true":
                    raise ValidationError(
                        f"pod {pod_key} on candidate {node.name} is do-not-disrupt"
                    )
                if pdb.can_evict(pod) is not None:
                    raise ValidationError(
                        f"pod {pod_key} on candidate {node.name} is PDB-blocked"
                    )
        # budgets against current state, excluding this command's own marks
        needed: dict[str, int] = {}
        for candidate in command.candidates:
            pool = candidate.node_pool.metadata.name
            needed[pool] = needed.get(pool, 0) + 1
        for pool_name, count in needed.items():
            pool = kube.get_node_pool(pool_name)
            if pool is None:
                raise ValidationError(f"nodepool {pool_name} vanished")
            total = self.engine.cluster.nodepool_node_count(pool_name)
            allowed = pool.must_get_allowed_disruptions(now, total, command.reason)
            deleting_others = sum(
                1
                for n in self.engine.cluster.nodes()
                if n.nodepool_name() == pool_name
                and n.deleting()
                and not (
                    n.node_claim is not None
                    and n.node_claim.metadata.name in candidate_names
                )
            )
            if allowed - deleting_others < count:
                raise ValidationError(f"budget for nodepool {pool_name} closed")
