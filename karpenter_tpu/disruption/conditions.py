"""NodeClaim disruption-condition controllers.

Counterpart of pkg/controllers/nodeclaim/disruption (1,323 LoC) and
nodeclaim/expiration: maintain the conditions the disruption engine
consumes —

- Consolidatable: consolidateAfter elapsed since the last pod event
  (consolidation.go:38); cleared while pods churn.
- Drifted: provider IsDrifted, or the NodePool template hash changed
  (static drift), or the claim no longer satisfies the pool's
  requirements (dynamic drift) (drift.go:50-185).
- Expiration: claims older than expireAfter are force-deleted
  (expiration/controller.go:57-100).
"""

from __future__ import annotations

import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    NODEPOOL_HASH_ANNOTATION,
    NODEPOOL_HASH_VERSION_ANNOTATION,
    NODEPOOL_HASH_VERSION,
    NODEPOOL_LABEL,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_CONSOLIDATABLE,
    COND_DRIFTED,
    NodeClaim,
)
from karpenter_tpu.apis.v1.nodepool import (
    CONSOLIDATION_WHEN_EMPTY,
    NodePool,
)
from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.scheduling.requirement import Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.utils.duration import parse_duration


class DisruptionConditionsController:
    def __init__(self, kube: KubeClient, cluster: Cluster, cloud: CloudProvider):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud

    def reconcile(self, claim: NodeClaim, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        pool = self.kube.get_node_pool(claim.metadata.labels.get(NODEPOOL_LABEL, ""))
        if pool is None:
            return
        self._consolidatable(claim, pool, now)
        self._drifted(claim, pool, now)

    def reconcile_all(self, now: Optional[float] = None) -> None:
        for claim in list(self.kube.node_claims()):
            self.reconcile(claim, now=now)

    # -- Consolidatable (nodeclaim/disruption/consolidation.go:38) -------------

    def _consolidatable(self, claim: NodeClaim, pool: NodePool, now: float) -> None:
        consolidate_after = parse_duration(pool.spec.disruption.consolidate_after)
        if consolidate_after is None:  # "Never"
            claim.status_conditions.clear(COND_CONSOLIDATABLE)
            return
        last_event = claim.status.last_pod_event_time or claim.metadata.creation_timestamp
        if now - last_event >= consolidate_after:
            claim.status_conditions.set_true(COND_CONSOLIDATABLE, now=now)
        else:
            claim.status_conditions.clear(COND_CONSOLIDATABLE)

    # -- Drifted (nodeclaim/disruption/drift.go:50-185) ------------------------

    def _drifted(self, claim: NodeClaim, pool: NodePool, now: float) -> None:
        if not claim.status_conditions.is_true("Launched"):
            return
        reason = self._drift_reason(claim, pool)
        if reason:
            claim.status_conditions.set_true(COND_DRIFTED, reason=reason, now=now)
        else:
            claim.status_conditions.clear(COND_DRIFTED)

    def _drift_reason(self, claim: NodeClaim, pool: NodePool) -> str:
        # provider-side drift (image/nodeclass changes)
        provider_reason = self.cloud.is_drifted(claim)
        if provider_reason:
            return provider_reason
        # static drift: template hash comparison at matching hash version
        claim_version = claim.metadata.annotations.get(NODEPOOL_HASH_VERSION_ANNOTATION)
        claim_hash = claim.metadata.annotations.get(NODEPOOL_HASH_ANNOTATION)
        if claim_version == NODEPOOL_HASH_VERSION and claim_hash:
            if claim_hash != pool.hash():
                return "NodePoolDrifted"
        # dynamic drift: claim labels must still satisfy pool requirements
        from karpenter_tpu.solver.encode import pool_template_requirements

        pool_reqs = pool_template_requirements(pool)
        claim_reqs = Requirements.from_labels(claim.metadata.labels)
        if claim_reqs.intersects(pool_reqs) is not None:
            return "RequirementsDrifted"
        return ""


class ExpirationController:
    """Force-deletes claims past expireAfter
    (nodeclaim/expiration/controller.go:57-100)."""

    def __init__(self, kube: KubeClient):
        self.kube = kube

    def reconcile_all(self, now: Optional[float] = None) -> list[NodeClaim]:
        now = time.time() if now is None else now
        expired = []
        for claim in list(self.kube.node_claims()):
            lifetime = parse_duration(claim.spec.expire_after)
            if lifetime is None:
                continue
            if now - claim.metadata.creation_timestamp >= lifetime:
                if claim.metadata.deletion_timestamp is None:
                    self.kube.delete(claim, now=now)
                    expired.append(claim)
        return expired


class PodEventsController:
    """Stamps status.last_pod_event_time on bind/terminal/terminating
    (nodeclaim/podevents/controller.go:63-110, 5s dedupe)."""

    DEDUPE_SECONDS = 5.0

    def __init__(self, kube: KubeClient, cluster: Cluster):
        self.kube = kube
        self.cluster = cluster

    def reconcile_all(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        claims_by_node = {
            c.status.node_name: c for c in self.kube.node_claims() if c.status.node_name
        }
        touched: set[str] = set()
        for pod in self.kube.pods():
            if not pod.spec.node_name:
                continue
            claim = claims_by_node.get(pod.spec.node_name)
            if claim is None or claim.metadata.name in touched:
                continue
            state = self.cluster.node_for_name(pod.spec.node_name)
            if state is None:
                continue
            last = claim.status.last_pod_event_time or 0.0
            times = self.cluster.pod_times(pod.key)
            event_time = max(times.bound, times.first_seen)
            if pod.is_terminal() or pod.is_terminating():
                event_time = now
            if event_time and event_time - last >= self.DEDUPE_SECONDS:
                claim.status.last_pod_event_time = event_time
                touched.add(claim.metadata.name)
