"""NodeClaim disruption-condition controllers.

Counterpart of pkg/controllers/nodeclaim/disruption (1,323 LoC) and
nodeclaim/expiration: maintain the conditions the disruption engine
consumes —

- Consolidatable: consolidateAfter elapsed since the last pod event
  (consolidation.go:38); cleared while pods churn.
- Drifted: provider IsDrifted, or the NodePool template hash changed
  (static drift), or the claim no longer satisfies the pool's
  requirements (dynamic drift) (drift.go:50-185).
- Expiration: claims older than expireAfter are force-deleted
  (expiration/controller.go:57-100).
"""

from __future__ import annotations

import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    NODEPOOL_HASH_ANNOTATION,
    NODEPOOL_HASH_VERSION_ANNOTATION,
    NODEPOOL_HASH_VERSION,
    NODEPOOL_LABEL,
)
from karpenter_tpu.apis.v1.nodeclaim import (
    COND_CONSOLIDATABLE,
    COND_DRIFTED,
    NodeClaim,
)
from karpenter_tpu.apis.v1.nodepool import (
    CONSOLIDATION_WHEN_EMPTY,
    NodePool,
)
from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.scheduling.requirement import Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.utils.duration import parse_duration


class DisruptionConditionsController:
    # provider-side drift (image/nodeclass rollouts) leaves no event in
    # our objects, so a periodic full sweep covers it — the analogue of
    # the reference controller's requeue interval
    DRIFT_SWEEP_SECONDS = 60.0

    def __init__(self, kube: KubeClient, cluster: Cluster, cloud: CloudProvider):
        import heapq as _heapq

        from karpenter_tpu.kube.dirty import DirtyTracker

        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud
        self.dirty = DirtyTracker(kube).watch("NodeClaim", "NodePool")
        self._heapq = _heapq
        # consolidatable flips by TIME, not by event: [(flip_time, key)]
        # with a scheduled-time guard so repeated reconciles of a claim
        # can't grow the heap unboundedly within one window
        self._recheck: list[tuple[float, str]] = []
        self._recheck_at: dict[str, float] = {}
        self._last_sweep = 0.0

    def reconcile(self, claim: NodeClaim, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        pool = self.kube.get_node_pool(claim.metadata.labels.get(NODEPOOL_LABEL, ""))
        if pool is None:
            return
        self._consolidatable(claim, pool, now)
        self._drifted(claim, pool, now)

    def reconcile_all(self, now: Optional[float] = None) -> None:
        for claim in list(self.kube.node_claims()):
            self.reconcile(claim, now=now)

    def reconcile_dirty(self, now: Optional[float] = None) -> None:
        """O(changes) tick: dirty claims, claims whose consolidatable
        window just elapsed, every claim of a pool whose spec changed,
        and a periodic full sweep for provider-side drift."""
        now = time.time() if now is None else now
        if now - self._last_sweep >= self.DRIFT_SWEEP_SECONDS:
            self._last_sweep = now
            self.dirty.drain("NodeClaim")
            self.dirty.drain("NodePool")
            self.reconcile_all(now=now)
            return
        keys = self.dirty.drain("NodeClaim")
        for pool_key in self.dirty.drain("NodePool"):
            pool = self.kube.get_node_pool(pool_key)
            name = pool.metadata.name if pool is not None else pool_key
            keys.update(
                c.key for c in self.kube.node_claims()
                if c.metadata.labels.get(NODEPOOL_LABEL) == name
            )
        while self._recheck and self._recheck[0][0] <= now:
            due, key = self._heapq.heappop(self._recheck)
            if self._recheck_at.get(key) == due:
                del self._recheck_at[key]
            keys.add(key)
        for key in keys:
            claim = self.kube.get_node_claim(key)
            if claim is not None:
                self.reconcile(claim, now=now)

    # -- Consolidatable (nodeclaim/disruption/consolidation.go:38) -------------

    def _consolidatable(self, claim: NodeClaim, pool: NodePool, now: float) -> None:
        consolidate_after = parse_duration(pool.spec.disruption.consolidate_after)
        if consolidate_after is None:  # "Never"
            claim.status_conditions.clear(COND_CONSOLIDATABLE)
            return
        last_event = claim.status.last_pod_event_time or claim.metadata.creation_timestamp
        if now - last_event >= consolidate_after:
            claim.status_conditions.set_true(COND_CONSOLIDATABLE, now=now)
        else:
            claim.status_conditions.clear(COND_CONSOLIDATABLE)
            # not yet: wake up exactly when the window elapses (skip
            # the push when that exact wake-up is already scheduled)
            flip_at = last_event + consolidate_after
            if self._recheck_at.get(claim.key) != flip_at:
                self._recheck_at[claim.key] = flip_at
                self._heapq.heappush(self._recheck, (flip_at, claim.key))

    # -- Drifted (nodeclaim/disruption/drift.go:50-185) ------------------------

    def _drifted(self, claim: NodeClaim, pool: NodePool, now: float) -> None:
        if not claim.status_conditions.is_true("Launched"):
            return
        reason = self._drift_reason(claim, pool)
        if reason:
            claim.status_conditions.set_true(COND_DRIFTED, reason=reason, now=now)
        else:
            claim.status_conditions.clear(COND_DRIFTED)

    def _drift_reason(self, claim: NodeClaim, pool: NodePool) -> str:
        # provider-side drift (image/nodeclass changes)
        provider_reason = self.cloud.is_drifted(claim)
        if provider_reason:
            return provider_reason
        # static drift: template hash comparison at matching hash version
        claim_version = claim.metadata.annotations.get(NODEPOOL_HASH_VERSION_ANNOTATION)
        claim_hash = claim.metadata.annotations.get(NODEPOOL_HASH_ANNOTATION)
        if claim_version == NODEPOOL_HASH_VERSION and claim_hash:
            if claim_hash != pool.hash():
                return "NodePoolDrifted"
        # dynamic drift: claim labels must still satisfy pool requirements
        from karpenter_tpu.solver.encode import pool_template_requirements

        pool_reqs = pool_template_requirements(pool)
        claim_reqs = Requirements.from_labels(claim.metadata.labels)
        if claim_reqs.intersects(pool_reqs) is not None:
            return "RequirementsDrifted"
        return ""


class ExpirationController:
    """Force-deletes claims past expireAfter
    (nodeclaim/expiration/controller.go:57-100)."""

    def __init__(self, kube: KubeClient):
        import heapq as _heapq

        from karpenter_tpu.kube.dirty import DirtyTracker

        self.kube = kube
        self._heapq = _heapq
        self.dirty = DirtyTracker(kube).watch("NodeClaim")
        self._due: list[tuple[float, str]] = []
        self._due_at: dict[str, float] = {}

    def _expire_if_due(self, claim: NodeClaim, now: float,
                       expired: list[NodeClaim]) -> None:
        lifetime = parse_duration(claim.spec.expire_after)
        if lifetime is None:
            return
        expire_at = claim.metadata.creation_timestamp + lifetime
        if now >= expire_at:
            if claim.metadata.deletion_timestamp is None:
                self.kube.delete(claim, now=now)
                expired.append(claim)
        elif self._due_at.get(claim.key) != expire_at:
            # deadline is fixed at creation; every later touch of the
            # claim would otherwise push a duplicate heap entry that
            # only drains at expiry
            self._due_at[claim.key] = expire_at
            self._heapq.heappush(self._due, (expire_at, claim.key))

    def reconcile_all(self, now: Optional[float] = None) -> list[NodeClaim]:
        now = time.time() if now is None else now
        expired: list[NodeClaim] = []
        for claim in list(self.kube.node_claims()):
            lifetime = parse_duration(claim.spec.expire_after)
            if lifetime is None:
                continue
            if now - claim.metadata.creation_timestamp >= lifetime:
                if claim.metadata.deletion_timestamp is None:
                    self.kube.delete(claim, now=now)
                    expired.append(claim)
        return expired

    def reconcile_dirty(self, now: Optional[float] = None) -> list[NodeClaim]:
        """O(changes): expiry deadlines live in a heap keyed at claim
        creation; a tick only pops what's due plus new/changed claims."""
        now = time.time() if now is None else now
        expired: list[NodeClaim] = []
        for key in self.dirty.drain("NodeClaim"):
            claim = self.kube.get_node_claim(key)
            if claim is not None:
                self._expire_if_due(claim, now, expired)
        while self._due and self._due[0][0] <= now:
            due, key = self._heapq.heappop(self._due)
            if self._due_at.get(key) == due:
                del self._due_at[key]
            claim = self.kube.get_node_claim(key)
            if claim is not None:
                self._expire_if_due(claim, now, expired)
        return expired


class PodEventsController:
    """Stamps status.last_pod_event_time on bind/terminal/terminating
    (nodeclaim/podevents/controller.go:63-110, 5s dedupe)."""

    DEDUPE_SECONDS = 5.0

    def __init__(self, kube: KubeClient, cluster: Cluster):
        from karpenter_tpu.kube.dirty import DirtyTracker

        self.kube = kube
        self.cluster = cluster
        self.dirty = DirtyTracker(kube).watch("Pod")

    def reconcile_all(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        claims_by_node = {
            c.status.node_name: c for c in self.kube.node_claims() if c.status.node_name
        }
        touched: set[str] = set()
        for pod in self.kube.pods():
            self._stamp(pod, claims_by_node.get(pod.spec.node_name), touched, now)

    def reconcile_dirty(self, now: Optional[float] = None) -> None:
        """O(changed pods): a pod event is the ONLY thing that can move
        a claim's lastPodEventTime (podevents/controller.go watches
        pods, nothing else)."""
        now = time.time() if now is None else now
        keys = self.dirty.drain("Pod")
        if not keys:
            return
        touched: set[str] = set()
        for key in keys:
            pod = self.kube.get("Pod", key)
            if pod is None or not pod.spec.node_name:
                continue
            state = self.cluster.node_for_name(pod.spec.node_name)
            claim = state.node_claim if state is not None else None
            self._stamp(pod, claim, touched, now)

    def _stamp(self, pod, claim, touched: set[str], now: float) -> None:
        if claim is None or not pod.spec.node_name:
            return
        if claim.metadata.name in touched:
            return
        state = self.cluster.node_for_name(pod.spec.node_name)
        if state is None:
            return
        last = claim.status.last_pod_event_time or 0.0
        times = self.cluster.pod_times(pod.key)
        event_time = max(times.bound, times.first_seen)
        if pod.is_terminal() or pod.is_terminating():
            event_time = now
        if event_time and event_time - last >= self.DEDUPE_SECONDS:
            claim.status.last_pod_event_time = event_time
            touched.add(claim.metadata.name)
            # announce the in-place stamp so the conditions controller
            # re-evaluates Consolidatable for this claim
            self.kube.touch(claim)
