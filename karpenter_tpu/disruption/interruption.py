"""Spot interruption controller: notice -> replace -> drain.

KubePACS-style interruption handling (PAPERS.md): a cloud interruption
notice is an EVENTUAL eviction — the capacity disappears whether or not
the controller acts. Acting early turns a forced outage into a planned
replacement:

- the node is tainted the moment the notice lands (the orchestration
  queue applies the standard disrupted NoSchedule taint, so no new pod
  boards doomed capacity) and its claim gets the `Interrupted`
  condition (consolidation skips it; kubectl sees it);
- replacement capacity is provisioned BEFORE draining starts
  (drain-after-replace — never capacity-gap-first): the displaced pods
  are re-solved against the cluster minus the interrupted node, the
  resulting claims are created immediately, and the candidate's drain
  waits until every replacement reports Initialized;
- the displaced pods route through the normal provisioning tick: the
  command's scheduling results ride the operator's pending-binding
  queue, so evicted pods land on the pre-provisioned claims instead of
  triggering a fresh solve (and a duplicate launch).

The OrchestrationQueue's replace-then-delete machinery is reused
wholesale, so interruption replacement inherits its wait-for-
Initialized gating, rollback, and retry semantics. Unlike graceful
disruption, interruption bypasses do-not-disrupt/PDB blocks and
disruption budgets at validation time (disruption/validation.py): the
reclaim happens regardless, and a planned drain strictly dominates the
forced one.

Notices come from the provider's `poll_interruptions()` hook (kwok /
fake): one `cloud_interrupt` fault-injector check per live spot
instance in sorted provider-id order, so a seeded
`spot_interruption@cloud_interrupt:*=rate` schedule is replay-identical
(solver/faults.py).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from karpenter_tpu.apis.v1.labels import (
    CAPACITY_TYPE_LABEL,
    INSTANCE_TYPE_LABEL,
    TOPOLOGY_ZONE_LABEL,
)
from karpenter_tpu.apis.v1.nodeclaim import COND_INTERRUPTED
from karpenter_tpu.apis.v1.nodepool import REASON_INTERRUPTED
from karpenter_tpu.disruption.engine import (
    Candidate,
    Command,
    DisruptionEngine,
    pod_disruption_cost,
)
from karpenter_tpu.metrics.store import INTERRUPTION_COMMANDS
from karpenter_tpu.state.cluster import StateNode

log = logging.getLogger("karpenter.interruption")

# how long a displaced pod may stay un-landed before new waves stop
# waiting for it: on a real substrate the workload owner may simply
# never recreate an evicted pod, and that must not wedge interruption
# handling forever
DISPLACED_LANDING_TTL_SECONDS = 15 * 60.0


class InterruptionController:
    """Polls the provider for interruption notices and starts one
    drain-after-replace command per noticed node."""

    def __init__(self, kube, cluster, cloud, engine: DisruptionEngine,
                 recorder=None):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud
        self.engine = engine
        self.queue = engine.queue
        self.recorder = recorder
        # provider ids whose command has been started (pruned once the
        # provider's notice clears — i.e. the instance is gone)
        self._handled: set[str] = set()
        # pod key -> (origin node name, landing deadline) for pods
        # displaced by started commands: a new wave must not be
        # simulated while any of these is still in flight (see
        # reconcile), but a pod that never comes back (real substrate:
        # the workload owner may not recreate it) must not wedge
        # interruption handling forever — the deadline bounds the wait
        self._displaced: dict[str, tuple[str, float]] = {}
        # commands this controller started that are (or were) in the
        # orchestration queue, with the provider ids they satisfy —
        # settled each reconcile so a rollback re-arms its notices
        self._inflight: list[tuple[Command, list[str]]] = []

    # -- one reconcile ---------------------------------------------------------

    def reconcile(self, now: Optional[float] = None) -> list[Command]:
        """Advance the provider's interruption checks, then start ONE
        replacement command covering every un-handled notice whose node
        is replaceable right now (the rest retry next tick). Returns
        the commands started this call so the operator can route their
        placements through the binding queue.

        Notices are batched into a single command per reconcile, and no
        new command starts while a previous interruption command is
        still in flight OR a previous wave's displaced pods have not
        landed yet: two waves simulated against state where the other's
        displaced pods have not rebound yet would each see the same
        free capacity and jointly overcommit it (the sim cannot know
        capacity a sibling wave's pending rebinds already spoke for).
        One wave at a time keeps every sim truthful; a storm converges
        one replacement wave per landing."""
        now = time.time() if now is None else now
        poll = getattr(self.cloud, "poll_interruptions", None)
        if poll is None:
            return []
        poll(now)
        self._settle_inflight(now)
        notices = set(getattr(self.cloud, "interrupted", ()) or ())
        self._handled &= notices  # instance gone -> notice consumed
        pending = [p for p in sorted(notices) if p not in self._handled]
        if not pending:
            return []
        # surface every fresh notice on its claim immediately (even
        # while the command must wait): consolidation skips noticed
        # nodes from this moment, and kubectl sees the condition
        wave: list[tuple[str, StateNode]] = []
        for pid in pending:
            node = self._notice(pid, now)
            if node is not None:
                wave.append((pid, node))
        if not wave:
            return []
        if any(c.reason == REASON_INTERRUPTED for c in self.queue.active):
            return []  # previous wave still draining; see docstring
        if self._landing_in_flight(now):
            return []  # previous wave's pods still rebinding
        candidates: list[Candidate] = []
        pids: list[str] = []
        for pid, node in wave:
            pool = self.kube.get_node_pool(node.nodepool_name())
            if pool is None:
                self._handled.add(pid)
                continue
            candidates.append(self._candidate(node, pool))
            pids.append(pid)
        if not candidates:
            return []
        results = None
        if any(c.reschedulable_pods for c in candidates):
            # pre-provision replacement capacity, co-solved with the
            # pending pods exactly like a consolidation command (the
            # results ride the binding queue either way, and a split
            # solve would let the provisioner's own tick race this
            # wave onto the same free capacity); a sim abort (capacity
            # still materializing — routine mid-storm) retries next
            # tick with the notices already surfaced
            results, ok = self.engine.simulate_scheduling(candidates)
            if not ok and not self.engine.has_uninitialized_capacity():
                # an unrelated unschedulable pending pod must not wedge
                # the forced reclaim forever: solve the wave's own pods
                # alone
                results, ok = self.engine.simulate_scheduling(
                    candidates, include_pending=False
                )
            if not ok:
                log.info(
                    "interruption replacement wave (%d nodes) deferred "
                    "(cluster still materializing capacity)",
                    len(candidates),
                )
                return []
        command = Command(
            reason=REASON_INTERRUPTED, candidates=candidates,
            results=results,
        )
        self.queue.start_command(command, now)
        if command not in self.queue.active:
            # replacement creation failed and the queue rolled the
            # command back (e.g. nodepool limits): leave the notices
            # un-handled so the wave retries next tick
            log.warning(
                "interruption replacement wave (%d nodes) rolled back "
                "at start; retrying next tick", len(candidates),
            )
            return []
        self._inflight.append((command, pids))
        from karpenter_tpu import explain

        for candidate in candidates:
            # terminal verdict: the cloud is reclaiming this node; the
            # wave's drain-after-replace owns it from here (overwrites
            # any weak keep a deferred earlier simulation recorded)
            explain.note_candidate(
                candidate.state_node.name, explain.VERDICT_INTERRUPTED,
                replacements=command.replacement_count,
            )
            INTERRUPTION_COMMANDS.inc(
                {"nodepool": candidate.node_pool.metadata.name}
            )
            for pod in candidate.reschedulable_pods:
                self._displaced[pod.key] = (
                    candidate.state_node.name,
                    now + DISPLACED_LANDING_TTL_SECONDS,
                )
        for pid in pids:
            self._handled.add(pid)
        log.info(
            "interruption: replacing %d node(s) (%d pods, %d replacement "
            "nodes) before drain", len(candidates),
            sum(len(c.reschedulable_pods) for c in candidates),
            command.replacement_count,
        )
        return [command]

    def _settle_inflight(self, now: float) -> None:
        """Resolve commands that have left the orchestration queue: a
        drained command's candidates are deleting (success — the
        notices stay handled until the instances vanish), a ROLLED BACK
        command's candidates are alive and untainted — its notices are
        re-armed so the wave retries, and its displaced-pod tracking is
        dropped (nothing was evicted)."""
        still: list[tuple[Command, list[str]]] = []
        for command, pids in self._inflight:
            if command in self.queue.active:
                still.append((command, pids))
                continue
            for candidate, pid in zip(command.candidates, pids):
                claim = candidate.state_node.node_claim
                live = (
                    self.kube.get_node_claim(claim.metadata.name)
                    if claim is not None else None
                )
                if live is not None and live.metadata.deletion_timestamp is None:
                    # rollback: the reclaim is still coming — retry
                    self._handled.discard(pid)
                    for pod in candidate.reschedulable_pods:
                        self._displaced.pop(pod.key, None)
        self._inflight = still

    def _landing_in_flight(self, now: float) -> bool:
        """True while a previous wave's displaced pods have not landed
        yet. Entries prune when the pod is gone/terminal, bound to a
        node other than its origin, or past its landing deadline."""
        still: dict[str, tuple[str, float]] = {}
        for key, (origin, deadline) in self._displaced.items():
            pod = self.kube.get_pod(*key.split("/", 1))
            if pod is None or pod.is_terminal():
                continue
            if pod.spec.node_name and pod.spec.node_name != origin:
                continue  # landed on its replacement capacity
            if now >= deadline:
                log.warning(
                    "displaced pod %s never landed within %ds; no "
                    "longer deferring interruption waves on it",
                    key, int(DISPLACED_LANDING_TTL_SECONDS),
                )
                continue
            still[key] = (origin, deadline)
        self._displaced = still
        return bool(still)

    def _notice(self, pid: str, now: float) -> Optional[StateNode]:
        """Stamp the Interrupted condition for one notice; returns the
        node when it is actionable this tick (registered, not already
        draining), else None (handled or retried later)."""
        node = self._node_for_pid(pid)
        if node is None:
            return None  # instance not registered yet; retry next tick
        claim = node.node_claim
        if claim is None or claim.metadata.deletion_timestamp is not None:
            self._handled.add(pid)
            return None
        if not claim.status_conditions.is_true(COND_INTERRUPTED):
            claim.status_conditions.set_true(
                COND_INTERRUPTED, reason="SpotInterruption", now=now,
            )
            self.kube.touch(claim)
            self._record(node, now)
        if node.deleting():
            # already being drained by another command (or its own
            # deletion): that command satisfies the notice
            self._handled.add(pid)
            return None
        return node

    # -- helpers ---------------------------------------------------------------

    def _node_for_pid(self, pid: str) -> Optional[StateNode]:
        for node in self.cluster.nodes():
            if node.provider_id == pid and node.node is not None:
                return node
        return None

    def _candidate(self, node: StateNode, pool) -> Candidate:
        """Candidate for a forced reclaim: every live non-daemon pod is
        reschedulable — do-not-disrupt and PDBs do not veto (the cloud
        evicts regardless; validation applies the same eventual
        rules)."""
        pods = []
        for pod_key in node.pod_keys:
            pod = self.kube.get_pod(*pod_key.split("/", 1))
            if pod is None or pod.is_terminal() or pod.is_terminating():
                continue
            if pod.owner_kind() == "DaemonSet":
                continue
            pods.append(pod)
        labels = node.labels()
        return Candidate(
            state_node=node,
            node_pool=pool,
            reschedulable_pods=pods,
            instance_type_name=labels.get(INSTANCE_TYPE_LABEL, ""),
            capacity_type=labels.get(CAPACITY_TYPE_LABEL, ""),
            zone=labels.get(TOPOLOGY_ZONE_LABEL, ""),
            price=0.0,  # interruption never price-compares
            disruption_cost=sum(pod_disruption_cost(p) for p in pods),
        )

    def _record(self, node: StateNode, now: float) -> None:
        if self.recorder is None:
            return
        from karpenter_tpu.events.recorder import Event

        if node.node is not None:
            self.recorder.publish(Event(
                kind="Node", name=node.node.metadata.name, type="Warning",
                reason="SpotInterrupted",
                message="Cloud signaled a spot interruption notice; "
                        "replacing before drain",
            ), now=now)
